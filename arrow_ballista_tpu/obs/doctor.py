"""The query doctor: rule-based bottleneck diagnosis with evidence.

Takes what the observability stack already *records* — the job detail
(stage states, synthetic skew/timing metrics), the per-stage profile,
the critical-path breakdown and the journal slice — and *interprets*
them into structured findings an operator can act on without
hand-deriving where the wall-clock went.  Every finding carries
``evidence`` coordinates pointing at real stage ids and metric values,
so it can be re-verified against ``/api/jobs/{id}/profile`` directly.

Finding shape::

    {"code": "skewed_stage", "severity": "warn" | "info",
     "stage_id": 3,                      # absent for job-level findings
     "summary": "...",                   # one line
     "evidence": {...},                  # metric coordinates
     "suggestion": "..."}                # what to try next

Thresholds are module constants so tests (and adventurous operators)
can pin them.  The doctor never raises: missing inputs simply produce
fewer findings.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .critical_path import compute_critical_path
from .export import TASK_RUNTIME_OP, job_profile

# ------------------------------------------------------------ thresholds
# skew-dominated stage: runtime max/median at least this, and the
# straggler at least this much absolute wall beyond the median
SKEW_COEFFICIENT = 2.0
SKEW_MIN_EXCESS_MS = 50.0
# fetch-bound stage: shuffle-fetch wait at least this fraction of the
# stage's total task time (and a floor so trivial stages stay quiet)
FETCH_FRACTION = 0.35
FETCH_MIN_MS = 20.0
# compile-dominated TPU stage
COMPILE_MIN_MS = 50.0
# admission-queued job: queue wait at least this fraction of wall-clock
ADMISSION_FRACTION = 0.2
ADMISSION_MIN_MS = 200.0
# barrier-dominated job: barrier wait at least this fraction of wall
BARRIER_FRACTION = 0.25
BARRIER_MIN_MS = 50.0
# underprovisioned cluster: scheduling delay (tasks runnable, no slot)
# at least this much of wall-clock while work queued at admission and
# the cluster below its executor ceiling
UNDERPROVISIONED_FRACTION = 0.2
UNDERPROVISIONED_MIN_MS = 200.0
# locality-miss stage: at least this many tasks placed off their
# preferred host, and more misses than hits
LOCALITY_MIN_MISSES = 2

_SEVERITY_ORDER = {"warn": 0, "info": 1}


def _finding(code, severity, summary, suggestion, stage_id=None, **evidence):
    out = {
        "code": code,
        "severity": severity,
        "summary": summary,
        "evidence": evidence,
        "suggestion": suggestion,
    }
    if stage_id is not None:
        out["stage_id"] = stage_id
    return out


def _rule_skewed_stages(detail, profile, out: List[dict]) -> None:
    metrics_by_stage = {
        int(r["stage_id"]): (r.get("metrics") or {})
        for r in detail.get("stages", [])
    }
    for row in profile.get("stages", []):
        skew = (row.get("skew") or {}).get("runtime_ms")
        if not skew:
            continue
        coef = skew.get("max_over_median", 0.0)
        excess = skew.get("max", 0) - skew.get("p50", 0)
        if coef < SKEW_COEFFICIENT or excess < SKEW_MIN_EXCESS_MS:
            continue
        sid = row["stage_id"]
        ev = {
            "runtime_ms_p50": skew.get("p50", 0),
            "runtime_ms_p99": skew.get("p99", 0),
            "runtime_ms_max": skew.get("max", 0),
            "max_over_median": coef,
            "partitions": (row.get("skew") or {}).get("partitions", 0),
        }
        runtimes = metrics_by_stage.get(sid, {}).get(TASK_RUNTIME_OP)
        if runtimes:
            slowest = max(runtimes, key=lambda p: runtimes[p])
            ev["slowest_partition"] = int(slowest)
        out.append(
            _finding(
                "skewed_stage",
                "warn",
                f"stage {sid} is skew-dominated: slowest task "
                f"{skew.get('max', 0)} ms vs median {skew.get('p50', 0)} ms "
                f"({coef:.1f}x)",
                "enable AQE skew splitting (ballista.aqe.skew_enabled) or "
                "speculative execution (ballista.speculation.enabled); "
                "check the partition key's value distribution",
                stage_id=sid,
                **ev,
            )
        )


def _rule_fetch_bound(cp, out: List[dict]) -> None:
    for sid, roll in (cp.get("stages") or {}).items():
        fetch = roll.get("fetch_wait_ms", 0.0)
        task = roll.get("task_time_ms", 0.0)
        if fetch < FETCH_MIN_MS or task <= 0 or fetch < FETCH_FRACTION * task:
            continue
        out.append(
            _finding(
                "fetch_bound_stage",
                "warn",
                f"stage {sid} spent {fetch:.0f} ms ({100 * fetch / task:.0f}% "
                "of its task time) waiting on shuffle fetch",
                "raise ballista.shuffle.fetch_concurrency / prefetch_bytes, "
                "enable locality placement "
                "(ballista.shuffle.locality_enabled), or check the serving "
                "executors' load",
                stage_id=int(sid),
                fetch_wait_ms=fetch,
                task_time_ms=task,
            )
        )


def _rule_compile_dominated(cp, out: List[dict]) -> None:
    for sid, roll in (cp.get("stages") or {}).items():
        compile_ms = roll.get("tpu_compile_ms", 0.0)
        execute_ms = roll.get("tpu_execute_ms", 0.0)
        if compile_ms < COMPILE_MIN_MS or compile_ms <= execute_ms:
            continue
        out.append(
            _finding(
                "compile_dominated_stage",
                "info",
                f"stage {sid} spent {compile_ms:.0f} ms compiling XLA vs "
                f"{execute_ms:.0f} ms executing",
                "expected on first-run shapes; recurring compiles mean the "
                "signature cache is thrashing — pin batch sizes "
                "(ballista.batch.size) so shapes repeat",
                stage_id=int(sid),
                tpu_compile_ms=compile_ms,
                tpu_execute_ms=execute_ms,
            )
        )


def _rule_admission_queued(cp, events, cluster, out: List[dict]) -> None:
    wait = (cp.get("breakdown") or {}).get("admission_queue_wait_ms", 0.0)
    wall = cp.get("wall_clock_ms") or 0.0
    if wait < ADMISSION_MIN_MS or wait < ADMISSION_FRACTION * max(wall, 1.0):
        return
    ev = {"queue_wait_ms": wait, "wall_clock_ms": wall}
    for e in events or []:
        if e.get("kind") == "job_admitted":
            if e.get("pool"):
                ev["pool"] = e["pool"]
            break
    suggestion = (
        "the cluster was saturated: raise the pool's weight "
        "(ballista.tenant.weight), mark the session interactive "
        "(ballista.tenant.priority), or add executors"
    )
    if cluster and cluster.get("scale_out_in_flight"):
        # the autoscaler already reacted: launches are in flight, so the
        # right next step is to wait for the capacity, not re-tune pools
        ev["scale_out_in_flight"] = True
        ev["autoscaler_launching"] = cluster.get("autoscaler_launching", 0)
        suggestion += (
            "; note: an autoscaler scale-out is already in flight "
            f"({cluster.get('autoscaler_launching', 0)} executor(s) "
            "launching) — queue wait should fall once they register"
        )
    out.append(
        _finding(
            "admission_queued_job",
            "warn",
            f"job waited {wait:.0f} ms ({100 * wait / max(wall, 1.0):.0f}% "
            "of wall-clock) in the admission queue before planning",
            suggestion,
            **ev,
        )
    )


def _rule_underprovisioned(cp, cluster, out: List[dict]) -> None:
    """Sustained scheduling delay + work queued at the admission door
    while the cluster sits below its executor ceiling: the job was slow
    because capacity was missing, not because the plan was bad."""
    if not cluster:
        return
    delay = (cp.get("breakdown") or {}).get("scheduling_delay_ms", 0.0)
    wall = cp.get("wall_clock_ms") or 0.0
    if (
        delay < UNDERPROVISIONED_MIN_MS
        or delay < UNDERPROVISIONED_FRACTION * max(wall, 1.0)
    ):
        return
    queued = cluster.get("admission_queued_jobs", 0)
    alive = cluster.get("alive_executors", 0)
    max_executors = cluster.get("max_executors", 0)
    if not queued or not max_executors or alive >= max_executors:
        return
    if cluster.get("autoscaler_enabled"):
        suggestion = (
            "the autoscaler has headroom "
            f"({alive} alive < max_executors {max_executors}): check its "
            "journal (autoscale_decision events) for launch failures or "
            "backoff, or raise ballista.autoscaler.max_executors"
        )
    else:
        suggestion = (
            "enable ballista.autoscaler.enabled so the scheduler launches "
            "executors when scheduling delay sustains, or add executors "
            "manually"
        )
    out.append(
        _finding(
            "underprovisioned_cluster",
            "warn",
            f"job spent {delay:.0f} ms ({100 * delay / max(wall, 1.0):.0f}% "
            "of wall-clock) waiting for task slots while "
            f"{queued} job(s) queued at admission and only {alive} of "
            f"{max_executors} allowed executor(s) were alive",
            suggestion,
            scheduling_delay_ms=delay,
            wall_clock_ms=wall,
            admission_queued_jobs=queued,
            alive_executors=alive,
            max_executors=max_executors,
            autoscaler_enabled=bool(cluster.get("autoscaler_enabled")),
        )
    )


def _rule_barrier_dominated(cp, detail, out: List[dict]) -> None:
    barrier = (cp.get("breakdown") or {}).get("barrier_wait_ms", 0.0)
    wall = cp.get("wall_clock_ms") or 0.0
    if barrier < BARRIER_MIN_MS or barrier < BARRIER_FRACTION * max(wall, 1.0):
        return
    stages = [
        r["stage_id"]
        for r in cp.get("critical_path", [])
        if (r.get("segments") or {}).get("barrier_wait_ms", 0.0) > 0
    ]
    # streamable/pipeline-breaker classification of the barrier
    # producers' CONSUMERS (the scheduler's classify_shuffle_inputs walk,
    # carried on the job detail): the upside is only reachable where the
    # consumer can legally start on partial input
    rows = {
        int(r["stage_id"]): r for r in (detail or {}).get("stages", [])
    }
    consumers: Dict[str, str] = {}
    for sid in stages:
        for c in (rows.get(int(sid)) or {}).get("output_links", []):
            pl = (rows.get(int(c)) or {}).get("pipeline") or {}
            streamable = int(sid) in (pl.get("streamable_inputs") or [])
            consumers[str(c)] = (
                "streamable" if streamable else "pipeline_breaker"
            )
    reachable = any(v == "streamable" for v in consumers.values())
    if reachable or not consumers:
        suggestion = (
            "enable pipelined execution (ballista.shuffle.pipelined=true): "
            "streamable consumers start once ballista.shuffle."
            "pipelined_min_fraction of map output has committed — "
            f"estimated upside up to {barrier:.0f} ms"
        )
    else:
        suggestion = (
            "the consumers are pipeline breakers (sort / hash-join "
            "build), so ballista.shuffle.pipelined cannot overlap this "
            "window — AQE coalescing and speculation shrink the stage "
            "tails instead"
        )
    out.append(
        _finding(
            "barrier_dominated_job",
            "warn",
            f"{barrier:.0f} ms ({100 * barrier / max(wall, 1.0):.0f}% of "
            "wall-clock) was stage-barrier wait: partial map output "
            "existed while consumers sat idle",
            suggestion,
            barrier_wait_ms=barrier,
            wall_clock_ms=wall,
            pipelining_upside_ms=barrier,
            producer_stages=stages,
            consumer_classification=consumers,
            upside_reachable=reachable,
        )
    )


def _rule_locality_miss(profile, out: List[dict]) -> None:
    for row in profile.get("stages", []):
        placement = (row.get("locality") or {}).get("placement")
        if not placement:
            continue
        local = int(placement.get("local", 0))
        misses = int(placement.get("any", 0))
        if misses < LOCALITY_MIN_MISSES or misses <= local:
            continue
        sid = row["stage_id"]
        out.append(
            _finding(
                "locality_miss_stage",
                "info",
                f"stage {sid} placed {misses} of {misses + local} tasks off "
                "their preferred (most-input-bytes) host",
                "raise ballista.shuffle.locality_wait_seconds, or check "
                "whether the preferred hosts' slots were saturated",
                stage_id=sid,
                placed_local=local,
                placed_any=misses,
                remote_fetches=(row.get("locality") or {}).get(
                    "remote_fetches", 0
                ),
            )
        )


def _rule_speculation_saved(profile, out: List[dict]) -> None:
    for row in profile.get("stages", []):
        spec = row.get("speculation") or {}
        if not spec.get("wins"):
            continue
        sid = row["stage_id"]
        out.append(
            _finding(
                "speculation_saved_straggler",
                "info",
                f"stage {sid}: {spec['wins']} straggler(s) were beaten by "
                "speculative duplicates",
                "working as intended — if this recurs on the same stage, "
                "the underlying skew/host imbalance is worth fixing",
                stage_id=sid,
                wins=spec.get("wins", 0),
                launched=spec.get("launched", 0),
                wasted=spec.get("wasted", 0),
            )
        )


def diagnose(
    detail: dict,
    profile: dict,
    cp: dict,
    events: Optional[List[dict]] = None,
    cluster: Optional[dict] = None,
) -> List[dict]:
    """Run every rule; returns findings sorted warn-first, then by
    stage id (job-level findings first within a severity).  ``cluster``
    is the scheduler's live context (alive/max executors, admission
    queue depth, autoscaler state) for the capacity rules — REST/gRPC
    handlers pass it, offline replays may not."""
    out: List[dict] = []
    _rule_admission_queued(cp, events, cluster, out)
    _rule_underprovisioned(cp, cluster, out)
    _rule_barrier_dominated(cp, detail, out)
    _rule_skewed_stages(detail, profile, out)
    _rule_fetch_bound(cp, out)
    _rule_compile_dominated(cp, out)
    _rule_locality_miss(profile, out)
    _rule_speculation_saved(profile, out)
    out.sort(
        key=lambda f: (
            _SEVERITY_ORDER.get(f.get("severity"), 9),
            f.get("stage_id", -1),
            f.get("code", ""),
        )
    )
    return out


def job_report(
    detail: dict,
    spans: List[dict],
    events: Optional[List[dict]] = None,
    cluster: Optional[dict] = None,
) -> dict:
    """One-stop diagnosis bundle: profile + critical path + findings.
    Shared by the REST handlers and the gRPC ``include_profile`` path so
    every surface (dashboard, ``explain_analyze``) reads identical
    numbers."""
    profile = job_profile(detail, spans)
    cp = compute_critical_path(detail, events)
    findings = diagnose(detail, profile, cp, events, cluster)
    profile["doctor"] = findings
    profile["breakdown"] = cp.get("breakdown")
    return {"profile": profile, "critical_path": cp, "doctor": findings}


# ------------------------------------------------------ explain analyze
def _fmt_ms(v) -> str:
    if v is None:
        return "?"
    return f"{v:.1f}ms" if v < 10_000 else f"{v / 1e3:.2f}s"


def _pct(part, whole) -> str:
    if not whole:
        return ""
    return f" ({100.0 * part / whole:.0f}%)"


def render_explain_analyze(report: dict) -> str:
    """EXPLAIN-ANALYZE-style text tree of a job's diagnosis bundle
    (client surface: ``BallistaContext.explain_analyze(job_id)``)."""
    profile = report.get("profile") or {}
    cp = report.get("critical_path") or {}
    findings = report.get("doctor") or []
    wall = cp.get("wall_clock_ms")
    lines = [
        f"Job {profile.get('job_id', '?')} [{profile.get('state', '?')}] — "
        f"wall-clock {_fmt_ms(wall)}"
        + ("" if cp.get("complete") else " (timing incomplete)")
    ]
    breakdown = cp.get("breakdown") or {}
    nonzero = [(k, v) for k, v in breakdown.items() if v and v > 0.05]
    if nonzero:
        lines.append("├─ where it went:")
        for k, v in sorted(nonzero, key=lambda kv: -kv[1]):
            label = k[:-3].replace("_", " ")
            lines.append(f"│    {label:<22} {_fmt_ms(v):>10}{_pct(v, wall)}")
    path = cp.get("critical_path") or []
    if path:
        lines.append("├─ critical path:")
        for i, row in enumerate(path):
            seg = row.get("segments") or {}
            parts = [
                f"{k[:-3].replace('_', ' ')} {_fmt_ms(v)}"
                for k, v in seg.items()
                if v and v > 0.05
            ]
            arrow = "└▶" if i == len(path) - 1 else "├▶"
            lines.append(
                f"│  {arrow} stage {row['stage_id']} "
                f"(task {row.get('partition', '?')}/{row.get('tasks', '?')}) "
                f"+{_fmt_ms(row.get('dispatch_ms'))} → "
                f"{_fmt_ms(row.get('completed_ms'))}"
            )
            if parts:
                lines.append(f"│       {' · '.join(parts)}")
    if findings:
        lines.append("├─ doctor:")
        for f in findings:
            lines.append(f"│    [{f['severity']}] {f['code']}: {f['summary']}")
    else:
        lines.append("├─ doctor: no findings")
    lines.append("└─ stages:")
    for row in profile.get("stages", []):
        bits = [f"{row.get('partitions', '?')} task(s)"]
        if row.get("cache"):
            # plan-cache serve: output restored from a fingerprint-
            # matched prior run, no tasks dispatched for this stage
            bits.append(f"cache hit ({row['cache'].get('bytes', 0):,}B)")
        if row.get("task_retries"):
            bits.append(f"{row['task_retries']} retr.")
        if row.get("shuffle_bytes_fetched"):
            bits.append(f"read {row['shuffle_bytes_fetched']:,}B")
        sw = row.get("shuffle_write") or {}
        if sw.get("bytes_wire"):
            bits.append(f"wrote {sw['bytes_wire']:,}B")
        tpu = row.get("tpu") or {}
        if tpu:
            bits.append(
                f"tpu {_fmt_ms(tpu.get('compile_ms', 0))} compile / "
                f"{_fmt_ms(tpu.get('execute_ms', 0))} exec"
            )
        skew = (row.get("skew") or {}).get("runtime_ms")
        if skew and skew.get("max_over_median", 0) >= SKEW_COEFFICIENT:
            bits.append(f"skew {skew['max_over_median']:.1f}x")
        lines.append(
            f"     stage {row['stage_id']:<3} [{row.get('state', '?'):<10}] "
            + " · ".join(bits)
        )
    return "\n".join(lines)
