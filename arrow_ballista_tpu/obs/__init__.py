"""Observability subsystem: distributed tracing, unified metrics, exports.

The staged shuffle architecture (scheduler → executor tasks → Flight fetch
→ TPU kernel) is a multi-process pipeline; this package makes it visible
end to end:

* :mod:`.trace` — span API (context manager + decorator, monotonic
  clocks, thread-local current span) with a trace/span id that propagates
  scheduler → executor → shuffle fetch over TaskDefinition fields and
  Flight metadata, so one job yields a single stitched trace;
* :mod:`.recorder` — bounded per-process ring buffer of finished spans
  plus the scheduler-side per-job trace store (executor spans ship
  piggybacked on task-status and heartbeat updates);
* :mod:`.registry` — unified counter/gauge/histogram registry backing
  ``/api/metrics`` and the Prometheus text-exposition endpoint;
* :mod:`.export` — Chrome-trace/Perfetto JSON and the EXPLAIN-ANALYZE
  style per-stage profile behind ``GET /api/jobs/{id}/trace`` and
  ``GET /api/jobs/{id}/profile``;
* :mod:`.telemetry` — per-executor resource sampler whose snapshots ride
  ``HeartBeatParams.telemetry_json`` to the scheduler;
* :mod:`.timeseries` — scheduler-side bounded downsampling series
  (per-executor + cluster aggregates) behind ``GET /api/cluster/health``
  and ``GET /api/cluster/timeseries``, plus per-session SLO tracking;
* :mod:`.events` — append-only size-rotated structured event journal
  (job/stage/task lifecycle, retries, speculation, quarantine, drain)
  behind ``GET /api/jobs/{id}/events`` and ``GET /api/events/tail``.

Tracing is gated by ``ballista.obs.enabled``; with it off the span API
is a near-zero-cost no-op (one module attribute read per call).  The
telemetry heartbeat piggyback is the one always-on piece; the journal
and SLO tracking are off until configured.
"""

from . import trace  # noqa: F401
from .recorder import get_recorder, trace_store  # noqa: F401
from .registry import MetricsRegistry, process_registry  # noqa: F401

__all__ = [
    "trace",
    "get_recorder",
    "trace_store",
    "MetricsRegistry",
    "process_registry",
]
