"""Scheduler-side cluster telemetry: bounded time series + SLO tracking
(ISSUE 7 tentpole, parts b and e).

:class:`SeriesRing` — a bounded ring of ``(ts, value)`` points that
**downsamples instead of truncating**: when the ring fills, every second
point is dropped and the minimum spacing between kept points doubles, so
a fixed-size buffer covers an ever-longer window at decaying resolution
(the classic RRD trade, without the fixed archive schedule).

:class:`ClusterTelemetry` — routes executor heartbeat snapshots
(``HeartBeatParams.telemetry_json``, produced by ``obs/telemetry.py``)
into per-executor rings + a latest-snapshot map, records the scheduler's
own cluster aggregates (queue depth, running tasks, slots free), and
mirrors the latest per-executor values into the scheduler's
MetricsRegistry as labeled gauges so one Prometheus scrape carries both
planes.  Parsing is TOLERANT: old executors ship no payload, broken ones
may ship garbage — both must never take the heartbeat path down.

:class:`SloTracker` — per-session job-latency SLO
(``ballista.obs.slo.job_latency_seconds``): completed jobs feed a
``slo_breaches_total`` counter and a burn-rate gauge (breach fraction
over a sliding window).

Everything here is read by ``GET /api/cluster/health`` and
``GET /api/cluster/timeseries?metric=…`` (scheduler/api.py).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_RING_POINTS = 360
# per-executor numeric keys mirrored into the registry as labeled gauges;
# anything else still rides the rings/latest map but not Prometheus
MIRRORED_GAUGES = {
    "cpu_percent": "executor process CPU percent (can exceed 100 on multicore)",
    "rss_bytes": "executor resident set size",
    "shuffle_disk_bytes": "bytes of shuffle data under the executor work dir",
    "fetch_queue_bytes": "fetched-but-unconsumed shuffle bytes staged in memory",
    "write_queue_bytes": "coalesced-but-unwritten shuffle write bytes queued",
    "replicator_backlog": "async replica uploads submitted but unfinished",
    "active_tasks": "tasks currently executing on the executor",
    "slots_total": "executor task-slot capacity",
}
MAX_SERIES_PER_EXECUTOR = 32


class SeriesRing:
    """Bounded, downsampling ``(ts, value)`` ring (thread-safe)."""

    def __init__(
        self, capacity: int = DEFAULT_RING_POINTS, min_interval_s: float = 0.0
    ):
        self.capacity = max(4, capacity)
        self.min_interval_s = min_interval_s
        self._points: List[List[float]] = []
        self._lock = threading.Lock()

    def add(self, ts: float, value: float) -> None:
        with self._lock:
            if (
                self._points
                and ts - self._points[-1][0] < self.min_interval_s
            ):
                # inside the current resolution: the newest value wins the
                # slot (the ring records state, not a sum)
                self._points[-1] = [ts, value]
                return
            self._points.append([ts, value])
            if len(self._points) >= self.capacity:
                # full: halve resolution, double the window headroom.
                # Keep the NEWEST point exactly (operators read the tail).
                self._points = self._points[(len(self._points) - 1) % 2 :: 2]
                self.min_interval_s = max(self.min_interval_s, 0.5) * 2

    def points(self) -> List[List[float]]:
        with self._lock:
            return [list(p) for p in self._points]

    def __len__(self) -> int:
        with self._lock:
            return len(self._points)


class ClusterTelemetry:
    def __init__(
        self,
        registry=None,
        ring_points: int = DEFAULT_RING_POINTS,
    ):
        self.registry = registry
        self.ring_points = ring_points
        self._lock = threading.Lock()
        self._per_executor: Dict[str, Dict[str, SeriesRing]] = {}
        self._latest: Dict[str, dict] = {}
        self._latest_mono: Dict[str, float] = {}
        self._cluster: Dict[str, SeriesRing] = {}
        self._parse_errors = None
        if registry is not None:
            self._parse_errors = registry.counter(
                "telemetry_parse_errors_total",
                "heartbeat telemetry payloads that failed to parse",
            )

    # ---------------------------------------------------------- executors
    def record_executor(self, executor_id: str, payload) -> bool:
        """Absorb one heartbeat snapshot.  ``payload`` is the raw
        ``telemetry_json`` bytes (or an already-parsed dict).  Returns
        True when something was recorded; malformed payloads from old or
        broken executors count a parse error and change nothing."""
        if not executor_id or not payload:
            return False
        snap = payload
        if isinstance(payload, (bytes, str)):
            try:
                snap = json.loads(payload)
            except Exception:  # noqa: BLE001 - garbage from the wire
                if self._parse_errors is not None:
                    self._parse_errors.inc()
                return False
        if not isinstance(snap, dict):
            if self._parse_errors is not None:
                self._parse_errors.inc()
            return False
        ts = snap.get("ts")
        if not isinstance(ts, (int, float)):
            ts = time.time()
        numeric = {
            k: v
            for k, v in snap.items()
            if k != "ts" and isinstance(v, (int, float))
            and not isinstance(v, bool)
        }
        with self._lock:
            # keep only the numeric view: downstream aggregation sums
            # latest-snapshot fields, so a string value smuggled in by a
            # broken executor must not survive past this point
            self._latest[executor_id] = {"ts": ts, **numeric}
            self._latest_mono[executor_id] = time.monotonic()
            rings = self._per_executor.setdefault(executor_id, {})
            for k, v in numeric.items():
                ring = rings.get(k)
                if ring is None:
                    if len(rings) >= MAX_SERIES_PER_EXECUTOR:
                        continue  # bounded: a hostile payload can't grow us
                    ring = rings[k] = SeriesRing(self.ring_points)
                ring.add(float(ts), float(v))
            # mirror under the same lock that forget_executor takes, so
            # an in-flight heartbeat can't re-register a removed
            # executor's labeled gauges after remove_by_label ran
            if self.registry is not None:
                for k, v in numeric.items():
                    help_ = MIRRORED_GAUGES.get(k)
                    if help_ is None:
                        continue
                    self.registry.gauge(
                        f"executor_{k}", help_, labels={"executor": executor_id}
                    ).set(v)
        return True

    def forget_executor(self, executor_id: str) -> None:
        """Drop a removed executor's series and labeled gauges (its
        latest snapshot would otherwise read as live forever)."""
        with self._lock:
            self._per_executor.pop(executor_id, None)
            self._latest.pop(executor_id, None)
            self._latest_mono.pop(executor_id, None)
            if self.registry is not None:
                self.registry.remove_by_label("executor", executor_id)

    # ------------------------------------------------------------ cluster
    def record_cluster(self, metrics: Dict[str, float], ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            for k, v in metrics.items():
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    continue
                ring = self._cluster.get(k)
                if ring is None:
                    ring = self._cluster[k] = SeriesRing(self.ring_points)
                ring.add(float(ts), float(v))

    # -------------------------------------------------------------- reads
    def latest(self) -> Dict[str, dict]:
        """{executor_id: {**snapshot, "age_s": seconds since receipt}}."""
        now = time.monotonic()
        with self._lock:
            return {
                eid: {**snap, "age_s": round(now - self._latest_mono[eid], 3)}
                for eid, snap in self._latest.items()
            }

    def series(
        self, metric: str, executor_id: Optional[str] = None
    ) -> Optional[List[List[float]]]:
        with self._lock:
            if executor_id:
                ring = self._per_executor.get(executor_id, {}).get(metric)
            else:
                ring = self._cluster.get(metric)
        return ring.points() if ring is not None else None

    def metric_names(self) -> dict:
        with self._lock:
            return {
                "cluster": sorted(self._cluster),
                "executor": sorted(
                    {k for r in self._per_executor.values() for k in r}
                ),
                "executors": sorted(self._per_executor),
            }


class SloTracker:
    """Per-session job-latency SLO.  ``observe`` is called once per
    COMPLETED job with the session's target
    (``ballista.obs.slo.job_latency_seconds``; 0/absent = untracked).
    Burn rate is the breach fraction over the trailing ``window_s`` of
    tracked completions — 0.0 is a healthy budget, 1.0 means every
    recent job breached."""

    def __init__(self, registry, window_s: float = 3600.0):
        self.window_s = window_s
        self._lock = threading.Lock()
        self._ring: deque = deque()  # (mono_ts, breached)
        self._jobs = registry.counter(
            "slo_jobs_total", "completed jobs with a latency SLO configured"
        )
        self._breaches = registry.counter(
            "slo_breaches_total",
            "completed jobs whose latency exceeded the session SLO",
        )
        registry.gauge(
            "slo_burn_rate",
            "fraction of SLO-tracked jobs breaching over the trailing window",
            fn=self.burn_rate,
        )

    def observe(self, latency_s: float, target_s: float) -> bool:
        """Record one completed job; returns True when it breached."""
        if target_s <= 0:
            return False
        breached = latency_s > target_s
        self._jobs.inc()
        if breached:
            self._breaches.inc()
        now = time.monotonic()
        with self._lock:
            self._ring.append((now, breached))
            cutoff = now - self.window_s
            while self._ring and self._ring[0][0] < cutoff:
                self._ring.popleft()
        return breached

    def burn_rate(self) -> float:
        cutoff = time.monotonic() - self.window_s
        with self._lock:
            while self._ring and self._ring[0][0] < cutoff:
                self._ring.popleft()
            if not self._ring:
                return 0.0
            return round(
                sum(1 for _, b in self._ring if b) / len(self._ring), 4
            )

    def snapshot(self) -> dict:
        return {
            "jobs": int(self._jobs.value),
            "breaches": int(self._breaches.value),
            "burn_rate": self.burn_rate(),
            "window_s": self.window_s,
        }
