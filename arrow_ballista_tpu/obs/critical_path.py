"""Critical-path attribution: where a job's wall-clock actually went.

Joins a job's stage DAG with the scheduler-side timeline anchors
(``__stage_timing__`` / ``__task_dispatch_us__`` / ``__task_finish_us__``
synthetic stage metrics, recorded by ``scheduler/execution_graph.py`` on
one clock) into:

* the **critical path** — the chain of stages whose last-committing
  tasks determined end-to-end latency (walk back from the final stage,
  always through the producer that finished last);
* a **time breakdown** that PARTITIONS the job's wall-clock into
  non-overlapping categories, so they sum to wall-clock by construction:

  - ``admission_queue_wait_ms`` — held in the admission queue before
    planning (journal ``job_admitted.queue_wait_s``; PR 12);
  - ``planning_ms`` — distributed planning (graph build);
  - ``scheduling_delay_ms`` — Σ over critical stages of
    resolvable → first dispatch (event-loop + slot-wait latency);
  - ``fetch_wait_ms`` / ``tpu_compile_ms`` / ``tpu_execute_ms`` /
    ``shuffle_write_ms`` / ``compute_ms`` — the critical stage's active
    window, split in proportion to its summed per-task operator metrics
    (``fetch_wait_time_ns``, ``tpu_compile_ns``, ``tpu_execute_ns``,
    ``write_time_ns``; the residual is host/device compute);
  - ``barrier_wait_ms`` — for every NON-final critical stage, the tail
    between its first task commit and its last: partial output already
    existed but the stage barrier held every consumer back.  This is the
    exact window streaming/pipelined execution (ROADMAP item 4) would
    overlap, so it doubles as the ``pipelining_upside_ms`` estimate.

Degradation contract: every anchor may be missing (decoded pre-PR
graphs, scheduler restart mid-job, sampling off — the anchors are
scheduler-side and do NOT depend on span sampling).  Missing data
degrades the affected segments to zero and flags ``complete: false``;
nothing here ever raises on a well-formed job detail.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

_NS_PER_MS = 1e6
_US_PER_MS = 1e3

# Wall-clock partition categories, in render order.
CATEGORIES = (
    "admission_queue_wait_ms",
    "planning_ms",
    "scheduling_delay_ms",
    "fetch_wait_ms",
    "tpu_compile_ms",
    "tpu_execute_ms",
    "compute_ms",
    "shuffle_write_ms",
    "barrier_wait_ms",
    "other_ms",
)

# operator-metric key -> breakdown category for the proportional split
# of a critical stage's active window
_METRIC_CATEGORIES = (
    ("fetch_wait_time_ns", "fetch_wait_ms"),
    ("tpu_compile_ns", "tpu_compile_ms"),
    ("tpu_execute_ns", "tpu_execute_ms"),
    ("write_time_ns", "shuffle_write_ms"),
)


def stage_timing_of(stage) -> dict:
    """Extract the timing block from a LIVE scheduler stage object
    (Resolved/Running: direct attrs; Completed: the persisted synthetic
    metrics).  Returns {} when nothing was recorded.  Called by
    ``TaskManager._detail_of`` under the job entry lock."""
    from .export import STAGE_TIMING_OP, TASK_DISPATCH_OP, TASK_FINISH_OP

    out: dict = {}
    ready = getattr(stage, "ready_unix_ns", 0)
    disp = getattr(stage, "task_dispatch_unix_ns", None)
    fin = getattr(stage, "task_finish_unix_ns", None)
    if disp or fin or ready:
        if ready:
            out["ready_us"] = int(ready) // 1000
        if disp:
            out["dispatch_us"] = {int(p): int(v) // 1000 for p, v in disp.items()}
        if fin:
            out["finish_us"] = {int(p): int(v) // 1000 for p, v in fin.items()}
        return out
    metrics = getattr(stage, "stage_metrics", None) or {}
    summary = metrics.get(STAGE_TIMING_OP)
    if summary and summary.get("ready_us"):
        out["ready_us"] = int(summary["ready_us"])
    disp = metrics.get(TASK_DISPATCH_OP)
    if disp:
        out["dispatch_us"] = {int(p): int(v) for p, v in disp.items()}
    fin = metrics.get(TASK_FINISH_OP)
    if fin:
        out["finish_us"] = {int(p): int(v) for p, v in fin.items()}
    return out


def _metric_sums(row: dict) -> Dict[str, int]:
    """Sum the attribution-relevant operator metrics across a stage row's
    non-synthetic operators."""
    out = {k: 0 for k, _ in _METRIC_CATEGORIES}
    for op, vals in (row.get("metrics") or {}).items():
        if op.startswith("__"):
            continue
        for k in out:
            out[k] += int(vals.get(k, 0))
    return out


def _timing(row: dict) -> dict:
    return row.get("timing") or {}


def _task_time_us(tm: dict) -> int:
    """Summed per-task wall (dispatch → commit) over the partitions
    carrying both anchors — the ONE task-time rule, shared by the
    breakdown's proportional split and the doctor's per-stage rollup so
    the evidence always agrees with the attribution it annotates."""
    disp = tm.get("dispatch_us") or {}
    fin = tm.get("finish_us") or {}
    return sum(max(0, fin[p] - disp[p]) for p in fin if p in disp)


def _stage_end_us(row: dict) -> Optional[int]:
    fin = _timing(row).get("finish_us")
    return max(fin.values()) if fin else None


def admission_wait_ms(events: Optional[List[dict]]) -> float:
    """Queue wait from the journal (``job_admitted.queue_wait_s``); 0
    when the journal is disabled or the job was never queued."""
    for e in events or []:
        if e.get("kind") == "job_admitted":
            try:
                return max(0.0, float(e.get("queue_wait_s", 0.0))) * 1e3
            except (TypeError, ValueError):
                return 0.0
    return 0.0


def _final_stage_id(stages: Dict[int, dict]) -> Optional[int]:
    sinks = [
        sid
        for sid, row in stages.items()
        if not [c for c in row.get("output_links", []) if int(c) in stages]
    ]
    return max(sinks) if sinks else (max(stages) if stages else None)


def _producers(stages: Dict[int, dict]) -> Dict[int, List[int]]:
    preds: Dict[int, List[int]] = {sid: [] for sid in stages}
    for sid, row in stages.items():
        for consumer in row.get("output_links", []):
            if int(consumer) in preds:
                preds[int(consumer)].append(sid)
    return preds


def _chain(stages: Dict[int, dict]) -> List[int]:
    """Final stage ← always the producer whose last task committed last
    (the one that determined when the consumer became dispatchable)."""
    final = _final_stage_id(stages)
    if final is None:
        return []
    preds = _producers(stages)
    chain = [final]
    seen = {final}
    cur = final
    while True:
        best: Optional[Tuple[int, int]] = None  # (end_us, sid)
        for p in preds.get(cur, []):
            if p in seen:
                continue
            end = _stage_end_us(stages[p])
            if end is None:
                # no timing on this producer: deterministic fallback so
                # the chain still descends (degraded, flagged upstream)
                end = -1
            if best is None or (end, p) > best:
                best = (end, p)
        if best is None:
            break
        cur = best[1]
        seen.add(cur)
        chain.append(cur)
    chain.reverse()
    return chain


def _split_window(
    window_us: int, sums_ns: Dict[str, int], total_task_ns: int, out: Dict[str, float]
) -> None:
    """Attribute ``window_us`` of wall-clock across the metric categories
    in proportion to the stage's summed task time; residual → compute.
    Exact partition: the parts always sum to the window."""
    if window_us <= 0:
        return
    window_ms = window_us / _US_PER_MS
    if total_task_ns <= 0:
        out["compute_ms"] += window_ms
        return
    attributed = 0.0
    for key, cat in _METRIC_CATEGORIES:
        # never over-attribute past the window (task-time sums can exceed
        # wall when tasks run concurrently inside one stage)
        part = min(
            max(0.0, window_ms * sums_ns.get(key, 0) / total_task_ns),
            window_ms - attributed,
        )
        out[cat] += part
        attributed += part
    out["compute_ms"] += max(0.0, window_ms - attributed)


def stage_rollup(row: dict) -> dict:
    """Per-stage attribution totals over ALL of the stage's task attempts
    (not just the critical one) — the doctor's per-stage evidence."""
    tm = _timing(row)
    disp = tm.get("dispatch_us") or {}
    fin = tm.get("finish_us") or {}
    total_task_us = _task_time_us(tm)
    sums = _metric_sums(row)
    out = {
        "stage_id": row.get("stage_id"),
        "task_time_ms": round(total_task_us / _US_PER_MS, 3),
        "fetch_wait_ms": round(sums["fetch_wait_time_ns"] / _NS_PER_MS, 3),
        "tpu_compile_ms": round(sums["tpu_compile_ns"] / _NS_PER_MS, 3),
        "tpu_execute_ms": round(sums["tpu_execute_ns"] / _NS_PER_MS, 3),
        "shuffle_write_ms": round(sums["write_time_ns"] / _NS_PER_MS, 3),
    }
    ready = tm.get("ready_us")
    if ready and disp:
        out["scheduling_delay_ms"] = round(
            sum(max(0, d - ready) for d in disp.values()) / _US_PER_MS, 3
        )
    if fin:
        end = max(fin.values())
        first = min(fin.values())
        out["barrier_tail_ms"] = round(max(0, end - first) / _US_PER_MS, 3)
    return out


def compute_critical_path(
    detail: dict, events: Optional[List[dict]] = None
) -> dict:
    """The ``GET /api/jobs/{id}/critical_path`` payload.  ``detail`` is
    ``TaskManager.get_job_detail`` output (stage rows carrying
    ``timing`` blocks); ``events`` the job's journal slice (admission
    wait), or None."""
    stages = {
        int(r["stage_id"]): r for r in detail.get("stages", [])
    }
    breakdown: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
    admission_ms = admission_wait_ms(events)
    breakdown["admission_queue_wait_ms"] = admission_ms

    out = {
        "job_id": detail.get("job_id"),
        "state": detail.get("state"),
        "complete": False,
        "critical_path": [],
        "breakdown": breakdown,
        "stages": {
            sid: stage_rollup(row) for sid, row in sorted(stages.items())
        },
    }

    submitted_us = detail.get("submitted_us")
    planning_us = detail.get("planning_us") or 0
    chain = _chain(stages)
    if not chain or submitted_us is None:
        out["wall_clock_ms"] = round(admission_ms, 3)
        return out

    breakdown["planning_ms"] = planning_us / _US_PER_MS
    cursor = submitted_us + planning_us
    degraded = False
    skipped_gap = False
    path_rows = []
    for i, sid in enumerate(chain):
        row = stages[sid]
        tm = _timing(row)
        disp = tm.get("dispatch_us") or {}
        fin = tm.get("finish_us") or {}
        if not disp or not fin:
            # no anchors (pre-upgrade stage, restart mid-job): its
            # runtime must degrade to UNATTRIBUTED time, not leak into
            # the next stage's scheduling delay
            degraded = True
            skipped_gap = True
            continue
        final_link = i == len(chain) - 1
        ready = tm.get("ready_us") or cursor
        first_dispatch = min(disp.values())
        first_finish = min(fin.values())
        end = max(fin.values())
        crit_partition = max(fin, key=lambda p: fin[p])

        if skipped_gap:
            # the wall spent inside the skipped anchor-less stage(s)
            # ends where this stage became dispatchable (its ready
            # anchor; first dispatch when that too is missing) — charge
            # it to other_ms so scheduling_delay_ms stays honest
            anchor = tm.get("ready_us") or first_dispatch
            breakdown["other_ms"] += max(0, anchor - cursor) / _US_PER_MS
            cursor = max(cursor, anchor)
            skipped_gap = False

        # monotone cursor advance: every segment is max(point-cursor, 0),
        # so the segments partition [submit, end] exactly whatever the
        # anchors' small-scale disorder
        sched_us = max(0, first_dispatch - cursor)
        breakdown["scheduling_delay_ms"] += sched_us / _US_PER_MS
        cursor = max(cursor, first_dispatch)

        seg: Dict[str, float] = {c: 0.0 for c in CATEGORIES[2:]}
        seg["scheduling_delay_ms"] = round(sched_us / _US_PER_MS, 3)
        # active window: dispatch → first commit (final stage: → last
        # commit; it has no consumer a barrier could hold back)
        window_end = end if final_link else max(first_finish, cursor)
        window_us = max(0, window_end - cursor)
        sums = _metric_sums(row)
        total_task_ns = _task_time_us(tm) * 1000
        _split_window(window_us, sums, total_task_ns, seg)
        cursor = max(cursor, window_end)
        if not final_link:
            # barrier wait ends where the next critical stage STARTED: a
            # pipelined consumer dispatches before this stage's last
            # commit, and from that point the wall is the consumer's
            # active window (its fetch-wait metrics attribute the
            # stall-on-producer), not barrier.  Barrier-scheduled jobs
            # have next_dispatch >= end, so their numbers are unchanged;
            # a next stage without anchors degrades to the full tail.
            next_disp = _timing(stages[chain[i + 1]]).get("dispatch_us") or {}
            cap = (
                min(end, max(min(next_disp.values()), cursor))
                if next_disp
                else end
            )
            barrier_us = max(0, cap - cursor)
            seg["barrier_wait_ms"] = round(barrier_us / _US_PER_MS, 3)
            cursor = max(cursor, cap)
        for c in CATEGORIES[3:]:
            breakdown[c] += seg[c]
            seg[c] = round(seg[c], 3)

        path_rows.append(
            {
                "stage_id": sid,
                "partition": crit_partition,
                "ready_ms": round((ready - submitted_us) / _US_PER_MS, 3),
                "dispatch_ms": round(
                    (first_dispatch - submitted_us) / _US_PER_MS, 3
                ),
                "first_finish_ms": round(
                    (first_finish - submitted_us) / _US_PER_MS, 3
                ),
                "completed_ms": round((end - submitted_us) / _US_PER_MS, 3),
                "tasks": row.get("partitions"),
                "segments": seg,
            }
        )

    wall_ms = admission_ms + max(0, cursor - submitted_us) / _US_PER_MS
    for c in breakdown:
        breakdown[c] = round(breakdown[c], 3)
    total = sum(breakdown.values())
    out.update(
        {
            "critical_path": path_rows,
            "wall_clock_ms": round(wall_ms, 3),
            "breakdown_total_ms": round(total, 3),
            "coverage": round(total / wall_ms, 4) if wall_ms > 0 else None,
            "pipelining_upside_ms": breakdown["barrier_wait_ms"],
            "complete": (
                not degraded
                and detail.get("state") == "completed"
                and bool(path_rows)
            ),
        }
    )
    return out
