"""Span API: context-manager + decorator tracing with cross-process ids.

Design rules:

* **Disabled is free.**  ``span()`` costs one module attribute read and
  returns a shared no-op context manager when tracing is off — no
  allocation, no clock read.  Production processes that never call
  :func:`configure` pay nothing for the instrumentation points.
* **Durations are monotonic.**  A span's ``dur`` comes from
  ``time.monotonic_ns`` deltas; its ``ts`` anchor is wall-clock
  (``time.time_ns``) so spans from different processes line up on one
  Chrome-trace timeline.  Wall jumps can skew alignment between
  processes, never a measured duration.
* **Propagation is explicit.**  The scheduler mints a trace id per job
  (root span id == trace id) and ships it on ``TaskDefinition``;
  executors :func:`activate` it around task execution; the shuffle
  fetcher forwards it over Flight headers
  (``x-ballista-trace-id`` / ``x-ballista-parent-span``) so the serving
  executor's ``do_get`` span stitches into the same trace.

Spans are plain dicts (JSON-portable — they ride gRPC piggybacked on
task-status/heartbeat updates):
``{"name", "trace", "span", "parent", "proc", "tid", "ts", "dur",
"attrs"}`` with ``ts``/``dur`` in nanoseconds.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Callable, Optional

TRACE_HEADER = b"x-ballista-trace-id"
PARENT_HEADER = b"x-ballista-parent-span"

# Process-wide switch: the ONLY state the disabled fast path reads.
_enabled = False
_process = "proc"
_sample_rate = 1.0

_tls = threading.local()


def new_id() -> str:
    """16-hex-char random id (spans and traces share the format)."""
    return os.urandom(8).hex()


def is_enabled() -> bool:
    return _enabled


def configure(
    enabled: Optional[bool] = None,
    process: Optional[str] = None,
    sample_rate: Optional[float] = None,
    buffer_cap: Optional[int] = None,
) -> None:
    """Set process-level tracing state.  ``process`` names this process in
    exported traces (``scheduler`` / ``executor:<id>``); ``buffer_cap``
    resizes the finished-span ring buffer."""
    global _enabled, _process, _sample_rate
    if process is not None:
        _process = process
    if sample_rate is not None:
        _sample_rate = max(0.0, min(1.0, float(sample_rate)))
    if buffer_cap is not None:
        from .recorder import get_recorder

        get_recorder().set_cap(buffer_cap)
    if enabled is not None:
        _enabled = bool(enabled)


def enable_from_config(config, process: Optional[str] = None) -> bool:
    """Ratchet tracing ON when a session/task config asks for it (it never
    ratchets off: other sessions in the process may still be traced).
    Returns the resulting enabled state."""
    try:
        if config.obs_enabled:
            configure(
                enabled=True,
                process=process,
                sample_rate=config.obs_sample_rate,
                buffer_cap=config.obs_buffer_spans,
            )
    except Exception:  # noqa: BLE001 - observability must never break jobs
        pass
    return _enabled


def enable_from_props(props, process: Optional[str] = None) -> bool:
    """Executor-side ratchet from TaskDefinition.props (string map).
    Malformed values are ignored — observability must never fail a task
    (props are unvalidated forward-compat keys on older schedulers)."""
    if not props:
        return _enabled
    try:
        if str(props.get("ballista.obs.enabled", "false")).lower() in (
            "true", "1", "yes",
        ):
            cap = props.get("ballista.obs.buffer_spans")
            configure(
                enabled=True,
                process=process,
                buffer_cap=int(cap) if cap else None,
            )
    except Exception:  # noqa: BLE001
        pass
    return _enabled


def sampled() -> bool:
    """One sampling decision (made per trace, at the scheduler)."""
    if _sample_rate >= 1.0:
        return True
    if _sample_rate <= 0.0:
        return False
    return int.from_bytes(os.urandom(4), "big") / 2**32 < _sample_rate


# --------------------------------------------------------------- contexts
class _Ctx:
    """Thread-local trace position: (trace_id, span_id of current span)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


def current_context() -> Optional[_Ctx]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _push(ctx: _Ctx) -> None:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def _pop() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


class _Activation:
    """Adopt a remote trace context (from TaskDefinition / Flight headers)
    as this thread's current position, so child spans stitch under it."""

    __slots__ = ("_ctx", "_active")

    def __init__(self, trace_id: str, parent_span_id: str):
        self._ctx = (
            _Ctx(trace_id, parent_span_id or trace_id) if trace_id else None
        )
        self._active = False

    def __enter__(self) -> "_Activation":
        if self._ctx is not None:
            self._push_now()
        return self

    def _push_now(self) -> None:
        _push(self._ctx)
        self._active = True

    def __exit__(self, *exc) -> None:
        if self._active:
            _pop()
            self._active = False


def activate(trace_id: str, parent_span_id: str = "") -> _Activation:
    """Context manager installing a propagated trace position.  An empty
    ``trace_id`` (unsampled or untraced job) activates nothing."""
    return _Activation(trace_id, parent_span_id)


def propagation_headers() -> list:
    """gRPC/Flight metadata for the current position ([] when untraced)."""
    ctx = current_context() if _enabled else None
    if ctx is None:
        return []
    return [
        (TRACE_HEADER, ctx.trace_id.encode()),
        (PARENT_HEADER, ctx.span_id.encode()),
    ]


# ------------------------------------------------------------------ spans
class _NoopSpan:
    """Shared do-nothing span: the disabled path and exception-safe
    fallback.  One instance serves the whole process."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def set_attr(self, key: str, value) -> None:
        pass


NOOP = _NoopSpan()


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attrs",
        "_start_unix_ns", "_start_mono_ns", "_pushed",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        parent_id: str,
        attrs: dict,
        span_id: Optional[str] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id or new_id()
        self.parent_id = parent_id
        self.attrs = attrs
        self._pushed = False

    def __enter__(self) -> "Span":
        _push(_Ctx(self.trace_id, self.span_id))
        self._pushed = True
        self._start_unix_ns = time.time_ns()
        self._start_mono_ns = time.monotonic_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.monotonic_ns() - self._start_mono_ns
        if self._pushed:
            _pop()
            self._pushed = False
        if exc is not None:
            self.attrs["error"] = f"{getattr(exc_type, '__name__', exc_type)}: {exc}"
        from .recorder import get_recorder

        get_recorder().record(
            {
                "name": self.name,
                "trace": self.trace_id,
                "span": self.span_id,
                "parent": self.parent_id,
                "proc": _process,
                "tid": threading.get_ident() & 0xFFFFFFFF,
                "ts": self._start_unix_ns,
                "dur": dur,
                "attrs": self.attrs,
            }
        )

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value


def span(name: str, parent: Optional[_Ctx] = None, **attrs):
    """Start a span as a context manager.

    Disabled path: one global read, returns the shared no-op.  A span
    also needs a POSITION — an explicit ``parent`` or the thread's
    current context.  Without one it returns the no-op too: that is what
    makes per-job sampling propagate end to end (an unsampled job ships
    an empty trace id, ``activate("")`` installs nothing, and every
    child span call on that task collapses to the no-op instead of
    minting orphan local traces).  Roots are explicit: :func:`root_span`
    / :func:`activate`.
    """
    if not _enabled:
        return NOOP
    ctx = parent if parent is not None else current_context()
    if ctx is None:
        return NOOP
    return Span(name, ctx.trace_id, ctx.span_id, attrs)


class _NoopManualSpan:
    """Disabled-path manual span: exposes .ctx (None) for child-parenting
    and no-op set_attr/finish."""

    __slots__ = ()
    ctx = None

    def set_attr(self, key: str, value) -> None:
        pass

    def finish(self) -> None:
        pass


NOOP_MANUAL = _NoopManualSpan()


class ManualSpan:
    """A span that never touches the thread-local stack — for GENERATOR
    bodies, where a ``with span(...)`` around yields would leave this
    span as the thread's current context while the generator is
    suspended (mis-parenting whatever the consumer records between
    next() calls) and could pop a foreign context if the generator is
    finalized on another thread.  Children parent via ``.ctx``
    explicitly; call :meth:`finish` exactly once (idempotent)."""

    __slots__ = ("name", "ctx", "parent_id", "attrs", "_start_unix_ns",
                 "_start_mono_ns", "_done")

    def __init__(self, name: str, parent: Optional[_Ctx], attrs: dict):
        self.name = name
        span_id = new_id()
        trace_id = parent.trace_id if parent is not None else span_id
        self.ctx = _Ctx(trace_id, span_id)
        self.parent_id = parent.span_id if parent is not None else ""
        self.attrs = attrs
        self._start_unix_ns = time.time_ns()
        self._start_mono_ns = time.monotonic_ns()
        self._done = False

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        from .recorder import get_recorder

        get_recorder().record(
            {
                "name": self.name,
                "trace": self.ctx.trace_id,
                "span": self.ctx.span_id,
                "parent": self.parent_id,
                "proc": _process,
                "tid": threading.get_ident() & 0xFFFFFFFF,
                "ts": self._start_unix_ns,
                "dur": time.monotonic_ns() - self._start_mono_ns,
                "attrs": self.attrs,
            }
        )


def manual_span(name: str, parent: Optional[_Ctx] = None, **attrs):
    """Start a stack-free span (see :class:`ManualSpan`).  Inherits the
    CALLING thread's current context when ``parent`` is omitted; like
    :func:`span`, positionless calls collapse to the no-op (sampling)."""
    if not _enabled:
        return NOOP_MANUAL
    ctx = parent if parent is not None else current_context()
    if ctx is None:
        return NOOP_MANUAL
    return ManualSpan(name, ctx, attrs)


def root_span(name: str, trace_id: str, **attrs):
    """The trace's root: span id == trace id (the convention every child
    shipped to another process parents under)."""
    if not _enabled or not trace_id:
        return NOOP
    return Span(name, trace_id, "", attrs, span_id=trace_id)


def traced(name: Optional[str] = None, **attrs) -> Callable:
    """Decorator form of :func:`span`."""

    def deco(fn: Callable) -> Callable:
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled:
                return fn(*args, **kwargs)
            with span(label, **attrs):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def record_raw(
    name: str,
    trace_id: str,
    span_id: str,
    parent_id: str,
    ts_unix_ns: int,
    dur_ns: int,
    **attrs,
) -> None:
    """Record an already-timed span (e.g. the job span emitted at
    completion from the graph's submit timestamps)."""
    if not _enabled or not trace_id:
        return
    from .recorder import get_recorder

    get_recorder().record(
        {
            "name": name,
            "trace": trace_id,
            "span": span_id,
            "parent": parent_id,
            "proc": _process,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "ts": ts_unix_ns,
            "dur": dur_ns,
            "attrs": attrs,
        }
    )
