"""Per-executor resource sampler (ISSUE 7 tentpole, part a).

One :class:`TelemetrySampler` per executor process; the push-mode
``Heartbeater`` calls :meth:`sample` right before each beat and ships the
snapshot as ``HeartBeatParams.telemetry_json``.  The scheduler's
``ClusterTelemetry`` (obs/timeseries.py) keeps the per-executor series
and the cluster aggregates both ROADMAP consumers need: admission
control / KEDA-style autoscaling reads queue depth and slot saturation;
adaptive re-planning reads the same executor pressure signals the skew
analytics complement.

Design rules:

* **Sampling must never hurt the data plane.**  Every probe is wrapped:
  a failed read degrades that field to absence, never the beat.  The
  work-dir disk walk — the only probe that is not O(1) — is throttled to
  once per ``disk_interval_s`` and reuses the previous value between
  walks.
* **Point-in-time, latest-wins.**  Unlike spans (which requeue on a
  failed heartbeat so the trace has no gaps), a telemetry snapshot is
  superseded by the next sample — a lost beat just means the scheduler
  sees the NEXT snapshot, so there is nothing to requeue.
* **Disabled is free.**  ``enabled=False`` turns :meth:`sample` into a
  single attribute check returning None.

Snapshot fields (all optional for the reader — old executors ship none,
newer ones may add more; the scheduler parses tolerantly):
``cpu_percent``, ``rss_bytes``, ``shuffle_disk_bytes``,
``fetch_queue_bytes``, ``write_queue_bytes``, ``replicator_backlog``,
``slots_total``, ``active_tasks``, ``span_drops``, ``ts``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

_PAGE_SIZE = 4096
try:
    _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):
    pass


def _rss_bytes() -> Optional[int]:
    """Resident set size via /proc (Linux); getrusage peak-RSS fallback."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except Exception:  # noqa: BLE001 - non-Linux or hardened /proc
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001
        return None


def dir_bytes(path: str) -> int:
    """Total file bytes under ``path`` (0 when absent/unreadable)."""
    total = 0
    try:
        for root, _dirs, files in os.walk(path):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(root, name))
                except OSError:
                    pass
    except OSError:
        pass
    return total


class TelemetrySampler:
    """Snapshot this process's resource pressure for the heartbeat
    piggyback.  ``active_tasks_fn`` is the executor's live task count
    (``Executor.active_task_count``); ``slots_total`` its concurrency."""

    def __init__(
        self,
        work_dir: str = "",
        slots_total: int = 0,
        active_tasks_fn: Optional[Callable[[], int]] = None,
        disk_interval_s: float = 10.0,
        enabled: bool = True,
    ):
        self.enabled = enabled
        self.work_dir = work_dir
        self.slots_total = slots_total
        self.active_tasks_fn = active_tasks_fn
        self.disk_interval_s = disk_interval_s
        self._lock = threading.Lock()
        # CPU%: process CPU time (all threads) over wall time between
        # samples — can exceed 100 on multi-core, exactly like top's view
        self._last_cpu: Optional[float] = None
        self._last_mono: Optional[float] = None
        self._disk_bytes = 0
        self._disk_sampled_mono = float("-inf")

    # ------------------------------------------------------------- probes
    def _cpu_percent(self, now_mono: float) -> Optional[float]:
        cpu = time.process_time()
        with self._lock:
            last_cpu, last_mono = self._last_cpu, self._last_mono
            self._last_cpu, self._last_mono = cpu, now_mono
        if last_cpu is None or last_mono is None or now_mono <= last_mono:
            return None  # first sample has no baseline
        return round(100.0 * (cpu - last_cpu) / (now_mono - last_mono), 2)

    def _shuffle_disk_bytes(self, now_mono: float) -> int:
        with self._lock:
            fresh = now_mono - self._disk_sampled_mono < self.disk_interval_s
            if fresh or not self.work_dir:
                return self._disk_bytes
            self._disk_sampled_mono = now_mono  # claim before the walk
        n = dir_bytes(self.work_dir)
        with self._lock:
            self._disk_bytes = n
        return n

    # ------------------------------------------------------------- sample
    def sample(self) -> Optional[dict]:
        """One snapshot dict, or None (disabled / sampler broke).  Never
        raises — telemetry must never fail a heartbeat."""
        if not self.enabled:
            return None
        try:
            now_mono = time.monotonic()
            out: dict = {"ts": round(time.time(), 3)}
            cpu = self._cpu_percent(now_mono)
            if cpu is not None:
                out["cpu_percent"] = cpu
            rss = _rss_bytes()
            if rss is not None:
                out["rss_bytes"] = rss
            if self.work_dir:
                out["shuffle_disk_bytes"] = self._shuffle_disk_bytes(now_mono)
            # queue occupancy: fetch-side staging bytes + write-pool
            # queued bytes are process-wide counters maintained by the
            # shuffle data plane (jax-free modules; cheap reads)
            from ..shuffle import fetcher, writer

            out["fetch_queue_bytes"] = fetcher.staging_bytes()
            out["write_queue_bytes"] = writer.queued_bytes()
            from ..shuffle import store as shuffle_store

            out["replicator_backlog"] = shuffle_store.replicator_backlog()
            if self.slots_total:
                out["slots_total"] = self.slots_total
            if self.active_tasks_fn is not None:
                out["active_tasks"] = int(self.active_tasks_fn())
            from .recorder import get_recorder

            out["span_drops"] = get_recorder().dropped
            return out
        except Exception:  # noqa: BLE001 - degrade to no payload
            return None
