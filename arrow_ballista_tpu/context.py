"""Local (single-process) session context.

This is the single-node engine entry point — the role DataFusion's
``SessionContext`` plays under the reference's ``BallistaContext``
(``client/src/context.rs:78-460``).  The distributed ``BallistaContext``
(client/context.py) delegates planning here and swaps execution for the
scheduler path.
"""

from __future__ import annotations

import datetime as _dt
from typing import Optional

import pyarrow as pa

from .catalog import Catalog, CsvTable, MemoryTable, ParquetTable, TableProvider
from .config import BallistaConfig
from .errors import PlanError, SqlError
from .exec.operators import ExecutionPlan, TaskContext, collect
from .exec.planner import PhysicalPlanner
from .plan import logical as lp
from .plan.builder import PlanBuilder, sql_type_to_arrow
from .plan.optimizer import optimize
from .sql import ast
from .sql.parser import parse_sql


class DataFrame:
    """Lazy query handle (reference: DataFusion DataFrame via
    BallistaContext::sql / read_parquet)."""

    def __init__(self, ctx: "SessionContext", plan: lp.LogicalPlan):
        self.ctx = ctx
        self.plan = plan

    # -- transformations -------------------------------------------------
    def select(self, *exprs) -> "DataFrame":
        from .plan import expressions as ex

        exprs = [ex.col(e) if isinstance(e, str) else e for e in exprs]
        return type(self)(self.ctx, lp.Projection(list(exprs), self.plan))

    def filter(self, predicate) -> "DataFrame":
        return type(self)(self.ctx, lp.Filter(predicate, self.plan))

    def aggregate(self, group_by: list, aggs: list) -> "DataFrame":
        return type(self)(self.ctx, lp.Aggregate(list(group_by), list(aggs), self.plan))

    def sort(self, *sort_exprs) -> "DataFrame":
        from .plan import expressions as ex

        fixed = []
        for e in sort_exprs:
            if isinstance(e, str):
                e = ex.col(e).sort()
            elif not isinstance(e, ex.SortExpr):
                e = e.sort()
            fixed.append(e)
        return type(self)(self.ctx, lp.Sort(fixed, self.plan))

    def limit(self, n: int, offset: int = 0) -> "DataFrame":
        return type(self)(self.ctx, lp.Limit(self.plan, offset, n))

    def join(self, right: "DataFrame", on: list, how: str = "inner") -> "DataFrame":
        from .plan import expressions as ex

        pairs = []
        for item in on:
            if isinstance(item, str):
                pairs.append((ex.col(item), ex.col(item)))
            else:
                l, r = item
                pairs.append(
                    (
                        ex.col(l) if isinstance(l, str) else l,
                        ex.col(r) if isinstance(r, str) else r,
                    )
                )
        return type(self)(self.ctx, lp.Join(self.plan, right.plan, pairs, how, None))

    def union(self, other: "DataFrame") -> "DataFrame":
        return type(self)(self.ctx, lp.Union([self.plan, other.plan]))

    def distinct(self) -> "DataFrame":
        return type(self)(self.ctx, lp.Distinct(self.plan))

    # -- actions ---------------------------------------------------------
    @property
    def schema(self) -> pa.Schema:
        return self.plan.schema

    def logical_plan(self) -> lp.LogicalPlan:
        return self.plan

    def optimized_plan(self) -> lp.LogicalPlan:
        return optimize(self.plan)

    def physical_plan(self) -> ExecutionPlan:
        return self.ctx.create_physical_plan(self.optimized_plan())

    def collect(self) -> pa.Table:
        return _unqualify(self.ctx.execute(self.physical_plan()))

    def to_pandas(self):
        return self.collect().to_pandas()

    def count(self) -> int:
        return self.collect().num_rows

    def explain(self) -> str:
        phys = self.physical_plan()
        return (
            "== Logical Plan ==\n"
            + self.optimized_plan().display()
            + "\n== Physical Plan ==\n"
            + phys.display()
        )

    def show(self, n: int = 20) -> None:
        print(self.limit(n).collect().to_pandas().to_string())


class SessionContext:
    def __init__(self, config: Optional[BallistaConfig] = None):
        from .udf import UdfRegistry, global_registry, load_udf_plugins

        self.config = config or BallistaConfig()
        self.catalog = Catalog()
        self.session_id = _gen_id()
        self.variables: dict[str, str] = {}
        # session UDFs shadow the process-global registry (plugins)
        self.udfs = UdfRegistry(parent=global_registry())
        from .config import PLUGIN_DIR

        plugin_dir = self.config.settings.get(PLUGIN_DIR, "")
        if plugin_dir:
            load_udf_plugins(plugin_dir)

    def fork(self) -> "SessionContext":
        """Statement-scoped view of this session: shares config/UDFs/
        variables and SEES the same tables, but owns a private catalog
        copy so CTE registration (``_sql_with_ctes`` mutates the catalog)
        cannot race concurrent statements on a shared session — the
        FlightSQL front-end runs every query on a fork."""
        child = SessionContext.__new__(SessionContext)
        child.config = self.config
        child.catalog = Catalog()
        child.catalog.tables = dict(self.catalog.tables)
        child.session_id = self.session_id
        child.variables = dict(self.variables)
        child.udfs = self.udfs
        return child

    # -- registration ----------------------------------------------------
    def register_table(self, name: str, provider: TableProvider) -> None:
        self.catalog.register(name, provider)

    def register_parquet(self, name: str, path: str) -> None:
        self.catalog.register(name, ParquetTable(path))

    def register_csv(
        self,
        name: str,
        path: str,
        schema: Optional[pa.Schema] = None,
        has_header: bool = True,
        delimiter: str = ",",
    ) -> None:
        self.catalog.register(name, CsvTable(path, schema, has_header, delimiter))

    def register_avro(self, name: str, path: str) -> None:
        from .catalog import AvroTable

        self.catalog.register(name, AvroTable(path))

    def read_avro(self, path: str) -> DataFrame:
        name = f"__anon_avro_{_gen_id()[:6]}"
        self.register_avro(name, path)
        return self.table(name)

    def register_record_batches(
        self, name: str, partitions: list[list[pa.RecordBatch]]
    ) -> None:
        self.catalog.register(name, MemoryTable(partitions))

    def register_arrow_table(self, name: str, table: pa.Table, partitions: int = 1) -> None:
        self.catalog.register(name, MemoryTable.from_table(table, partitions))

    def deregister_table(self, name: str) -> None:
        self.catalog.deregister(name)

    # -- user-defined functions ------------------------------------------
    def register_udf(self, udf) -> None:
        """Register a ScalarUDF for this session AND process-wide, so
        in-proc executors (standalone mode) can resolve it at evaluation
        time — the distributed analogue is the executor's plugin dir."""
        from .udf import global_registry

        self.udfs.register_scalar(udf)
        global_registry().register_scalar(udf)

    def register_udaf(self, udaf) -> None:
        from .udf import global_registry

        self.udfs.register_aggregate(udaf)
        global_registry().register_aggregate(udaf)

    def read_parquet(self, path: str) -> DataFrame:
        name = f"__anon_parquet_{_gen_id()[:6]}"
        self.register_parquet(name, path)
        return self.table(name)

    def read_csv(self, path: str, **kw) -> DataFrame:
        name = f"__anon_csv_{_gen_id()[:6]}"
        self.register_csv(name, path, **kw)
        return self.table(name)

    def table(self, name: str) -> DataFrame:
        provider = self.catalog.get(name)
        return DataFrame(self, lp.TableScan(name.lower(), provider))

    # -- SQL -------------------------------------------------------------
    def sql(self, query: str, stmt: Optional[ast.Statement] = None) -> DataFrame:
        """Run a SQL statement.  ``stmt`` lets a caller that already parsed
        the text (FlightSQL's Query/DDL dispatch) skip the second parse."""
        if stmt is None:
            stmt = parse_sql(query)
        if isinstance(stmt, ast.Query):
            if stmt.ctes:
                return self._sql_with_ctes(stmt)
            builder = PlanBuilder(self.catalog, self.udfs)
            return DataFrame(self, builder.build_query(stmt))
        if isinstance(stmt, ast.CreateExternalTable):
            return self._create_external_table(stmt)
        if isinstance(stmt, ast.ShowStmt):
            return self._show(stmt)
        if isinstance(stmt, ast.SetVariable):
            self.variables[stmt.name] = stmt.value
            if stmt.name.startswith("ballista."):
                settings = self.config.to_dict()
                settings[stmt.name] = stmt.value
                self.config = BallistaConfig.from_dict(settings)
            return self._values_df(pa.table({"result": pa.array(["ok"])}))
        if isinstance(stmt, ast.Explain):
            builder = PlanBuilder(self.catalog, self.udfs)
            df = DataFrame(self, builder.build_query(stmt.query))
            if stmt.analyze:
                # EXPLAIN ANALYZE (reference: DataFusion's analyze plan):
                # execute the physical plan, then render it annotated
                # with every operator's runtime metrics
                import time as _time

                phys = df.physical_plan()
                t0 = _time.perf_counter()
                self.execute(phys)
                elapsed = _time.perf_counter() - t0
                text = (
                    phys.display(with_metrics=True)
                    + f"\nelapsed: {elapsed:.6f}s"
                )
                return self._values_df(
                    pa.table(
                        {"plan_type": ["explain analyze"], "plan": [text]}
                    )
                )
            text = df.explain()
            return self._values_df(
                pa.table({"plan_type": ["explain"], "plan": [text]})
            )
        if isinstance(stmt, ast.DropTable):
            if stmt.name.lower() not in self.catalog.tables and not stmt.if_exists:
                raise PlanError(f"table {stmt.name!r} does not exist")
            self.deregister_table(stmt.name)
            return self._values_df(pa.table({"result": pa.array(["ok"])}))
        raise SqlError(f"unsupported statement {type(stmt).__name__}")

    def _sql_with_ctes(self, stmt: ast.Query) -> DataFrame:
        """Materialize each WITH-clause query ONCE and expose it as an
        in-memory table to the main query (and to later CTEs).

        Eager single evaluation (rather than inline expansion at every
        reference) both avoids recomputation and guarantees bit-identical
        results across references — q15's ``total_revenue = (select
        max(total_revenue) from revenue0)`` float equality depends on it.
        """
        import dataclasses

        # (name, previously-registered provider or None) so a CTE that
        # shadows a real table restores it afterwards
        registered: list[tuple[str, Optional[TableProvider]]] = []
        try:
            for name, sub in stmt.ctes:
                shadowed = self.catalog.tables.get(name.lower())
                sub_df = self.sql_query_ast(sub)
                tbl = sub_df.collect()
                self.catalog.register(
                    name,
                    MemoryTable.from_table(tbl, self.config.shuffle_partitions),
                )
                registered.append((name, shadowed))
            main = dataclasses.replace(stmt, ctes=[])
            builder = PlanBuilder(self.catalog, self.udfs)
            return DataFrame(self, builder.build_query(main))
        finally:
            for name, shadowed in registered:
                self.catalog.deregister(name)
                if shadowed is not None:
                    self.catalog.register(name, shadowed)

    def sql_query_ast(self, q: ast.Query) -> DataFrame:
        if q.ctes:
            return self._sql_with_ctes(q)
        return DataFrame(self, PlanBuilder(self.catalog, self.udfs).build_query(q))

    def _create_external_table(self, stmt: ast.CreateExternalTable) -> DataFrame:
        if stmt.name.lower() in self.catalog.tables and stmt.if_not_exists:
            return self._values_df(pa.table({"result": pa.array(["exists"])}))
        schema = None
        if stmt.columns:
            schema = pa.schema(
                [pa.field(n, sql_type_to_arrow(t)) for n, t in stmt.columns]
            )
        ft = stmt.file_type.upper()
        if ft == "PARQUET":
            self.register_parquet(stmt.name, stmt.location)
        elif ft == "CSV":
            self.catalog.register(
                stmt.name,
                CsvTable(stmt.location, schema, stmt.has_header, stmt.delimiter),
            )
        elif ft == "AVRO":
            self.register_avro(stmt.name, stmt.location)
        else:
            raise SqlError(f"unsupported file type {stmt.file_type}")
        return self._values_df(pa.table({"result": pa.array(["ok"])}))

    def _show(self, stmt: ast.ShowStmt) -> DataFrame:
        what = [p.upper() for p in stmt.variable]
        if what[:1] == ["TABLES"]:
            return self._values_df(
                pa.table({"table_name": pa.array(self.catalog.names())})
            )
        if what[:1] == ["COLUMNS"]:
            tname = stmt.variable[-1]
            schema = self.catalog.get(tname).schema
            return self._values_df(
                pa.table(
                    {
                        "column_name": pa.array(schema.names),
                        "data_type": pa.array([str(f.type) for f in schema]),
                        "is_nullable": pa.array(
                            ["YES" if f.nullable else "NO" for f in schema]
                        ),
                    }
                )
            )
        raise SqlError(f"unsupported SHOW {' '.join(stmt.variable)}")

    def _values_df(self, tbl: pa.Table) -> DataFrame:
        # ephemeral relation: not registered in the catalog so it never
        # leaks into SHOW TABLES or error messages
        provider = MemoryTable.from_table(tbl)
        return DataFrame(self, lp.TableScan("__result", provider))

    # -- execution -------------------------------------------------------
    def create_physical_plan(self, logical: lp.LogicalPlan) -> ExecutionPlan:
        phys = PhysicalPlanner(self.config).create_physical_plan(logical)
        from .ops.stage_compiler import maybe_accelerate
        from .parallel.mesh_stage import maybe_mesh

        return maybe_mesh(maybe_accelerate(phys, self.config), self.config)

    def execute(self, plan: ExecutionPlan) -> pa.Table:
        return collect(plan, self.task_context())

    def task_context(self) -> TaskContext:
        return TaskContext(session_id=self.session_id, config=self.config)


def _unqualify(tbl: pa.Table) -> pa.Table:
    """Strip relation qualifiers from output column names (user-facing
    results use bare names, like DataFusion's RecordBatch output)."""
    new = [n.split(".")[-1] for n in tbl.schema.names]
    if len(set(new)) != len(new):
        return tbl
    return tbl.rename_columns(new)


def _gen_id() -> str:
    """7-char alphanumeric id (reference: task_manager.rs:544-551)."""
    import random
    import string

    return "".join(random.choices(string.ascii_lowercase + string.digits, k=7))
