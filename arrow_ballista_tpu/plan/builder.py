"""AST → logical plan builder (name resolution & analysis).

Counterpart of DataFusion's SQL planner as used by the reference's
``BallistaContext::sql`` (``client/src/context.rs:346-460``).  Resolves table
names against the catalog, extracts aggregates out of SELECT/HAVING/ORDER BY,
decorrelates ``IN (subquery)`` into semi/anti joins, and plans uncorrelated
scalar subqueries as :class:`~..plan.expressions.ScalarSubqueryExpr`.
"""

from __future__ import annotations

import datetime as _dt
from typing import Optional

import pyarrow as pa

from ..catalog import Catalog
from ..errors import NotImplementedYet, PlanError, SqlError
from ..sql import ast
from . import expressions as ex
from . import logical as lp


def sql_type_to_arrow(name: str) -> pa.DataType:
    n = name.strip().upper()
    base = n.split("(")[0].strip()
    if base in ("INT", "INTEGER"):
        return pa.int32()
    if base in ("BIGINT", "LONG"):
        return pa.int64()
    if base == "SMALLINT":
        return pa.int16()
    if base == "TINYINT":
        return pa.int8()
    if base in ("FLOAT", "REAL"):
        return pa.float32()
    if base in ("DOUBLE", "DOUBLE PRECISION"):
        return pa.float64()
    if base in ("DECIMAL", "NUMERIC"):
        # decimals execute as float64 on the TPU path (MXU/VPU have no
        # decimal unit); precision-sensitive users can cast explicitly
        return pa.float64()
    if base in ("VARCHAR", "CHAR", "TEXT", "STRING"):
        return pa.string()
    if base in ("BOOLEAN", "BOOL"):
        return pa.bool_()
    if base == "DATE":
        return pa.date32()
    if base in ("TIMESTAMP", "DATETIME"):
        return pa.timestamp("us")
    raise SqlError(f"unsupported SQL type {name!r}")


_INTERVAL_UNIT_MONTHS = {"YEAR": 12, "MONTH": 1}
_INTERVAL_UNIT_DAYS = {"DAY": 1, "WEEK": 7}


def _split_conjuncts(e: ast.SqlExpr) -> list[ast.SqlExpr]:
    if isinstance(e, ast.Binary) and e.op == "AND":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _conjoin(exprs: list[ex.Expr]) -> Optional[ex.Expr]:
    if not exprs:
        return None
    out = exprs[0]
    for e in exprs[1:]:
        out = ex.BinaryExpr(out, "AND", e)
    return out


class PlanBuilder:
    def __init__(self, catalog: Catalog, udfs=None):
        self.catalog = catalog
        self.udfs = udfs  # Optional[UdfRegistry]
        self._sq_counter = 0  # fresh-name counter for decorrelated subqueries
        self._ctes: dict[str, ast.Query] = {}

    # ------------------------------------------------------------- queries
    def build_query(self, q: ast.Query) -> lp.LogicalPlan:
        if q.ctes:
            saved = dict(self._ctes)
            try:
                for name, sub in q.ctes:
                    self._ctes[name.lower()] = sub
                return self._build_query_body(q)
            finally:
                self._ctes = saved
        return self._build_query_body(q)

    def _build_query_body(self, q: ast.Query) -> lp.LogicalPlan:
        # FROM
        if q.from_:
            plan = self._plan_table_ref(q.from_[0])
            for ref in q.from_[1:]:
                plan = lp.CrossJoin(plan, self._plan_table_ref(ref))
        else:
            plan = lp.EmptyRelation(produce_one_row=True)

        # WHERE — peel IN/EXISTS-subquery conjuncts into semi/anti joins.
        # Plain conjuncts are filtered FIRST so the optimizer's
        # Filter(CrossJoin) → hash-join rewrite still sees the cross-join
        # tree; subquery joins are planted on top of the filtered plan.
        if q.where is not None:
            plain: list[ex.Expr] = []
            sub_conjs: list[ast.SqlExpr] = []
            scalar_conjs: list[ast.Binary] = []
            for conj in _split_conjuncts(q.where):
                # normalize NOT EXISTS(...) / NOT (x IN (sub)) shapes
                if (
                    isinstance(conj, ast.Unary)
                    and conj.op == "NOT"
                    and isinstance(conj.operand, (ast.InSubquery, ast.Exists))
                ):
                    inner_c = conj.operand
                    conj = (
                        ast.Exists(inner_c.query, not inner_c.negated)
                        if isinstance(inner_c, ast.Exists)
                        else ast.InSubquery(
                            inner_c.operand, inner_c.query, not inner_c.negated
                        )
                    )
                if isinstance(conj, (ast.InSubquery, ast.Exists)):
                    sub_conjs.append(conj)
                    continue
                try:
                    plain.append(self._expr(conj, plan.schema))
                except PlanError:
                    # a comparison against a *correlated* scalar subquery
                    # fails normal building (outer refs don't resolve);
                    # decorrelate it below instead
                    if isinstance(conj, ast.Binary) and (
                        isinstance(conj.left, ast.ScalarSubquery)
                        or isinstance(conj.right, ast.ScalarSubquery)
                    ):
                        scalar_conjs.append(conj)
                    else:
                        raise
            pred = _conjoin(plain)
            if pred is not None:
                plan = lp.Filter(pred, plan)
            for conj in scalar_conjs:
                outer_fields = list(plan.schema)
                plan, cmp_expr = self._decorrelate_scalar(plan, conj)
                plan = lp.Filter(cmp_expr, plan)
                # project the helper key/value columns back out; alias to the
                # FULL (possibly qualified) field name so later qualified
                # references still resolve
                plan = lp.Projection(
                    [ex.Alias(ex.col(f.name), f.name) for f in outer_fields], plan
                )
            for conj in sub_conjs:
                if isinstance(conj, ast.InSubquery):
                    plan = self._plan_in_subquery(plan, conj)
                else:
                    plan = self._plan_exists(plan, conj)

        in_schema = plan.schema

        # SELECT list with * expansion
        select_exprs: list[ex.Expr] = []
        for item in q.select:
            if isinstance(item.expr, ast.Star):
                qual = item.expr.qualifier
                for f in in_schema:
                    parts = f.name.split(".")
                    if qual is None or (len(parts) == 2 and parts[0] == qual):
                        select_exprs.append(
                            ex.Column(parts[-1], parts[0] if len(parts) == 2 else None)
                        )
            else:
                e = self._expr(item.expr, in_schema)
                if item.alias:
                    e = ex.Alias(e, item.alias)
                select_exprs.append(e)

        alias_map = {e.name: e for e in select_exprs}

        # GROUP BY (supports ordinals and select aliases)
        group_exprs: list[ex.Expr] = []
        for g in q.group_by:
            if isinstance(g, ast.NumberLit):
                idx = int(g.value) - 1
                if idx < 0 or idx >= len(select_exprs):
                    raise SqlError(f"GROUP BY position {g.value} out of range")
                ge = select_exprs[idx]
                ge = ge.expr if isinstance(ge, ex.Alias) else ge
            else:
                ge = self._expr(g, in_schema, alias_map)
            group_exprs.append(ge)

        # aggregates appearing anywhere in select / having / order by
        agg_exprs: list[ex.AggregateExpr] = []

        def _collect(e: ex.Expr) -> None:
            for a in ex.find_aggregates(e):
                if not any(str(a) == str(b) for b in agg_exprs):
                    agg_exprs.append(a)

        for e in select_exprs:
            _collect(e)
        having_expr = (
            self._expr(q.having, in_schema, alias_map) if q.having is not None else None
        )
        if having_expr is not None:
            _collect(having_expr)
        order_exprs: list[ex.SortExpr] = []
        for oi in q.order_by:
            if isinstance(oi.expr, ast.NumberLit):
                idx = int(oi.expr.value) - 1
                if idx < 0 or idx >= len(select_exprs):
                    raise SqlError(f"ORDER BY position {oi.expr.value} out of range")
                base = select_exprs[idx]
                base = base.expr if isinstance(base, ex.Alias) else base
            else:
                base = self._expr(oi.expr, in_schema, alias_map)
            _collect(base)
            order_exprs.append(ex.SortExpr(base, oi.asc, oi.nulls_first))

        if group_exprs or agg_exprs:
            plan = lp.Aggregate(group_exprs, list(agg_exprs), plan)
            agg_schema = plan.schema

            # rewrite select/having/order exprs: aggregate and group-expr
            # occurrences become column refs into the aggregate output
            rewrite_map: dict[str, str] = {}
            for i, g in enumerate(group_exprs):
                rewrite_map[str(g)] = agg_schema.field(i).name
            for j, a in enumerate(agg_exprs):
                rewrite_map[str(a)] = agg_schema.field(len(group_exprs) + j).name

            def _rw(e: ex.Expr) -> ex.Expr:
                def fn(node: ex.Expr) -> ex.Expr:
                    key = str(node)
                    if key in rewrite_map and not isinstance(node, ex.Column):
                        return ex.col(rewrite_map[key])
                    return node

                return ex.transform(e, fn)

            select_exprs = [
                ex.Alias(_rw(e.expr), e.alias_name) if isinstance(e, ex.Alias) else _rw(e)
                for e in select_exprs
            ]
            # validate: non-aggregate select exprs must be grouping exprs
            for e in select_exprs:
                inner = e.expr if isinstance(e, ex.Alias) else e
                for c in ex.find_columns(inner):
                    try:
                        c.resolve_index(agg_schema)
                    except PlanError as err:
                        raise PlanError(
                            f"expression {e} is neither aggregated nor grouped"
                        ) from err
            if having_expr is not None:
                plan = lp.Filter(_rw(having_expr), plan)
            order_exprs = [
                ex.SortExpr(_rw(s.expr), s.asc, s.nulls_first) for s in order_exprs
            ]

        # WINDOW functions evaluate between aggregation and projection:
        # collect distinct window exprs from select/order, plant a Window
        # node, then rewrite occurrences into column refs on its output
        win_exprs: list[ex.WindowExpr] = []

        def _collect_wins(e: ex.Expr) -> None:
            for w in ex.find_windows(e):
                if not any(str(w) == str(x) for x in win_exprs):
                    win_exprs.append(w)

        for e in select_exprs:
            _collect_wins(e)
        for s in order_exprs:
            _collect_wins(s.expr)
        if win_exprs:
            plan = lp.Window(win_exprs, plan)
            wschema = plan.schema
            base = len(wschema) - len(win_exprs)
            wmap = {
                str(w): wschema.field(base + i).name
                for i, w in enumerate(win_exprs)
            }

            def _rww(e: ex.Expr) -> ex.Expr:
                def fn(node: ex.Expr) -> ex.Expr:
                    if isinstance(node, ex.WindowExpr):
                        return ex.col(wmap[str(node)])
                    return node

                return ex.transform(e, fn)

            select_exprs = [
                ex.Alias(_rww(e.expr), e.alias_name)
                if isinstance(e, ex.Alias)
                else _rww(e)
                for e in select_exprs
            ]
            order_exprs = [
                ex.SortExpr(_rww(s.expr), s.asc, s.nulls_first)
                for s in order_exprs
            ]

        plan = lp.Projection(select_exprs, plan)

        if q.distinct:
            plan = lp.Distinct(plan)

        if order_exprs:
            # a top-k sort may keep at most limit+offset rows — the Limit
            # above still applies the skip
            topk = (q.limit + (q.offset or 0)) if q.limit is not None else None
            # resolve sort keys against projection output where possible;
            # otherwise extend the projection, sort, and re-project
            proj_schema = plan.schema
            missing: list[ex.Expr] = []
            resolved: list[ex.SortExpr] = []
            for s in order_exprs:
                try:
                    # data_type alone is not enough: exprs with a fixed
                    # return type (UDFs) succeed without resolving their
                    # argument columns
                    for c in ex.find_columns(s.expr):
                        c.resolve_index(proj_schema)
                    s.expr.data_type(proj_schema)
                    resolved.append(s)
                except PlanError:
                    missing.append(s.expr)
                    # downstream of the widened projection the computed sort
                    # key exists as a named column — reference it by name
                    resolved.append(ex.SortExpr(ex.col(s.expr.name), s.asc, s.nulls_first))
            if missing and isinstance(plan, lp.Projection):
                wide = lp.Projection(plan.exprs + missing, plan.input)
                keep = [f.name for f in proj_schema]
                plan = lp.Projection(
                    [ex.col(n) for n in keep], lp.Sort(resolved, wide, fetch=topk)
                )
            else:
                plan = lp.Sort(resolved, plan, fetch=topk)

        if q.limit is not None or q.offset is not None:
            plan = lp.Limit(plan, q.offset or 0, q.limit)
        return plan

    # ----------------------------------------------------------- table refs
    def _plan_table_ref(self, ref: ast.TableRef) -> lp.LogicalPlan:
        # Inline-expansion fallback for CTEs reaching the builder directly
        # (context._sql_with_ctes materializes top-level CTEs once instead;
        # this path serves nested WITH and direct build_query callers)
        if isinstance(ref, ast.NamedTable) and ref.name.lower() in self._ctes:
            sub = self.build_query(self._ctes[ref.name.lower()])
            return lp.SubqueryAlias(sub, ref.alias or ref.name)
        if isinstance(ref, ast.NamedTable):
            provider = self.catalog.get(ref.name)
            scan = lp.TableScan(ref.name, provider)
            if ref.alias and ref.alias != ref.name:
                return lp.SubqueryAlias(scan, ref.alias)
            return scan
        if isinstance(ref, ast.DerivedTable):
            sub = self.build_query(ref.query)
            return lp.SubqueryAlias(sub, ref.alias)
        if isinstance(ref, ast.JoinClause):
            left = self._plan_table_ref(ref.left)
            right = self._plan_table_ref(ref.right)
            if ref.kind == "CROSS":
                return lp.CrossJoin(left, right)
            schema = pa.schema(list(left.schema) + list(right.schema))
            on_pairs, residual = self._extract_equijoin(
                ref.on, left.schema, right.schema, schema
            )
            if not on_pairs:
                raise NotImplementedYet("non-equi joins require an equality condition")
            jt = ref.kind.lower()
            return lp.Join(left, right, on_pairs, jt, residual)
        raise PlanError(f"unhandled table ref {ref}")

    def _extract_equijoin(
        self,
        on: Optional[ast.SqlExpr],
        left_schema: pa.Schema,
        right_schema: pa.Schema,
        joint: pa.Schema,
    ) -> tuple[list[tuple[ex.Column, ex.Column]], Optional[ex.Expr]]:
        pairs: list[tuple[ex.Column, ex.Column]] = []
        residual: list[ex.Expr] = []
        if on is None:
            return pairs, None
        for conj in _split_conjuncts(on):
            done = False
            if isinstance(conj, ast.Binary) and conj.op == "=":
                l = self._expr(conj.left, joint)
                r = self._expr(conj.right, joint)
                if isinstance(l, ex.Column) and isinstance(r, ex.Column):
                    l_in_left = _column_in(l, left_schema)
                    r_in_left = _column_in(r, left_schema)
                    if l_in_left and not r_in_left:
                        pairs.append((l, r))
                        done = True
                    elif r_in_left and not l_in_left:
                        pairs.append((r, l))
                        done = True
            if not done:
                residual.append(self._expr(conj, joint))
        return pairs, _conjoin(residual)

    def _plan_in_subquery(
        self, plan: lp.LogicalPlan, conj: ast.InSubquery
    ) -> lp.LogicalPlan:
        sub = self.build_query(conj.query)
        if len(sub.schema) != 1:
            raise SqlError("IN subquery must return one column")
        left_key = self._expr(conj.operand, plan.schema)
        if not isinstance(left_key, ex.Column):
            raise NotImplementedYet("IN subquery on computed expressions")
        right_field = sub.schema.field(0).name
        right_key = ex.col(right_field)
        jt = "anti" if conj.negated else "semi"
        return lp.Join(plan, sub, [(left_key, right_key)], jt, None)

    # ------------------------------------------------------- decorrelation
    def _sub_from(self, sub_q: ast.Query) -> lp.LogicalPlan:
        if not sub_q.from_:
            raise SqlError("subquery requires a FROM clause")
        sub_plan = self._plan_table_ref(sub_q.from_[0])
        for ref in sub_q.from_[1:]:
            sub_plan = lp.CrossJoin(sub_plan, self._plan_table_ref(ref))
        return sub_plan

    def _classify_correlated(
        self,
        where: Optional[ast.SqlExpr],
        inner_schema: pa.Schema,
        outer_schema: pa.Schema,
    ) -> tuple[list[ast.SqlExpr], list[tuple[ex.Column, ex.Column]], list[ast.SqlExpr]]:
        """Split a subquery WHERE into (local conjuncts, correlated equality
        pairs as (outer_col, inner_col), residual correlated conjuncts).

        SQL scoping rule: a name binds to the innermost (subquery) scope
        first and only falls back to the outer scope if unresolved — hence
        the try-inner-first classification.  Counterpart of DataFusion's
        decorrelation rules the reference relies on upstream.
        """
        local: list[ast.SqlExpr] = []
        pairs: list[tuple[ex.Column, ex.Column]] = []
        residual: list[ast.SqlExpr] = []
        if where is None:
            return local, pairs, residual
        for c in _split_conjuncts(where):
            try:
                self._expr(c, inner_schema)
                local.append(c)
                continue
            except (PlanError, SqlError):
                pass
            pair = None
            if isinstance(c, ast.Binary) and c.op == "=":
                for a, b in ((c.left, c.right), (c.right, c.left)):
                    try:
                        ie = self._expr(a, inner_schema)
                        oe = self._expr(b, outer_schema)
                    except (PlanError, SqlError):
                        continue
                    if isinstance(ie, ex.Column) and isinstance(oe, ex.Column):
                        pair = (oe, ie)
                        break
            if pair is not None:
                pairs.append(pair)
            else:
                residual.append(c)
        return local, pairs, residual

    def _plan_exists(self, plan: lp.LogicalPlan, conj: ast.Exists) -> lp.LogicalPlan:
        """Correlated [NOT] EXISTS → semi/anti hash join (TPC-H q4/q21/q22).

        Correlated equalities become join keys; other correlated conjuncts
        (e.g. q21's ``l2.l_suppkey <> l1.l_suppkey``) become the join's
        residual filter, evaluated over the combined outer+inner row.
        """
        sub_q = conj.query
        sub_plan = self._sub_from(sub_q)
        inner_schema = sub_plan.schema
        outer_schema = plan.schema
        local, pairs, residual = self._classify_correlated(
            sub_q.where, inner_schema, outer_schema
        )
        if not pairs:
            raise NotImplementedYet(
                "EXISTS subquery without a correlated equality predicate"
            )
        local_pred = _conjoin([self._expr(c, inner_schema) for c in local])
        if local_pred is not None:
            sub_plan = lp.Filter(local_pred, sub_plan)
        joint = pa.schema(list(outer_schema) + list(inner_schema))
        res_pred = _conjoin([self._expr(c, joint) for c in residual])
        jt = "anti" if conj.negated else "semi"
        return lp.Join(plan, sub_plan, pairs, jt, res_pred)

    def _decorrelate_scalar(
        self, plan: lp.LogicalPlan, conj: ast.Binary
    ) -> tuple[lp.LogicalPlan, ex.Expr]:
        """Rewrite ``expr CMP (correlated scalar aggregate subquery)`` into a
        group-by-correlation-keys aggregate joined back to the outer plan
        (TPC-H q2/q17/q20).  Returns (joined plan, comparison filter expr).

        Empty groups: the spec scalar subquery yields NULL there and the
        comparison is then not-true — the inner join drops those rows, which
        is equivalent for a WHERE conjunct.
        """
        left_is_sub = isinstance(conj.left, ast.ScalarSubquery)
        sub_ast = conj.left if left_is_sub else conj.right
        other_ast = conj.right if left_is_sub else conj.left
        assert isinstance(sub_ast, ast.ScalarSubquery)
        sub_q = sub_ast.query
        if sub_q.group_by or len(sub_q.select) != 1:
            raise NotImplementedYet(
                "correlated scalar subquery must be a single ungrouped aggregate"
            )
        sub_plan = self._sub_from(sub_q)
        inner_schema = sub_plan.schema
        outer_schema = plan.schema
        local, pairs, residual = self._classify_correlated(
            sub_q.where, inner_schema, outer_schema
        )
        if residual:
            raise NotImplementedYet(
                "non-equality correlated predicate in scalar subquery"
            )
        if not pairs:
            raise PlanError("scalar subquery is not correlated; cannot decorrelate")
        local_pred = _conjoin([self._expr(c, inner_schema) for c in local])
        if local_pred is not None:
            sub_plan = lp.Filter(local_pred, sub_plan)

        val = self._expr(sub_q.select[0].expr, inner_schema)
        aggs = list(ex.find_aggregates(val))
        if not aggs:
            raise NotImplementedYet("correlated scalar subquery without aggregate")
        group_exprs: list[ex.Expr] = [inner for _, inner in pairs]
        agg_plan = lp.Aggregate(group_exprs, aggs, sub_plan)
        agg_schema = agg_plan.schema
        rewrite: dict[str, str] = {}
        for j, a in enumerate(aggs):
            rewrite[str(a)] = agg_schema.field(len(group_exprs) + j).name

        def _rw(node: ex.Expr) -> ex.Expr:
            key = str(node)
            if key in rewrite and not isinstance(node, ex.Column):
                return ex.col(rewrite[key])
            return node

        n = self._sq_counter
        self._sq_counter += 1
        proj_exprs: list[ex.Expr] = [
            ex.Alias(ex.col(agg_schema.field(i).name), f"__sq{n}_k{i}")
            for i in range(len(pairs))
        ]
        proj_exprs.append(ex.Alias(ex.transform(val, _rw), f"__sq{n}_v"))
        proj = lp.Projection(proj_exprs, agg_plan)

        on = [
            (outer, ex.col(f"__sq{n}_k{i}"))
            for i, (outer, _) in enumerate(pairs)
        ]
        joined = lp.Join(plan, proj, on, "inner", None)
        other = self._expr(other_ast, outer_schema)
        v = ex.col(f"__sq{n}_v")
        cmp_expr = (
            ex.BinaryExpr(v, conj.op, other)
            if left_is_sub
            else ex.BinaryExpr(other, conj.op, v)
        )
        return joined, cmp_expr

    # ---------------------------------------------------------- expressions
    def _window_expr(
        self,
        e: ast.FunctionCall,
        schema: pa.Schema,
        alias_map: Optional[dict[str, ex.Expr]] = None,
    ) -> ex.WindowExpr:
        fname = e.name
        if e.distinct:
            raise SqlError(f"DISTINCT is not supported in window {fname}")
        offset = 1
        if fname in ex.WINDOW_RANKING_FUNCTIONS:
            if fname == "ntile":
                if len(e.args) != 1 or not isinstance(e.args[0], ast.NumberLit):
                    raise SqlError("ntile takes one literal integer argument")
                try:
                    offset = int(e.args[0].value)
                except ValueError as err:
                    raise SqlError(
                        f"ntile bucket count must be an integer, "
                        f"got {e.args[0].value!r}"
                    ) from err
                if offset < 1:
                    raise SqlError("ntile bucket count must be >= 1")
            elif e.args:
                raise SqlError(f"{fname}() takes no arguments")
            if not e.over.order_by:
                raise SqlError(f"{fname}() requires ORDER BY in its window")
            arg = None
        elif fname in ex.WINDOW_VALUE_FUNCTIONS:
            if not e.over.order_by:
                raise SqlError(f"{fname}() requires ORDER BY in its window")
            max_args = 2 if fname in ("lag", "lead") else 1
            if not 1 <= len(e.args) <= max_args:
                raise SqlError(f"bad argument count for window {fname}")
            arg = self._expr(e.args[0], schema, alias_map)
            if len(e.args) == 2:
                if not isinstance(e.args[1], ast.NumberLit):
                    raise SqlError(f"{fname} offset must be a literal integer")
                try:
                    offset = int(e.args[1].value)
                except ValueError as err:
                    raise SqlError(
                        f"{fname} offset must be a literal integer, "
                        f"got {e.args[1].value!r}"
                    ) from err
        elif fname in ("sum", "avg", "min", "max", "count"):
            if fname == "count" and len(e.args) == 1 and isinstance(
                e.args[0], ast.Star
            ):
                arg = None
            elif len(e.args) == 1:
                arg = self._expr(e.args[0], schema, alias_map)
            else:
                raise SqlError(f"window {fname} takes one argument")
        else:
            raise SqlError(f"unsupported window function {fname}")
        partition_by = tuple(
            self._expr(p, schema, alias_map) for p in e.over.partition_by
        )
        order_by = tuple(
            ex.SortExpr(
                self._expr(oi.expr, schema, alias_map), oi.asc, oi.nulls_first
            )
            for oi in e.over.order_by
        )
        frame = None
        if e.over.frame is not None:
            if fname not in ("sum", "avg", "min", "max", "count"):
                raise SqlError(
                    f"a ROWS frame applies to aggregate windows, not {fname}"
                )
            if not order_by:
                raise SqlError("a ROWS frame requires ORDER BY in its window")
            f = e.over.frame
            if (
                f.start is not None
                and f.end is not None
                and f.start > f.end
            ):
                raise SqlError("ROWS frame start is after its end")
            frame = (f.start, f.end)
        return ex.WindowExpr(fname, arg, partition_by, order_by, offset, frame)

    def _expr(
        self,
        e: ast.SqlExpr,
        schema: pa.Schema,
        alias_map: Optional[dict[str, ex.Expr]] = None,
    ) -> ex.Expr:
        if isinstance(e, ast.ColumnRef):
            c = ex.Column(e.name, e.qualifier)
            try:
                c.resolve_index(schema)
                return c
            except PlanError:
                if alias_map and e.qualifier is None and e.name in alias_map:
                    a = alias_map[e.name]
                    return a.expr if isinstance(a, ex.Alias) else a
                raise
        if isinstance(e, ast.NumberLit):
            if "." in e.value or "e" in e.value.lower():
                return ex.lit(float(e.value))
            return ex.lit(int(e.value))
        if isinstance(e, ast.StringLit):
            return ex.lit(e.value)
        if isinstance(e, ast.BoolLit):
            return ex.lit(e.value)
        if isinstance(e, ast.NullLit):
            return ex.lit(None)
        if isinstance(e, ast.DateLit):
            try:
                return ex.lit(_dt.date.fromisoformat(e.value))
            except ValueError as err:
                raise SqlError(f"bad date literal {e.value!r}") from err
        if isinstance(e, ast.IntervalLit):
            amount = int(float(e.value))
            if e.unit in _INTERVAL_UNIT_MONTHS:
                return ex.IntervalLiteral(months=amount * _INTERVAL_UNIT_MONTHS[e.unit])
            if e.unit in _INTERVAL_UNIT_DAYS:
                return ex.IntervalLiteral(days=amount * _INTERVAL_UNIT_DAYS[e.unit])
            raise NotImplementedYet(f"interval unit {e.unit}")
        if isinstance(e, ast.Binary):
            if e.op in ("AND", "OR"):
                return ex.BinaryExpr(
                    self._expr(e.left, schema, alias_map),
                    e.op,
                    self._expr(e.right, schema, alias_map),
                )
            return ex.BinaryExpr(
                self._expr(e.left, schema, alias_map),
                e.op,
                self._expr(e.right, schema, alias_map),
            )
        if isinstance(e, ast.Unary):
            if e.op == "NOT":
                return ex.NotExpr(self._expr(e.operand, schema, alias_map))
            inner = self._expr(e.operand, schema, alias_map)
            if isinstance(inner, ex.Literal) and isinstance(inner.value, (int, float)):
                return ex.Literal(-inner.value, inner.dtype)
            return ex.NegativeExpr(inner)
        if isinstance(e, ast.IsNull):
            return ex.IsNullExpr(self._expr(e.operand, schema, alias_map), e.negated)
        if isinstance(e, ast.Between):
            return ex.BetweenExpr(
                self._expr(e.operand, schema, alias_map),
                self._expr(e.low, schema, alias_map),
                self._expr(e.high, schema, alias_map),
                e.negated,
            )
        if isinstance(e, ast.InList):
            return ex.InListExpr(
                self._expr(e.operand, schema, alias_map),
                tuple(self._expr(i, schema, alias_map) for i in e.items),
                e.negated,
            )
        if isinstance(e, ast.Like):
            return ex.LikeExpr(
                self._expr(e.operand, schema, alias_map),
                self._expr(e.pattern, schema, alias_map),
                e.negated,
            )
        if isinstance(e, ast.Case):
            return ex.CaseExpr(
                self._expr(e.operand, schema, alias_map) if e.operand else None,
                tuple(
                    (self._expr(w, schema, alias_map), self._expr(t, schema, alias_map))
                    for w, t in e.whens
                ),
                self._expr(e.else_expr, schema, alias_map) if e.else_expr else None,
            )
        if isinstance(e, ast.CastExpr):
            return ex.CastExpr(
                self._expr(e.operand, schema, alias_map), sql_type_to_arrow(e.type_name)
            )
        if isinstance(e, ast.Extract):
            return ex.ScalarFunction(
                "date_part",
                (ex.lit(e.field.lower()), self._expr(e.operand, schema, alias_map)),
            )
        if isinstance(e, ast.Substring):
            args = [
                self._expr(e.operand, schema, alias_map),
                self._expr(e.start, schema, alias_map),
            ]
            if e.length is not None:
                args.append(self._expr(e.length, schema, alias_map))
            return ex.ScalarFunction("substr", tuple(args))
        if isinstance(e, ast.FunctionCall):
            fname = e.name
            if e.over is not None:
                return self._window_expr(e, schema, alias_map)
            if fname == "count" and e.distinct:
                fname = "count_distinct"
            # synonyms → canonical names; a user-registered UDF/UDAF with
            # the synonym's name keeps precedence (it resolved before the
            # synonyms existed, and hijacking it silently would change
            # that query's answer)
            if self.udfs is None or (
                self.udfs.scalar(fname) is None
                and self.udfs.aggregate(fname) is None
            ):
                fname = {
                    "stddev_samp": "stddev", "std": "stddev",
                    "var_samp": "var", "variance": "var",
                    "pow": "power",
                }.get(fname, fname)
            if fname in ex.AGGREGATE_FUNCTIONS:
                if e.distinct and fname not in ("count_distinct", "min", "max"):
                    # DISTINCT would silently be ignored: refuse instead
                    # (min/max are distinct-invariant and pass through)
                    raise SqlError(f"DISTINCT is not supported for {fname}")
                if fname == "corr":
                    if len(e.args) != 2:
                        raise SqlError("corr takes two arguments")
                    return ex.AggregateExpr(
                        fname,
                        self._expr(e.args[0], schema, alias_map),
                        False,
                        arg2=self._expr(e.args[1], schema, alias_map),
                    )
                if len(e.args) == 1 and isinstance(e.args[0], ast.Star):
                    return ex.AggregateExpr(fname, None, e.distinct)
                if len(e.args) != 1:
                    raise SqlError(f"{fname} takes one argument")
                return ex.AggregateExpr(
                    fname, self._expr(e.args[0], schema, alias_map), e.distinct
                )
            if fname in ex.SCALAR_FUNCTIONS:
                return ex.ScalarFunction(
                    fname, tuple(self._expr(a, schema, alias_map) for a in e.args)
                )
            # user-defined functions, resolved from the session registry
            if self.udfs is not None:
                u = self.udfs.scalar(fname)
                if u is not None:
                    if len(e.args) != len(u.input_types):
                        raise SqlError(
                            f"UDF {fname} takes {len(u.input_types)} "
                            f"argument(s), got {len(e.args)}"
                        )
                    return ex.ScalarUDFExpr(
                        u.name,
                        tuple(self._expr(a, schema, alias_map) for a in e.args),
                        u.return_type,
                    )
                ua = self.udfs.aggregate(fname)
                if ua is not None:
                    if len(e.args) != 1:
                        raise SqlError(f"UDAF {fname} takes one argument")
                    if e.distinct:
                        raise NotImplementedYet(
                            f"DISTINCT is not supported for UDAF {fname}"
                        )
                    return ex.AggregateExpr(
                        f"udaf:{ua.name}",
                        self._expr(e.args[0], schema, alias_map),
                        False,
                        udaf_type=ua.return_type,
                    )
            raise SqlError(f"unknown function {fname!r}")
        if isinstance(e, ast.ScalarSubquery):
            sub = self.build_query(e.query)
            if len(sub.schema) != 1:
                raise SqlError("scalar subquery must return one column")
            return ex.ScalarSubqueryExpr(sub)
        if isinstance(e, ast.Exists):
            raise NotImplementedYet("EXISTS outside of top-level WHERE conjunct")
        if isinstance(e, ast.Star):
            raise SqlError("* not allowed here")
        raise PlanError(f"unhandled AST expression {e}")


def _column_in(c: ex.Column, schema: pa.Schema) -> bool:
    try:
        c.resolve_index(schema)
        return True
    except PlanError:
        return False
