"""AST → logical plan builder (name resolution & analysis).

Counterpart of DataFusion's SQL planner as used by the reference's
``BallistaContext::sql`` (``client/src/context.rs:346-460``).  Resolves table
names against the catalog, extracts aggregates out of SELECT/HAVING/ORDER BY,
decorrelates ``IN (subquery)`` into semi/anti joins, and plans uncorrelated
scalar subqueries as :class:`~..plan.expressions.ScalarSubqueryExpr`.
"""

from __future__ import annotations

import datetime as _dt
from typing import Optional

import pyarrow as pa

from ..catalog import Catalog
from ..errors import NotImplementedYet, PlanError, SqlError
from ..sql import ast
from . import expressions as ex
from . import logical as lp


def sql_type_to_arrow(name: str) -> pa.DataType:
    n = name.strip().upper()
    base = n.split("(")[0].strip()
    if base in ("INT", "INTEGER"):
        return pa.int32()
    if base in ("BIGINT", "LONG"):
        return pa.int64()
    if base == "SMALLINT":
        return pa.int16()
    if base == "TINYINT":
        return pa.int8()
    if base in ("FLOAT", "REAL"):
        return pa.float32()
    if base in ("DOUBLE", "DOUBLE PRECISION"):
        return pa.float64()
    if base in ("DECIMAL", "NUMERIC"):
        # decimals execute as float64 on the TPU path (MXU/VPU have no
        # decimal unit); precision-sensitive users can cast explicitly
        return pa.float64()
    if base in ("VARCHAR", "CHAR", "TEXT", "STRING"):
        return pa.string()
    if base in ("BOOLEAN", "BOOL"):
        return pa.bool_()
    if base == "DATE":
        return pa.date32()
    if base in ("TIMESTAMP", "DATETIME"):
        return pa.timestamp("us")
    raise SqlError(f"unsupported SQL type {name!r}")


_INTERVAL_UNIT_MONTHS = {"YEAR": 12, "MONTH": 1}
_INTERVAL_UNIT_DAYS = {"DAY": 1, "WEEK": 7}


def _split_conjuncts(e: ast.SqlExpr) -> list[ast.SqlExpr]:
    if isinstance(e, ast.Binary) and e.op == "AND":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _conjoin(exprs: list[ex.Expr]) -> Optional[ex.Expr]:
    if not exprs:
        return None
    out = exprs[0]
    for e in exprs[1:]:
        out = ex.BinaryExpr(out, "AND", e)
    return out


class PlanBuilder:
    def __init__(self, catalog: Catalog):
        self.catalog = catalog

    # ------------------------------------------------------------- queries
    def build_query(self, q: ast.Query) -> lp.LogicalPlan:
        # FROM
        if q.from_:
            plan = self._plan_table_ref(q.from_[0])
            for ref in q.from_[1:]:
                plan = lp.CrossJoin(plan, self._plan_table_ref(ref))
        else:
            plan = lp.EmptyRelation(produce_one_row=True)

        # WHERE — peel IN/EXISTS-subquery conjuncts into semi/anti joins
        if q.where is not None:
            plain: list[ex.Expr] = []
            for conj in _split_conjuncts(q.where):
                if isinstance(conj, ast.InSubquery):
                    plan = self._plan_in_subquery(plan, conj)
                elif isinstance(conj, ast.Exists):
                    raise NotImplementedYet(
                        "correlated EXISTS subqueries (TPC-H q4/q21/q22) not yet supported"
                    )
                else:
                    plain.append(self._expr(conj, plan.schema))
            pred = _conjoin(plain)
            if pred is not None:
                plan = lp.Filter(pred, plan)

        in_schema = plan.schema

        # SELECT list with * expansion
        select_exprs: list[ex.Expr] = []
        for item in q.select:
            if isinstance(item.expr, ast.Star):
                qual = item.expr.qualifier
                for f in in_schema:
                    parts = f.name.split(".")
                    if qual is None or (len(parts) == 2 and parts[0] == qual):
                        select_exprs.append(
                            ex.Column(parts[-1], parts[0] if len(parts) == 2 else None)
                        )
            else:
                e = self._expr(item.expr, in_schema)
                if item.alias:
                    e = ex.Alias(e, item.alias)
                select_exprs.append(e)

        alias_map = {e.name: e for e in select_exprs}

        # GROUP BY (supports ordinals and select aliases)
        group_exprs: list[ex.Expr] = []
        for g in q.group_by:
            if isinstance(g, ast.NumberLit):
                idx = int(g.value) - 1
                if idx < 0 or idx >= len(select_exprs):
                    raise SqlError(f"GROUP BY position {g.value} out of range")
                ge = select_exprs[idx]
                ge = ge.expr if isinstance(ge, ex.Alias) else ge
            else:
                ge = self._expr(g, in_schema, alias_map)
            group_exprs.append(ge)

        # aggregates appearing anywhere in select / having / order by
        agg_exprs: list[ex.AggregateExpr] = []

        def _collect(e: ex.Expr) -> None:
            for a in ex.find_aggregates(e):
                if not any(str(a) == str(b) for b in agg_exprs):
                    agg_exprs.append(a)

        for e in select_exprs:
            _collect(e)
        having_expr = (
            self._expr(q.having, in_schema, alias_map) if q.having is not None else None
        )
        if having_expr is not None:
            _collect(having_expr)
        order_exprs: list[ex.SortExpr] = []
        for oi in q.order_by:
            if isinstance(oi.expr, ast.NumberLit):
                idx = int(oi.expr.value) - 1
                if idx < 0 or idx >= len(select_exprs):
                    raise SqlError(f"ORDER BY position {oi.expr.value} out of range")
                base = select_exprs[idx]
                base = base.expr if isinstance(base, ex.Alias) else base
            else:
                base = self._expr(oi.expr, in_schema, alias_map)
            _collect(base)
            order_exprs.append(ex.SortExpr(base, oi.asc, oi.nulls_first))

        if group_exprs or agg_exprs:
            plan = lp.Aggregate(group_exprs, list(agg_exprs), plan)
            agg_schema = plan.schema

            # rewrite select/having/order exprs: aggregate and group-expr
            # occurrences become column refs into the aggregate output
            rewrite_map: dict[str, str] = {}
            for i, g in enumerate(group_exprs):
                rewrite_map[str(g)] = agg_schema.field(i).name
            for j, a in enumerate(agg_exprs):
                rewrite_map[str(a)] = agg_schema.field(len(group_exprs) + j).name

            def _rw(e: ex.Expr) -> ex.Expr:
                def fn(node: ex.Expr) -> ex.Expr:
                    key = str(node)
                    if key in rewrite_map and not isinstance(node, ex.Column):
                        return ex.col(rewrite_map[key])
                    return node

                return ex.transform(e, fn)

            select_exprs = [
                ex.Alias(_rw(e.expr), e.alias_name) if isinstance(e, ex.Alias) else _rw(e)
                for e in select_exprs
            ]
            # validate: non-aggregate select exprs must be grouping exprs
            for e in select_exprs:
                inner = e.expr if isinstance(e, ex.Alias) else e
                for c in ex.find_columns(inner):
                    try:
                        c.resolve_index(agg_schema)
                    except PlanError as err:
                        raise PlanError(
                            f"expression {e} is neither aggregated nor grouped"
                        ) from err
            if having_expr is not None:
                plan = lp.Filter(_rw(having_expr), plan)
            order_exprs = [
                ex.SortExpr(_rw(s.expr), s.asc, s.nulls_first) for s in order_exprs
            ]

        plan = lp.Projection(select_exprs, plan)

        if q.distinct:
            plan = lp.Distinct(plan)

        if order_exprs:
            # a top-k sort may keep at most limit+offset rows — the Limit
            # above still applies the skip
            topk = (q.limit + (q.offset or 0)) if q.limit is not None else None
            # resolve sort keys against projection output where possible;
            # otherwise extend the projection, sort, and re-project
            proj_schema = plan.schema
            missing: list[ex.Expr] = []
            resolved: list[ex.SortExpr] = []
            for s in order_exprs:
                try:
                    s.expr.data_type(proj_schema)
                    resolved.append(s)
                except PlanError:
                    missing.append(s.expr)
                    # downstream of the widened projection the computed sort
                    # key exists as a named column — reference it by name
                    resolved.append(ex.SortExpr(ex.col(s.expr.name), s.asc, s.nulls_first))
            if missing and isinstance(plan, lp.Projection):
                wide = lp.Projection(plan.exprs + missing, plan.input)
                keep = [f.name for f in proj_schema]
                plan = lp.Projection(
                    [ex.col(n) for n in keep], lp.Sort(resolved, wide, fetch=topk)
                )
            else:
                plan = lp.Sort(resolved, plan, fetch=topk)

        if q.limit is not None or q.offset is not None:
            plan = lp.Limit(plan, q.offset or 0, q.limit)
        return plan

    # ----------------------------------------------------------- table refs
    def _plan_table_ref(self, ref: ast.TableRef) -> lp.LogicalPlan:
        if isinstance(ref, ast.NamedTable):
            provider = self.catalog.get(ref.name)
            scan = lp.TableScan(ref.name, provider)
            if ref.alias and ref.alias != ref.name:
                return lp.SubqueryAlias(scan, ref.alias)
            return scan
        if isinstance(ref, ast.DerivedTable):
            sub = self.build_query(ref.query)
            return lp.SubqueryAlias(sub, ref.alias)
        if isinstance(ref, ast.JoinClause):
            left = self._plan_table_ref(ref.left)
            right = self._plan_table_ref(ref.right)
            if ref.kind == "CROSS":
                return lp.CrossJoin(left, right)
            schema = pa.schema(list(left.schema) + list(right.schema))
            on_pairs, residual = self._extract_equijoin(
                ref.on, left.schema, right.schema, schema
            )
            if not on_pairs:
                raise NotImplementedYet("non-equi joins require an equality condition")
            jt = ref.kind.lower()
            return lp.Join(left, right, on_pairs, jt, residual)
        raise PlanError(f"unhandled table ref {ref}")

    def _extract_equijoin(
        self,
        on: Optional[ast.SqlExpr],
        left_schema: pa.Schema,
        right_schema: pa.Schema,
        joint: pa.Schema,
    ) -> tuple[list[tuple[ex.Column, ex.Column]], Optional[ex.Expr]]:
        pairs: list[tuple[ex.Column, ex.Column]] = []
        residual: list[ex.Expr] = []
        if on is None:
            return pairs, None
        for conj in _split_conjuncts(on):
            done = False
            if isinstance(conj, ast.Binary) and conj.op == "=":
                l = self._expr(conj.left, joint)
                r = self._expr(conj.right, joint)
                if isinstance(l, ex.Column) and isinstance(r, ex.Column):
                    l_in_left = _column_in(l, left_schema)
                    r_in_left = _column_in(r, left_schema)
                    if l_in_left and not r_in_left:
                        pairs.append((l, r))
                        done = True
                    elif r_in_left and not l_in_left:
                        pairs.append((r, l))
                        done = True
            if not done:
                residual.append(self._expr(conj, joint))
        return pairs, _conjoin(residual)

    def _plan_in_subquery(
        self, plan: lp.LogicalPlan, conj: ast.InSubquery
    ) -> lp.LogicalPlan:
        sub = self.build_query(conj.query)
        if len(sub.schema) != 1:
            raise SqlError("IN subquery must return one column")
        left_key = self._expr(conj.operand, plan.schema)
        if not isinstance(left_key, ex.Column):
            raise NotImplementedYet("IN subquery on computed expressions")
        right_field = sub.schema.field(0).name
        right_key = ex.col(right_field)
        jt = "anti" if conj.negated else "semi"
        return lp.Join(plan, sub, [(left_key, right_key)], jt, None)

    # ---------------------------------------------------------- expressions
    def _expr(
        self,
        e: ast.SqlExpr,
        schema: pa.Schema,
        alias_map: Optional[dict[str, ex.Expr]] = None,
    ) -> ex.Expr:
        if isinstance(e, ast.ColumnRef):
            c = ex.Column(e.name, e.qualifier)
            try:
                c.resolve_index(schema)
                return c
            except PlanError:
                if alias_map and e.qualifier is None and e.name in alias_map:
                    a = alias_map[e.name]
                    return a.expr if isinstance(a, ex.Alias) else a
                raise
        if isinstance(e, ast.NumberLit):
            if "." in e.value or "e" in e.value.lower():
                return ex.lit(float(e.value))
            return ex.lit(int(e.value))
        if isinstance(e, ast.StringLit):
            return ex.lit(e.value)
        if isinstance(e, ast.BoolLit):
            return ex.lit(e.value)
        if isinstance(e, ast.NullLit):
            return ex.lit(None)
        if isinstance(e, ast.DateLit):
            try:
                return ex.lit(_dt.date.fromisoformat(e.value))
            except ValueError as err:
                raise SqlError(f"bad date literal {e.value!r}") from err
        if isinstance(e, ast.IntervalLit):
            amount = int(float(e.value))
            if e.unit in _INTERVAL_UNIT_MONTHS:
                return ex.IntervalLiteral(months=amount * _INTERVAL_UNIT_MONTHS[e.unit])
            if e.unit in _INTERVAL_UNIT_DAYS:
                return ex.IntervalLiteral(days=amount * _INTERVAL_UNIT_DAYS[e.unit])
            raise NotImplementedYet(f"interval unit {e.unit}")
        if isinstance(e, ast.Binary):
            if e.op in ("AND", "OR"):
                return ex.BinaryExpr(
                    self._expr(e.left, schema, alias_map),
                    e.op,
                    self._expr(e.right, schema, alias_map),
                )
            return ex.BinaryExpr(
                self._expr(e.left, schema, alias_map),
                e.op,
                self._expr(e.right, schema, alias_map),
            )
        if isinstance(e, ast.Unary):
            if e.op == "NOT":
                return ex.NotExpr(self._expr(e.operand, schema, alias_map))
            inner = self._expr(e.operand, schema, alias_map)
            if isinstance(inner, ex.Literal) and isinstance(inner.value, (int, float)):
                return ex.Literal(-inner.value, inner.dtype)
            return ex.NegativeExpr(inner)
        if isinstance(e, ast.IsNull):
            return ex.IsNullExpr(self._expr(e.operand, schema, alias_map), e.negated)
        if isinstance(e, ast.Between):
            return ex.BetweenExpr(
                self._expr(e.operand, schema, alias_map),
                self._expr(e.low, schema, alias_map),
                self._expr(e.high, schema, alias_map),
                e.negated,
            )
        if isinstance(e, ast.InList):
            return ex.InListExpr(
                self._expr(e.operand, schema, alias_map),
                tuple(self._expr(i, schema, alias_map) for i in e.items),
                e.negated,
            )
        if isinstance(e, ast.Like):
            return ex.LikeExpr(
                self._expr(e.operand, schema, alias_map),
                self._expr(e.pattern, schema, alias_map),
                e.negated,
            )
        if isinstance(e, ast.Case):
            return ex.CaseExpr(
                self._expr(e.operand, schema, alias_map) if e.operand else None,
                tuple(
                    (self._expr(w, schema, alias_map), self._expr(t, schema, alias_map))
                    for w, t in e.whens
                ),
                self._expr(e.else_expr, schema, alias_map) if e.else_expr else None,
            )
        if isinstance(e, ast.CastExpr):
            return ex.CastExpr(
                self._expr(e.operand, schema, alias_map), sql_type_to_arrow(e.type_name)
            )
        if isinstance(e, ast.Extract):
            return ex.ScalarFunction(
                "date_part",
                (ex.lit(e.field.lower()), self._expr(e.operand, schema, alias_map)),
            )
        if isinstance(e, ast.Substring):
            args = [
                self._expr(e.operand, schema, alias_map),
                self._expr(e.start, schema, alias_map),
            ]
            if e.length is not None:
                args.append(self._expr(e.length, schema, alias_map))
            return ex.ScalarFunction("substr", tuple(args))
        if isinstance(e, ast.FunctionCall):
            fname = e.name
            if fname == "count" and e.distinct:
                fname = "count_distinct"
            if fname in ex.AGGREGATE_FUNCTIONS:
                if len(e.args) == 1 and isinstance(e.args[0], ast.Star):
                    return ex.AggregateExpr(fname, None, e.distinct)
                if len(e.args) != 1:
                    raise SqlError(f"{fname} takes one argument")
                return ex.AggregateExpr(
                    fname, self._expr(e.args[0], schema, alias_map), e.distinct
                )
            if fname in ex.SCALAR_FUNCTIONS:
                return ex.ScalarFunction(
                    fname, tuple(self._expr(a, schema, alias_map) for a in e.args)
                )
            raise SqlError(f"unknown function {fname!r}")
        if isinstance(e, ast.ScalarSubquery):
            sub = self.build_query(e.query)
            if len(sub.schema) != 1:
                raise SqlError("scalar subquery must return one column")
            return ex.ScalarSubqueryExpr(sub)
        if isinstance(e, ast.Exists):
            raise NotImplementedYet("EXISTS outside of top-level WHERE conjunct")
        if isinstance(e, ast.Star):
            raise SqlError("* not allowed here")
        raise PlanError(f"unhandled AST expression {e}")


def _column_in(c: ex.Column, schema: pa.Schema) -> bool:
    try:
        c.resolve_index(schema)
        return True
    except PlanError:
        return False
