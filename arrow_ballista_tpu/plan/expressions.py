"""Logical expression IR.

Counterpart of DataFusion's ``Expr`` as serialized by the reference's
``core/proto/datafusion.proto`` (LogicalExprNode) — redesigned as Python
dataclasses with pyarrow-based type inference.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Any, Optional

import pyarrow as pa

from ..errors import PlanError

# ------------------------------------------------------------------ operators
COMPARISON_OPS = {"=", "<>", "<", "<=", ">", ">="}
ARITHMETIC_OPS = {"+", "-", "*", "/", "%"}
BOOLEAN_OPS = {"AND", "OR"}

AGGREGATE_FUNCTIONS = {
    "sum", "avg", "min", "max", "count", "count_distinct",
    # single-stage statistical aggregates (each group wholly in one
    # partition, like count_distinct): exact median, sample/population
    # stddev + variance, Pearson correlation (two arguments)
    "median", "stddev", "stddev_pop", "var", "var_pop", "corr",
}

SCALAR_FUNCTIONS = {
    # math
    "abs", "ceil", "floor", "round", "sqrt", "exp", "ln", "log10", "log2",
    "power", "sin", "cos", "tan", "signum",
    # string
    "lower", "upper", "trim", "ltrim", "rtrim", "length", "char_length",
    "substr", "substring", "concat", "replace", "starts_with", "strpos",
    "left", "right", "repeat", "reverse", "ascii", "lpad", "rpad", "btrim",
    "initcap", "split_part", "translate", "to_hex", "md5", "sha256",
    # temporal
    "date_part", "date_trunc", "extract", "to_timestamp", "now",
    # conditional / misc
    "coalesce", "nullif", "random",
}


def _is_numeric(t: pa.DataType) -> bool:
    return (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_decimal(t)
    )


def coerce_types(lt: pa.DataType, rt: pa.DataType, op: str) -> pa.DataType:
    """Binary-op result/coercion type (simplified DataFusion coercion rules)."""
    if lt.equals(rt):
        return lt
    if pa.types.is_null(lt):
        return rt
    if pa.types.is_null(rt):
        return lt
    # date arithmetic with intervals handled by the caller
    if _is_numeric(lt) and _is_numeric(rt):
        if pa.types.is_decimal(lt) or pa.types.is_decimal(rt):
            return pa.float64()
        if pa.types.is_floating(lt) or pa.types.is_floating(rt):
            return pa.float64() if (lt.bit_width == 64 or rt.bit_width == 64) else pa.float32()
        # both ints
        return pa.int64()
    if (pa.types.is_date(lt) and pa.types.is_string(rt)) or (
        pa.types.is_string(lt) and pa.types.is_date(rt)
    ):
        return pa.date32()
    if pa.types.is_string(lt) and pa.types.is_string(rt):
        return pa.string()
    if pa.types.is_boolean(lt) and pa.types.is_boolean(rt):
        return pa.bool_()
    if pa.types.is_timestamp(lt) or pa.types.is_timestamp(rt):
        return pa.timestamp("us")
    raise PlanError(f"cannot coerce {lt} {op} {rt}")


class Expr:
    """Base logical expression."""

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        raise NotImplementedError

    def nullable(self, schema: pa.Schema) -> bool:
        return True

    @property
    def name(self) -> str:
        """Output column name when this expr lands in a projection."""
        return str(self)

    def children(self) -> list["Expr"]:
        return []

    # Convenience builders (DataFrame API surface)
    def __eq__(self, other: object):  # type: ignore[override]
        if isinstance(other, Expr) or not isinstance(other, (str, bytes)):
            return BinaryExpr(self, "=", _lit_or_expr(other))
        return NotImplemented

    def __hash__(self) -> int:
        return hash(str(self))

    def __lt__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "<", _lit_or_expr(other))

    def __le__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "<=", _lit_or_expr(other))

    def __gt__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, ">", _lit_or_expr(other))

    def __ge__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, ">=", _lit_or_expr(other))

    def __add__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "+", _lit_or_expr(other))

    def __sub__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "-", _lit_or_expr(other))

    def __mul__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "*", _lit_or_expr(other))

    def __truediv__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "/", _lit_or_expr(other))

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def neq(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "<>", _lit_or_expr(other))

    def and_(self, other: "Expr") -> "BinaryExpr":
        return BinaryExpr(self, "AND", other)

    def or_(self, other: "Expr") -> "BinaryExpr":
        return BinaryExpr(self, "OR", other)

    def is_null(self) -> "IsNullExpr":
        return IsNullExpr(self, False)

    def sort(self, asc: bool = True, nulls_first: Optional[bool] = None) -> "SortExpr":
        return SortExpr(self, asc, nulls_first)


def _lit_or_expr(v: Any) -> Expr:
    return v if isinstance(v, Expr) else lit(v)


@dataclass(frozen=True, eq=False)
class Column(Expr):
    """A resolved column reference, optionally relation-qualified."""

    cname: str
    qualifier: Optional[str] = None

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return schema.field(self.resolve_index(schema)).type

    def nullable(self, schema: pa.Schema) -> bool:
        return schema.field(self.resolve_index(schema)).nullable

    def resolve_index(self, schema: pa.Schema) -> int:
        flat = self.flat_name
        idx = schema.get_field_index(flat)
        if idx >= 0:
            return idx
        if self.qualifier is not None:
            # a qualified ref may bind to an exactly-named unqualified field
            # (e.g. aggregate/projection output), but never suffix-match a
            # field carrying a DIFFERENT qualifier
            idx = schema.get_field_index(self.cname)
            if idx >= 0 and "." not in schema.field(idx).name:
                return idx
            raise PlanError(f"column {flat!r} not found in {schema.names}")
        # unqualified reference: qualified schema fields match on suffix
        matches = [
            i
            for i, f in enumerate(schema)
            if f.name.split(".")[-1] == self.cname
        ]
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            raise PlanError(f"ambiguous column {self.flat_name!r} in {schema.names}")
        raise PlanError(f"column {self.flat_name!r} not found in {schema.names}")

    @property
    def flat_name(self) -> str:
        return f"{self.qualifier}.{self.cname}" if self.qualifier else self.cname

    @property
    def name(self) -> str:
        return self.cname

    def __str__(self) -> str:
        return self.flat_name


@dataclass(frozen=True, eq=False)
class Literal(Expr):
    value: Any
    dtype: pa.DataType = field(default_factory=pa.null)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.dtype

    def nullable(self, schema: pa.Schema) -> bool:
        return self.value is None

    def __str__(self) -> str:
        if isinstance(self.value, str):
            return f"'{self.value}'"
        return str(self.value)


def lit(v: Any) -> Literal:
    if v is None:
        return Literal(None, pa.null())
    if isinstance(v, bool):
        return Literal(v, pa.bool_())
    if isinstance(v, int):
        return Literal(v, pa.int64())
    if isinstance(v, float):
        return Literal(v, pa.float64())
    if isinstance(v, str):
        return Literal(v, pa.string())
    if isinstance(v, _dt.date):
        return Literal(v, pa.date32())
    if isinstance(v, _dt.datetime):
        return Literal(v, pa.timestamp("us"))
    raise PlanError(f"unsupported literal {v!r}")


@dataclass(frozen=True, eq=False)
class IntervalLiteral(Expr):
    """Calendar interval; kept symbolic so date arithmetic stays exact."""

    months: int = 0
    days: int = 0

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.month_day_nano_interval()

    def __str__(self) -> str:
        return f"INTERVAL {self.months} MONTH {self.days} DAY"


@dataclass(frozen=True, eq=False)
class Alias(Expr):
    expr: Expr
    alias_name: str

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.expr.data_type(schema)

    def nullable(self, schema: pa.Schema) -> bool:
        return self.expr.nullable(schema)

    @property
    def name(self) -> str:
        return self.alias_name

    def children(self) -> list[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"{self.expr} AS {self.alias_name}"


@dataclass(frozen=True, eq=False)
class BinaryExpr(Expr):
    left: Expr
    op: str
    right: Expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        if self.op in COMPARISON_OPS or self.op in BOOLEAN_OPS:
            return pa.bool_()
        lt = self.left.data_type(schema)
        rt = self.right.data_type(schema)
        # date ± interval
        if pa.types.is_date(lt) and isinstance(self.right, IntervalLiteral):
            return lt
        if self.op == "||":
            return pa.string()
        return coerce_types(lt, rt, self.op)

    def children(self) -> list[Expr]:
        return [self.left, self.right]

    def __str__(self) -> str:
        return f"({self.left} {self.op} {self.right})"


@dataclass(frozen=True, eq=False)
class NotExpr(Expr):
    expr: Expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> list[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"NOT {self.expr}"


@dataclass(frozen=True, eq=False)
class NegativeExpr(Expr):
    expr: Expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.expr.data_type(schema)

    def children(self) -> list[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"(- {self.expr})"


@dataclass(frozen=True, eq=False)
class IsNullExpr(Expr):
    expr: Expr
    negated: bool = False

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def nullable(self, schema: pa.Schema) -> bool:
        return False

    def children(self) -> list[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"{self.expr} IS {'NOT ' if self.negated else ''}NULL"


@dataclass(frozen=True, eq=False)
class BetweenExpr(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> list[Expr]:
        return [self.expr, self.low, self.high]

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.expr} {neg}BETWEEN {self.low} AND {self.high}"


@dataclass(frozen=True, eq=False)
class InListExpr(Expr):
    expr: Expr
    items: tuple[Expr, ...] = ()
    negated: bool = False

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> list[Expr]:
        return [self.expr, *self.items]

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.expr} {neg}IN ({', '.join(map(str, self.items))})"


@dataclass(frozen=True, eq=False)
class LikeExpr(Expr):
    expr: Expr
    pattern: Expr
    negated: bool = False

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> list[Expr]:
        return [self.expr, self.pattern]

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.expr} {neg}LIKE {self.pattern}"


@dataclass(frozen=True, eq=False)
class CaseExpr(Expr):
    operand: Optional[Expr]
    whens: tuple[tuple[Expr, Expr], ...]
    else_expr: Optional[Expr]

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        for _, then in self.whens:
            t = then.data_type(schema)
            if not pa.types.is_null(t):
                return t
        if self.else_expr is not None:
            return self.else_expr.data_type(schema)
        return pa.null()

    def children(self) -> list[Expr]:
        out: list[Expr] = []
        if self.operand:
            out.append(self.operand)
        for w, t in self.whens:
            out.extend([w, t])
        if self.else_expr:
            out.append(self.else_expr)
        return out

    def __str__(self) -> str:
        parts = ["CASE"]
        if self.operand:
            parts.append(str(self.operand))
        for w, t in self.whens:
            parts.append(f"WHEN {w} THEN {t}")
        if self.else_expr:
            parts.append(f"ELSE {self.else_expr}")
        parts.append("END")
        return " ".join(parts)


@dataclass(frozen=True, eq=False)
class CastExpr(Expr):
    expr: Expr
    to_type: pa.DataType = field(default_factory=pa.float64)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.to_type

    def children(self) -> list[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"CAST({self.expr} AS {self.to_type})"


@dataclass(frozen=True, eq=False)
class ScalarFunction(Expr):
    fname: str
    args: tuple[Expr, ...] = ()

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        f = self.fname
        if f in {"length", "char_length", "strpos", "ascii"}:
            return pa.int64()
        if f in {"lower", "upper", "trim", "ltrim", "rtrim", "substr", "substring",
                 "concat", "replace", "left", "right", "repeat", "reverse",
                 "lpad", "rpad", "btrim", "initcap", "split_part", "translate",
                 "to_hex", "md5", "sha256"}:
            return pa.string()
        if f == "starts_with":
            return pa.bool_()
        if f in {"date_part", "extract"}:
            return pa.int64()
        if f == "date_trunc":
            unit = self.args[0].value if isinstance(self.args[0], Literal) else None
            if unit in ("day", "week", "month", "quarter", "year"):
                return pa.date32()
            return pa.timestamp("us")
        if f in {"to_timestamp", "now"}:
            return pa.timestamp("us")
        if f in {"coalesce", "nullif"}:
            return self.args[0].data_type(schema)
        if f in {"abs", "signum"}:
            return self.args[0].data_type(schema)
        if f in {"ceil", "floor", "round"}:
            return pa.float64()
        return pa.float64()

    def children(self) -> list[Expr]:
        return list(self.args)

    def __str__(self) -> str:
        return f"{self.fname}({', '.join(map(str, self.args))})"


@dataclass(frozen=True, eq=False)
class ScalarUDFExpr(Expr):
    """A user scalar function call, resolved by NAME from the UDF registry
    (reference: ScalarUDF shipped as UdfNode, code loaded via plugin)."""

    fname: str
    args: tuple = ()
    return_type: pa.DataType = field(default_factory=pa.float64)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.return_type

    def nullable(self, schema: pa.Schema) -> bool:
        return True

    def children(self) -> list["Expr"]:
        return list(self.args)

    @property
    def name(self) -> str:
        return self.fname

    def __str__(self) -> str:
        return f"{self.fname}({', '.join(str(a) for a in self.args)})"


STAT_AGGREGATES = {"median", "stddev", "stddev_pop", "var", "var_pop", "corr"}


@dataclass(frozen=True, eq=False)
class AggregateExpr(Expr):
    func: str  # sum | avg | min | max | count | count_distinct | median
    #            | stddev | stddev_pop | var | var_pop | corr | udaf:<name>
    arg: Optional[Expr]  # None for COUNT(*)
    distinct: bool = False
    # UDAF return type, captured at build time and shipped over the wire so
    # a scheduler that has not registered the UDAF can still plan the job
    udaf_type: Optional[pa.DataType] = None
    arg2: Optional[Expr] = None  # corr's second argument

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        if self.func.startswith("udaf:"):
            if self.udaf_type is not None:
                return self.udaf_type
            from ..udf import global_registry

            u = global_registry().aggregate(self.func[5:])
            if u is None:
                raise PlanError(f"UDAF {self.func[5:]!r} not registered")
            return u.return_type
        if self.func.startswith("count"):
            return pa.int64()
        if self.func == "avg" or self.func in STAT_AGGREGATES:
            return pa.float64()
        assert self.arg is not None
        t = self.arg.data_type(schema)
        if self.func == "sum":
            if pa.types.is_integer(t):
                return pa.int64()
            return pa.float64()
        return t  # min/max keep input type

    def children(self) -> list[Expr]:
        out = [self.arg] if self.arg is not None else []
        if self.arg2 is not None:
            out.append(self.arg2)
        return out

    def __str__(self) -> str:
        inner = "*" if self.arg is None else str(self.arg)
        if self.distinct:
            inner = f"DISTINCT {inner}"
        if self.arg2 is not None:
            inner = f"{inner}, {self.arg2}"
        fname = "count" if self.func == "count_distinct" else self.func
        return f"{fname}({inner})"


# ranking window functions (the aggregate set also works over windows);
# ntile(k) carries its bucket count in WindowExpr.offset
WINDOW_RANKING_FUNCTIONS = {"row_number", "rank", "dense_rank", "ntile"}
# value window functions: argument-typed, ORDER BY required
WINDOW_VALUE_FUNCTIONS = {"lag", "lead", "first_value", "last_value"}


@dataclass(frozen=True, eq=False)
class WindowExpr(Expr):
    """``func(...) OVER (PARTITION BY ... ORDER BY ...)``.

    Reference parity note: DataFusion's single-node engine evaluates
    window functions; Ballista's distributed planner raises
    NotImplemented for WindowAggExec (``planner.rs`` WindowAggExec arm).
    Here the physical planner repartitions on the PARTITION BY keys so
    windows also run distributed — each hash partition holds whole
    window partitions.

    Semantics: ranking functions need ORDER BY; aggregate functions
    without ORDER BY cover the whole partition, with ORDER BY they are
    running aggregates over the default frame (RANGE UNBOUNDED PRECEDING
    — peer rows share the value).
    """

    func: str  # row_number | rank | dense_rank | ntile | lag | lead
    #            | first_value | last_value | sum | avg | min | max | count
    arg: Optional["Expr"]  # None for ranking functions and count(*)
    partition_by: tuple = ()
    order_by: tuple = ()  # of SortExpr
    offset: int = 1  # lag/lead distance; ntile bucket count
    # explicit ROWS frame as (start, end) row offsets relative to the
    # current row (negative = preceding, None = unbounded); None = the
    # default RANGE frame
    frame: Optional[tuple] = None

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        if self.func in WINDOW_RANKING_FUNCTIONS or self.func.startswith(
            "count"
        ):
            return pa.int64()
        if self.func == "avg":
            return pa.float64()
        assert self.arg is not None
        t = self.arg.data_type(schema)
        if self.func == "sum":
            return pa.int64() if pa.types.is_integer(t) else pa.float64()
        return t  # min/max and the value functions keep the input type

    def children(self) -> list["Expr"]:
        out = [self.arg] if self.arg is not None else []
        out.extend(self.partition_by)
        out.extend(s.expr for s in self.order_by)
        return out

    def __str__(self) -> str:
        inner = "*" if self.arg is None else str(self.arg)
        if self.func in WINDOW_RANKING_FUNCTIONS:
            # ntile's bucket count must stay visible: the builder dedups
            # window exprs BY THIS STRING, so ntile(2) and ntile(3) over
            # the same window must not collapse into one column
            inner = str(self.offset) if self.func == "ntile" else ""
        if self.func in ("lag", "lead"):
            inner = f"{inner}, {self.offset}"
        parts = []
        if self.partition_by:
            parts.append(
                "PARTITION BY " + ", ".join(str(p) for p in self.partition_by)
            )
        if self.order_by:
            parts.append(
                "ORDER BY " + ", ".join(str(s) for s in self.order_by)
            )
        if self.frame is not None:
            # part of the dedup identity: same window, different frame
            # must stay a different column

            def b(v, side):
                if v is None:
                    return f"UNBOUNDED {side}"
                if v == 0:
                    return "CURRENT ROW"
                return f"{-v} PRECEDING" if v < 0 else f"{v} FOLLOWING"

            parts.append(
                f"ROWS BETWEEN {b(self.frame[0], 'PRECEDING')} "
                f"AND {b(self.frame[1], 'FOLLOWING')}"
            )
        return f"{self.func}({inner}) OVER ({' '.join(parts)})"


@dataclass(frozen=True, eq=False)
class SortExpr(Expr):
    expr: Expr
    asc: bool = True
    nulls_first: Optional[bool] = None

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.expr.data_type(schema)

    def children(self) -> list[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        s = f"{self.expr} {'ASC' if self.asc else 'DESC'}"
        if self.nulls_first is not None:
            s += " NULLS FIRST" if self.nulls_first else " NULLS LAST"
        return s


@dataclass(frozen=True, eq=False)
class ScalarSubqueryExpr(Expr):
    """Uncorrelated scalar subquery; replaced by a Literal by the optimizer."""

    plan: Any  # LogicalPlan (deferred import)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.plan.schema.field(0).type

    def __str__(self) -> str:
        return "(<scalar subquery>)"


def col(name: str) -> Column:
    if "." in name:
        q, c = name.rsplit(".", 1)
        return Column(c, q)
    return Column(name)


# ------------------------------------------------------------- tree walking
def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def find_columns(e: Expr) -> list[Column]:
    return [x for x in walk(e) if isinstance(x, Column)]


def find_aggregates(e: Expr) -> list[AggregateExpr]:
    # note: a windowed aggregate (sum(x) OVER (...)) is a WindowExpr with
    # func="sum", never a wrapped AggregateExpr — so any AggregateExpr
    # found inside a window's arg/partition/order refers to the enclosing
    # GROUP BY level and is correctly collected here
    return [x for x in walk(e) if isinstance(x, AggregateExpr)]


def find_windows(e: Expr) -> list[WindowExpr]:
    return [x for x in walk(e) if isinstance(x, WindowExpr)]


def transform(e: Expr, fn) -> Expr:
    """Bottom-up expression rewrite."""
    if isinstance(e, Alias):
        e2: Expr = Alias(transform(e.expr, fn), e.alias_name)
    elif isinstance(e, BinaryExpr):
        e2 = BinaryExpr(transform(e.left, fn), e.op, transform(e.right, fn))
    elif isinstance(e, NotExpr):
        e2 = NotExpr(transform(e.expr, fn))
    elif isinstance(e, NegativeExpr):
        e2 = NegativeExpr(transform(e.expr, fn))
    elif isinstance(e, IsNullExpr):
        e2 = IsNullExpr(transform(e.expr, fn), e.negated)
    elif isinstance(e, BetweenExpr):
        e2 = BetweenExpr(
            transform(e.expr, fn), transform(e.low, fn), transform(e.high, fn), e.negated
        )
    elif isinstance(e, InListExpr):
        e2 = InListExpr(
            transform(e.expr, fn), tuple(transform(i, fn) for i in e.items), e.negated
        )
    elif isinstance(e, LikeExpr):
        e2 = LikeExpr(transform(e.expr, fn), transform(e.pattern, fn), e.negated)
    elif isinstance(e, CaseExpr):
        e2 = CaseExpr(
            transform(e.operand, fn) if e.operand else None,
            tuple((transform(w, fn), transform(t, fn)) for w, t in e.whens),
            transform(e.else_expr, fn) if e.else_expr else None,
        )
    elif isinstance(e, CastExpr):
        e2 = CastExpr(transform(e.expr, fn), e.to_type)
    elif isinstance(e, ScalarFunction):
        e2 = ScalarFunction(e.fname, tuple(transform(a, fn) for a in e.args))
    elif isinstance(e, ScalarUDFExpr):
        e2 = ScalarUDFExpr(
            e.fname, tuple(transform(a, fn) for a in e.args), e.return_type
        )
    elif isinstance(e, AggregateExpr):
        e2 = AggregateExpr(
            e.func,
            transform(e.arg, fn) if e.arg is not None else None,
            e.distinct,
            udaf_type=e.udaf_type,
            arg2=transform(e.arg2, fn) if e.arg2 is not None else None,
        )
    elif isinstance(e, WindowExpr):
        e2 = WindowExpr(
            e.func,
            transform(e.arg, fn) if e.arg is not None else None,
            tuple(transform(p, fn) for p in e.partition_by),
            tuple(
                SortExpr(transform(s.expr, fn), s.asc, s.nulls_first)
                for s in e.order_by
            ),
            e.offset,
            e.frame,
        )
    elif isinstance(e, SortExpr):
        e2 = SortExpr(transform(e.expr, fn), e.asc, e.nulls_first)
    else:
        e2 = e
    return fn(e2)
