"""Logical plan nodes.

Counterpart of DataFusion's ``LogicalPlan`` as carried over the wire by the
reference (``core/proto/datafusion.proto`` LogicalPlanNode).  Schemas are
``pyarrow.Schema``; field names carry relation qualifiers as ``"rel.col"``
flat names, mirroring DataFusion's ``DFSchema`` qualified fields.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import TYPE_CHECKING, Any, Optional

import pyarrow as pa

from ..errors import PlanError
from . import expressions as ex

if TYPE_CHECKING:
    from ..catalog import TableProvider


class LogicalPlan:
    @property
    def schema(self) -> pa.Schema:
        raise NotImplementedError

    def children(self) -> list["LogicalPlan"]:
        return []

    def display(self, indent: int = 0) -> str:
        out = "  " * indent + str(self)
        for c in self.children():
            out += "\n" + c.display(indent + 1)
        return out


def _qualify(schema: pa.Schema, qualifier: str) -> pa.Schema:
    return pa.schema(
        [
            pa.field(f"{qualifier}.{f.name.split('.')[-1]}", f.type, f.nullable)
            for f in schema
        ]
    )


@dataclass
class TableScan(LogicalPlan):
    table_name: str
    provider: "TableProvider"
    projection: Optional[list[str]] = None  # column names (unqualified)
    filters: list[ex.Expr] = dc_field(default_factory=list)  # pushed-down

    @property
    def schema(self) -> pa.Schema:
        base = self.provider.schema
        if self.projection is not None:
            base = pa.schema([base.field(n) for n in self.projection])
        return _qualify(base, self.table_name)

    def __str__(self) -> str:
        proj = f" projection={self.projection}" if self.projection is not None else ""
        filt = f" filters={[str(f) for f in self.filters]}" if self.filters else ""
        return f"TableScan: {self.table_name}{proj}{filt}"


@dataclass
class SubqueryAlias(LogicalPlan):
    input: LogicalPlan
    alias: str

    @property
    def schema(self) -> pa.Schema:
        return _qualify(self.input.schema, self.alias)

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        return f"SubqueryAlias: {self.alias}"


@dataclass
class Projection(LogicalPlan):
    exprs: list[ex.Expr]
    input: LogicalPlan

    @property
    def schema(self) -> pa.Schema:
        in_schema = self.input.schema
        return pa.schema(
            [
                pa.field(e.name, e.data_type(in_schema), e.nullable(in_schema))
                for e in self.exprs
            ]
        )

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        return f"Projection: {', '.join(str(e) for e in self.exprs)}"


@dataclass
class Filter(LogicalPlan):
    predicate: ex.Expr
    input: LogicalPlan

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        return f"Filter: {self.predicate}"


@dataclass
class Aggregate(LogicalPlan):
    group_exprs: list[ex.Expr]
    agg_exprs: list[ex.Expr]  # AggregateExpr possibly wrapped in Alias
    input: LogicalPlan

    @property
    def schema(self) -> pa.Schema:
        in_schema = self.input.schema
        fields = [
            pa.field(e.name, e.data_type(in_schema), True) for e in self.group_exprs
        ]
        fields += [
            pa.field(e.name, e.data_type(in_schema), True) for e in self.agg_exprs
        ]
        return pa.schema(fields)

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        g = ", ".join(str(e) for e in self.group_exprs)
        a = ", ".join(str(e) for e in self.agg_exprs)
        return f"Aggregate: groupBy=[{g}], aggr=[{a}]"


@dataclass
class Window(LogicalPlan):
    """Window evaluation: output = input columns + one column per
    window expression (in original row order — windows do not reorder)."""

    window_exprs: list[ex.WindowExpr]
    input: LogicalPlan

    @property
    def schema(self) -> pa.Schema:
        in_schema = self.input.schema
        fields = list(in_schema)
        fields += [
            pa.field(str(w), w.data_type(in_schema), True)
            for w in self.window_exprs
        ]
        return pa.schema(fields)

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        return (
            "Window: "
            + ", ".join(str(w) for w in self.window_exprs)
        )


@dataclass
class Sort(LogicalPlan):
    sort_exprs: list[ex.SortExpr]
    input: LogicalPlan
    fetch: Optional[int] = None

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        return f"Sort: {', '.join(str(e) for e in self.sort_exprs)}"


@dataclass
class Limit(LogicalPlan):
    input: LogicalPlan
    skip: int = 0
    fetch: Optional[int] = None

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        return f"Limit: skip={self.skip}, fetch={self.fetch}"


JOIN_TYPES = {"inner", "left", "right", "full", "semi", "anti"}


@dataclass
class Join(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    on: list[tuple[ex.Column, ex.Column]]  # equijoin keys (left, right)
    join_type: str = "inner"
    filter: Optional[ex.Expr] = None  # extra non-equi condition

    def __post_init__(self) -> None:
        if self.join_type not in JOIN_TYPES:
            raise PlanError(f"unsupported join type {self.join_type}")

    @property
    def schema(self) -> pa.Schema:
        if self.join_type in ("semi", "anti"):
            return self.left.schema
        lf = list(self.left.schema)
        rf = list(self.right.schema)
        if self.join_type in ("left", "full"):
            rf = [f.with_nullable(True) for f in rf]
        if self.join_type in ("right", "full"):
            lf = [f.with_nullable(True) for f in lf]
        return pa.schema(lf + rf)

    def children(self) -> list[LogicalPlan]:
        return [self.left, self.right]

    def __str__(self) -> str:
        on = ", ".join(f"{l} = {r}" for l, r in self.on)
        f = f" filter={self.filter}" if self.filter is not None else ""
        return f"Join({self.join_type}): on=[{on}]{f}"


@dataclass
class CrossJoin(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan

    @property
    def schema(self) -> pa.Schema:
        return pa.schema(list(self.left.schema) + list(self.right.schema))

    def children(self) -> list[LogicalPlan]:
        return [self.left, self.right]

    def __str__(self) -> str:
        return "CrossJoin"


@dataclass
class Union(LogicalPlan):
    inputs: list[LogicalPlan]

    @property
    def schema(self) -> pa.Schema:
        return self.inputs[0].schema

    def children(self) -> list[LogicalPlan]:
        return list(self.inputs)

    def __str__(self) -> str:
        return "Union"


@dataclass
class Distinct(LogicalPlan):
    input: LogicalPlan

    @property
    def schema(self) -> pa.Schema:
        return self.input.schema

    def children(self) -> list[LogicalPlan]:
        return [self.input]

    def __str__(self) -> str:
        return "Distinct"


@dataclass
class EmptyRelation(LogicalPlan):
    produce_one_row: bool = False
    schema_: pa.Schema = dc_field(default_factory=lambda: pa.schema([]))

    @property
    def schema(self) -> pa.Schema:
        return self.schema_

    def __str__(self) -> str:
        return f"EmptyRelation: produce_one_row={self.produce_one_row}"


@dataclass
class Values(LogicalPlan):
    rows: list[list[Any]]
    schema_: pa.Schema = dc_field(default_factory=lambda: pa.schema([]))

    @property
    def schema(self) -> pa.Schema:
        return self.schema_

    def __str__(self) -> str:
        return f"Values: {len(self.rows)} rows"


@dataclass
class ExplainPlan(LogicalPlan):
    plan: LogicalPlan
    verbose: bool = False

    @property
    def schema(self) -> pa.Schema:
        return pa.schema([pa.field("plan_type", pa.string()), pa.field("plan", pa.string())])

    def children(self) -> list[LogicalPlan]:
        return [self.plan]

    def __str__(self) -> str:
        return "Explain"


def transform_up(plan: LogicalPlan, fn) -> LogicalPlan:
    """Bottom-up plan rewrite; fn(node_with_new_children) -> node."""
    kids = plan.children()
    if kids:
        new_kids = [transform_up(c, fn) for c in kids]
        plan = with_new_children(plan, new_kids)
    return fn(plan)


def with_new_children(plan: LogicalPlan, kids: list[LogicalPlan]) -> LogicalPlan:
    import copy

    p = copy.copy(plan)
    if isinstance(
        p,
        (Projection, Filter, Aggregate, Window, Sort, Limit, Distinct, SubqueryAlias),
    ):
        p.input = kids[0]
    elif isinstance(p, (Join, CrossJoin)):
        p.left, p.right = kids
    elif isinstance(p, Union):
        p.inputs = kids
    elif isinstance(p, ExplainPlan):
        p.plan = kids[0]
    elif kids:
        raise PlanError(f"with_new_children: unhandled node {type(plan).__name__}")
    return p
