"""Logical plan optimizer.

The reference inherits DataFusion's optimizer; this rebuild implements the
rules that matter for its workload (TPC-H via ``benchmarks/queries``):

1. ``simplify_expressions`` — constant folding, notably ``DATE ± INTERVAL``.
2. ``rewrite_cross_joins`` — comma-style FROM lists arrive as CrossJoin
   chains under a Filter; equality conjuncts become hash-join keys.
3. ``push_down_predicates`` — split conjuncts and push each to the deepest
   side of joins it fully references; register scan-level filters for
   parquet row-group pruning.
4. ``push_down_projection`` — prune unused columns all the way into scans
   (critical on TPU: every pruned column is HBM bandwidth saved).
"""

from __future__ import annotations

import datetime as _dt
from typing import Optional

import pyarrow as pa

from ..errors import PlanError
from . import expressions as ex
from . import logical as lp


def optimize(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    plan = simplify_expressions(plan)
    plan = factor_or_common(plan)
    plan = rewrite_cross_joins(plan)
    plan = push_down_predicates(plan)
    plan = push_down_projection(plan)
    return plan


# ------------------------------------------------------------ rule 1: folding
def _add_months(d: _dt.date, months: int) -> _dt.date:
    y, m = divmod(d.year * 12 + (d.month - 1) + months, 12)
    day = min(
        d.day,
        [31, 29 if y % 4 == 0 and (y % 100 != 0 or y % 400 == 0) else 28,
         31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m],
    )
    return _dt.date(y, m + 1, day)


def fold_expr(e: ex.Expr) -> ex.Expr:
    def fn(node: ex.Expr) -> ex.Expr:
        if isinstance(node, ex.BinaryExpr) and node.op in ("+", "-"):
            l, r = node.left, node.right
            if (
                isinstance(l, ex.Literal)
                and isinstance(l.value, _dt.date)
                and isinstance(r, ex.IntervalLiteral)
            ):
                sign = 1 if node.op == "+" else -1
                d = l.value
                if r.months:
                    d = _add_months(d, sign * r.months)
                if r.days:
                    d = d + _dt.timedelta(days=sign * r.days)
                return ex.lit(d)
            if (
                isinstance(l, ex.Literal)
                and isinstance(r, ex.Literal)
                and isinstance(l.value, (int, float))
                and isinstance(r.value, (int, float))
            ):
                v = l.value + r.value if node.op == "+" else l.value - r.value
                return ex.lit(v)
        if isinstance(node, ex.BinaryExpr) and node.op in ("*", "/"):
            l, r = node.left, node.right
            if (
                isinstance(l, ex.Literal)
                and isinstance(r, ex.Literal)
                and isinstance(l.value, (int, float))
                and isinstance(r.value, (int, float))
            ):
                return ex.lit(l.value * r.value if node.op == "*" else l.value / r.value)
        return node

    return ex.transform(e, fn)


def _map_exprs(plan: lp.LogicalPlan, f) -> lp.LogicalPlan:
    import copy

    p = copy.copy(plan)
    if isinstance(p, lp.Projection):
        p.exprs = [f(e) for e in p.exprs]
    elif isinstance(p, lp.Filter):
        p.predicate = f(p.predicate)
    elif isinstance(p, lp.Aggregate):
        p.group_exprs = [f(e) for e in p.group_exprs]
        p.agg_exprs = [f(e) for e in p.agg_exprs]
    elif isinstance(p, lp.Sort):
        p.sort_exprs = [f(e) for e in p.sort_exprs]
    elif isinstance(p, lp.Join) and p.filter is not None:
        p.filter = f(p.filter)
    return p


def simplify_expressions(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    return lp.transform_up(plan, lambda p: _map_exprs(p, fold_expr))


# ----------------------------------------------------- rule 2: cross → equi
def _schema_of(plans: list[lp.LogicalPlan]) -> pa.Schema:
    fields: list[pa.Field] = []
    for p in plans:
        fields.extend(p.schema)
    return pa.schema(fields)


def _refs_within(e: ex.Expr, schema: pa.Schema) -> bool:
    try:
        for c in ex.find_columns(e):
            c.resolve_index(schema)
        return True
    except PlanError:
        return False


def rewrite_cross_joins(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    def fn(p: lp.LogicalPlan) -> lp.LogicalPlan:
        if not (isinstance(p, lp.Filter) and isinstance(p.input, lp.CrossJoin)):
            return p
        # flatten the cross-join tree
        rels: list[lp.LogicalPlan] = []

        def flatten(n: lp.LogicalPlan) -> None:
            if isinstance(n, lp.CrossJoin):
                flatten(n.left)
                flatten(n.right)
            else:
                rels.append(n)

        flatten(p.input)
        conjuncts: list[ex.Expr] = _split_expr_conjuncts(p.predicate)

        # equality conjuncts between two distinct relations become join edges
        joined = rels[0]
        remaining = rels[1:]
        residual: list[ex.Expr] = list(conjuncts)
        progress = True
        while remaining and progress:
            progress = False
            for cand in list(remaining):
                trial_schema = _schema_of([joined, cand])
                keys: list[tuple[ex.Column, ex.Column]] = []
                used: list[ex.Expr] = []
                for c in residual:
                    if (
                        isinstance(c, ex.BinaryExpr)
                        and c.op == "="
                        and isinstance(c.left, ex.Column)
                        and isinstance(c.right, ex.Column)
                    ):
                        l_in = _refs_within(c.left, joined.schema)
                        r_in = _refs_within(c.right, joined.schema)
                        l_cand = _refs_within(c.left, cand.schema)
                        r_cand = _refs_within(c.right, cand.schema)
                        if l_in and r_cand and not l_cand and not r_in:
                            keys.append((c.left, c.right))
                            used.append(c)
                        elif r_in and l_cand and not r_cand and not l_in:
                            keys.append((c.right, c.left))
                            used.append(c)
                if keys:
                    joined = lp.Join(joined, cand, keys, "inner", None)
                    remaining = [r for r in remaining if r is not cand]
                    # NB: identity-based removal — Expr.__eq__ is overloaded
                    # to build comparison expressions (DataFrame API), so
                    # list.remove() must never be used on Expr lists
                    used_ids = {id(u) for u in used}
                    residual = [r for r in residual if id(r) not in used_ids]
                    progress = True
        for cand in remaining:  # no join edge found — keep cartesian
            joined = lp.CrossJoin(joined, cand)
        pred = _conjoin(residual)
        return lp.Filter(pred, joined) if pred is not None else joined

    return lp.transform_up(plan, fn)


def _split_disjuncts(e: ex.Expr) -> list[ex.Expr]:
    if isinstance(e, ex.BinaryExpr) and e.op == "OR":
        return _split_disjuncts(e.left) + _split_disjuncts(e.right)
    return [e]


def factor_or_common(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    """``(A and B) or (A and C)`` → ``A and (B or C)``.

    TPC-H q19's predicate repeats ``p_partkey = l_partkey`` inside every OR
    branch; factoring it out lets rewrite_cross_joins turn the cartesian
    product into a hash join (DataFusion does this as part of its filter
    simplification)."""

    def fix_pred(pred: ex.Expr) -> ex.Expr:
        branches = _split_disjuncts(pred)
        if len(branches) < 2:
            return pred
        per_branch = [_split_expr_conjuncts(b) for b in branches]
        common_keys = set(str(c) for c in per_branch[0])
        for cs in per_branch[1:]:
            common_keys &= {str(c) for c in cs}
        if not common_keys:
            return pred
        common: list[ex.Expr] = []
        seen: set[str] = set()
        for c in per_branch[0]:
            if str(c) in common_keys and str(c) not in seen:
                common.append(c)
                seen.add(str(c))
        rests: list[ex.Expr] = []
        for cs in per_branch:
            rest = [c for c in cs if str(c) not in common_keys]
            if not rest:
                # a branch that is exactly the common part: the OR is
                # implied true once common holds — drop the disjunction
                return _conjoin(common)  # type: ignore[return-value]
            rests.append(_conjoin(rest))  # type: ignore[arg-type]
        ored = rests[0]
        for r in rests[1:]:
            ored = ex.BinaryExpr(ored, "OR", r)
        return _conjoin(common + [ored])  # type: ignore[return-value]

    def fn(p: lp.LogicalPlan) -> lp.LogicalPlan:
        if isinstance(p, lp.Filter):
            new_pred = _conjoin(
                [fix_pred(c) for c in _split_expr_conjuncts(p.predicate)]
            )
            if new_pred is not None and str(new_pred) != str(p.predicate):
                return lp.Filter(new_pred, p.input)
        return p

    return lp.transform_up(plan, fn)


def _split_expr_conjuncts(e: ex.Expr) -> list[ex.Expr]:
    if isinstance(e, ex.BinaryExpr) and e.op == "AND":
        return _split_expr_conjuncts(e.left) + _split_expr_conjuncts(e.right)
    return [e]


def _conjoin(exprs: list[ex.Expr]) -> Optional[ex.Expr]:
    if not exprs:
        return None
    out = exprs[0]
    for e in exprs[1:]:
        out = ex.BinaryExpr(out, "AND", e)
    return out


# --------------------------------------------------- rule 3: predicate push
def push_down_predicates(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    def fn(p: lp.LogicalPlan) -> lp.LogicalPlan:
        if not isinstance(p, lp.Filter):
            return p
        conjuncts = _split_expr_conjuncts(p.predicate)
        child = p.input
        if isinstance(child, lp.Join) and child.join_type == "inner":
            left_push: list[ex.Expr] = []
            right_push: list[ex.Expr] = []
            keep: list[ex.Expr] = []
            for c in conjuncts:
                in_l = _refs_within(c, child.left.schema)
                in_r = _refs_within(c, child.right.schema)
                if in_l and not in_r:
                    left_push.append(c)
                elif in_r and not in_l:
                    right_push.append(c)
                else:
                    keep.append(c)
            if left_push or right_push:
                new_left = (
                    fn(lp.Filter(_conjoin(left_push), child.left))
                    if left_push
                    else child.left
                )
                new_right = (
                    fn(lp.Filter(_conjoin(right_push), child.right))
                    if right_push
                    else child.right
                )
                new_join = lp.Join(
                    new_left, new_right, child.on, child.join_type, child.filter
                )
                kp = _conjoin(keep)
                return lp.Filter(kp, new_join) if kp is not None else new_join
            return p
        if isinstance(child, lp.TableScan):
            # register as scan filters (row-group pruning hint); keep Filter
            child = lp.TableScan(
                child.table_name, child.provider, child.projection,
                child.filters + conjuncts,
            )
            return lp.Filter(p.predicate, child)
        if isinstance(child, lp.SubqueryAlias):
            # translate alias-qualified refs to the inner schema positionally
            outer, inner = child.schema, child.input.schema

            def translate(e: ex.Expr) -> ex.Expr:
                def t(node: ex.Expr) -> ex.Expr:
                    if isinstance(node, ex.Column):
                        idx = node.resolve_index(outer)
                        return ex.col(inner.field(idx).name)
                    return node

                return ex.transform(e, t)

            try:
                inner_pred = translate(p.predicate)
            except PlanError:
                return p
            return lp.SubqueryAlias(fn(lp.Filter(inner_pred, child.input)), child.alias)
        return p

    return lp.transform_up(plan, fn)


# -------------------------------------------------- rule 4: projection push
def push_down_projection(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    return _push_proj(plan, None)


def _required_from_exprs(exprs: list[ex.Expr], schema: pa.Schema) -> set[str]:
    req: set[str] = set()
    for e in exprs:
        for c in ex.find_columns(e):
            req.add(schema.field(c.resolve_index(schema)).name)
    return req


def _push_proj(plan: lp.LogicalPlan, required: Optional[set[str]]) -> lp.LogicalPlan:
    import copy

    if isinstance(plan, lp.Projection):
        p = copy.copy(plan)
        in_schema = p.input.schema
        req = _required_from_exprs(p.exprs, in_schema)
        p.input = _push_proj(p.input, req)
        return p
    if isinstance(plan, lp.Filter):
        p = copy.copy(plan)
        in_schema = p.input.schema
        req = None
        if required is not None:
            req = set(required) | _required_from_exprs([p.predicate], in_schema)
        p.input = _push_proj(p.input, req)
        return p
    if isinstance(plan, lp.Aggregate):
        p = copy.copy(plan)
        in_schema = p.input.schema
        req = _required_from_exprs(p.group_exprs + p.agg_exprs, in_schema)
        p.input = _push_proj(p.input, req)
        return p
    if isinstance(plan, lp.Sort):
        p = copy.copy(plan)
        in_schema = p.input.schema
        req = None
        if required is not None:
            req = set(required) | _required_from_exprs(list(p.sort_exprs), in_schema)
        p.input = _push_proj(p.input, req)
        return p
    if isinstance(plan, lp.Window):
        # Window passes every input column through and appends its own
        # outputs: keep the upstream requirement minus the window output
        # names, plus whatever the window exprs reference
        p = copy.copy(plan)
        in_schema = p.input.schema
        win_refs = _required_from_exprs(list(p.window_exprs), in_schema)
        req = None
        if required is not None:
            in_names = {f.name for f in in_schema}
            req = {r for r in required if r in in_names} | win_refs
        p.input = _push_proj(p.input, req)
        return p
    if isinstance(plan, (lp.Limit, lp.Distinct)):
        p = copy.copy(plan)
        p.input = _push_proj(p.input, required)
        return p
    if isinstance(plan, lp.SubqueryAlias):
        p = copy.copy(plan)
        inner_req = None
        if required is not None:
            outer, inner = p.schema, p.input.schema
            inner_req = set()
            for name in required:
                idx = outer.get_field_index(name)
                if idx >= 0:
                    inner_req.add(inner.field(idx).name)
        p.input = _push_proj(p.input, inner_req)
        return p
    if isinstance(plan, lp.Join):
        p = copy.copy(plan)
        lreq: Optional[set[str]] = None
        rreq: Optional[set[str]] = None
        if required is not None:
            ls, rs = p.left.schema, p.right.schema
            lreq, rreq = set(), set()
            for name in required:
                if ls.get_field_index(name) >= 0:
                    lreq.add(name)
                elif rs.get_field_index(name) >= 0:
                    rreq.add(name)
            for lk, rk in p.on:
                lreq.add(ls.field(lk.resolve_index(ls)).name)
                rreq.add(rs.field(rk.resolve_index(rs)).name)
            if p.filter is not None:
                for c in ex.find_columns(p.filter):
                    for s, tgt in ((ls, lreq), (rs, rreq)):
                        try:
                            tgt.add(s.field(c.resolve_index(s)).name)
                            break
                        except PlanError:
                            continue
        p.left = _push_proj(p.left, lreq)
        p.right = _push_proj(p.right, rreq)
        return p
    if isinstance(plan, lp.CrossJoin):
        p = copy.copy(plan)
        lreq: Optional[set[str]] = None
        rreq: Optional[set[str]] = None
        if required is not None:
            ls, rs = p.left.schema, p.right.schema
            lreq, rreq = set(), set()
            for name in required:
                if ls.get_field_index(name) >= 0:
                    lreq.add(name)
                elif rs.get_field_index(name) >= 0:
                    rreq.add(name)
        p.left = _push_proj(p.left, lreq)
        p.right = _push_proj(p.right, rreq)
        return p
    if isinstance(plan, lp.Union):
        p = copy.copy(plan)
        p.inputs = [_push_proj(c, None) for c in p.inputs]
        return p
    if isinstance(plan, lp.TableScan):
        if required is None:
            return plan
        # required holds qualified flat names; scan projection wants the
        # provider's unqualified names, in provider schema order
        unq = {n.split(".")[-1] for n in required}
        for f in plan.filters:
            for c in ex.find_columns(f):
                unq.add(c.cname)
        cols = [f.name for f in plan.provider.schema if f.name in unq]
        if not cols and len(plan.provider.schema) > 0:
            # a column-free scan would lose the row count (batches with no
            # arrays have num_rows 0) — count(*)-only queries need one
            # column kept; pick the narrowest
            def width(f: "object") -> int:
                try:
                    return f.type.bit_width
                except Exception:
                    return 1 << 16  # strings/nested sort last
            narrowest = min(plan.provider.schema, key=width)
            cols = [narrowest.name]
        return lp.TableScan(plan.table_name, plan.provider, cols, plan.filters)
    return plan
