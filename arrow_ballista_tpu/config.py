"""Session configuration.

Counterpart of ``BallistaConfig`` (``ballista/rust/core/src/config.rs:30-187``
in /root/reference): validated string key/value settings with typed defaults,
shipped with every query and materialized into the per-session execution
context.  New TPU-specific knobs are added for the accelerated stage path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable

from .errors import ConfigError

# Settings keys (reference: core/src/config.rs:30-38)
SHUFFLE_PARTITIONS = "ballista.shuffle.partitions"
BATCH_SIZE = "ballista.batch.size"
REPARTITION_JOINS = "ballista.repartition.joins"
REPARTITION_AGGREGATIONS = "ballista.repartition.aggregations"
REPARTITION_WINDOWS = "ballista.repartition.windows"
PARQUET_PRUNING = "ballista.parquet.pruning"
WITH_INFORMATION_SCHEMA = "ballista.with_information_schema"
PLUGIN_DIR = "ballista.plugin_dir"
# TPU-native additions
TPU_ENABLE = "ballista.tpu.enable"
TPU_SEGMENT_CAPACITY = "ballista.tpu.segment_capacity"
TPU_MAX_CAPACITY = "ballista.tpu.max_capacity"
TPU_BATCH_ROWS = "ballista.tpu.batch_rows"
TPU_DTYPE = "ballista.tpu.dtype"
TPU_MIN_ROWS = "ballista.tpu.min_rows"
TPU_CACHE_COLUMNS = "ballista.tpu.cache_columns"
TPU_HIGHCARD_MODE = "ballista.tpu.highcard_mode"
TPU_DEVICE_ENCODE = "ballista.tpu.device_encode"
TPU_KEYED_BUFFER_MB = "ballista.tpu.keyed_buffer_mb"
TPU_READAHEAD = "ballista.tpu.readahead"
TPU_WHOLE_STAGE_FUSION = "ballista.tpu.whole_stage_fusion"
MESH_ENABLE = "ballista.mesh.enable"
MESH_DEVICES = "ballista.mesh.devices"
MESH_EXCHANGE_MAX_ROWS = "ballista.mesh.exchange_max_rows"
SHUFFLE_TO_MEMORY = "ballista.shuffle.to_memory"
SHUFFLE_FETCH_CONCURRENCY = "ballista.shuffle.fetch_concurrency"
SHUFFLE_PREFETCH_BYTES = "ballista.shuffle.prefetch_bytes"
SHUFFLE_FETCH_RETRIES = "ballista.shuffle.fetch_retries"
SHUFFLE_FETCH_BACKOFF_MS = "ballista.shuffle.fetch_backoff_ms"
SHUFFLE_COALESCE_ROWS = "ballista.shuffle.coalesce_rows"
SHUFFLE_WRITE_COALESCE_ROWS = "ballista.shuffle.write_coalesce_rows"
SHUFFLE_WRITE_QUEUE_BYTES = "ballista.shuffle.write_queue_bytes"
SHUFFLE_WRITE_CONCURRENCY = "ballista.shuffle.write_concurrency"
SHUFFLE_WRITE_PIPELINED = "ballista.shuffle.write_pipelined"
SHUFFLE_COMPRESSION = "ballista.shuffle.compression"
# Pluggable shuffle storage + replication (docs/user-guide/fault-tolerance.md)
SHUFFLE_STORE = "ballista.shuffle.store"
SHUFFLE_REPLICATION = "ballista.shuffle.replication"
SHUFFLE_EXTERNAL_PATH = "ballista.shuffle.external_path"
# Locality-aware data plane (docs/user-guide/shuffle.md "Data plane")
SHUFFLE_LOCAL_TRANSPORT = "ballista.shuffle.local_transport"
SHUFFLE_FETCH_BATCHED = "ballista.shuffle.fetch_batched"
SHUFFLE_LOCALITY_ENABLED = "ballista.shuffle.locality_enabled"
SHUFFLE_LOCALITY_WAIT_S = "ballista.shuffle.locality_wait_seconds"
# Streaming pipelined execution (docs/user-guide/shuffle.md
# "Pipelined execution")
SHUFFLE_PIPELINED = "ballista.shuffle.pipelined"
SHUFFLE_PIPELINED_MIN_FRACTION = "ballista.shuffle.pipelined_min_fraction"
# Adaptive query execution (see docs/user-guide/aqe.md)
AQE_ENABLED = "ballista.aqe.enabled"
AQE_COALESCE_ENABLED = "ballista.aqe.coalesce_enabled"
AQE_BROADCAST_ENABLED = "ballista.aqe.broadcast_enabled"
AQE_SKEW_ENABLED = "ballista.aqe.skew_enabled"
AQE_TARGET_PARTITION_BYTES = "ballista.aqe.target_partition_bytes"
AQE_BROADCAST_THRESHOLD_BYTES = "ballista.aqe.broadcast_threshold_bytes"
AQE_SKEW_FACTOR = "ballista.aqe.skew_factor"
AQE_MAX_SPLITS = "ballista.aqe.max_splits"
AQE_COALESCE_MIN_PARTITIONS = "ballista.aqe.coalesce_min_partitions"
# Fault tolerance (see docs/user-guide/fault-tolerance.md)
TASK_MAX_ATTEMPTS = "ballista.task.max_attempts"
TASK_TIMEOUT_S = "ballista.task.timeout_seconds"
STAGE_MAX_ATTEMPTS = "ballista.stage.max_attempts"
# Speculative execution (straggler mitigation; fault-tolerance.md)
SPECULATION_ENABLED = "ballista.speculation.enabled"
SPECULATION_INTERVAL_S = "ballista.speculation.interval_seconds"
SPECULATION_MULTIPLIER = "ballista.speculation.multiplier"
SPECULATION_MIN_COMPLETED_FRACTION = "ballista.speculation.min_completed_fraction"
SPECULATION_MIN_RUNTIME_S = "ballista.speculation.min_runtime_seconds"
SPECULATION_MAX_COPIES_PER_STAGE = "ballista.speculation.max_copies_per_stage"
EXECUTOR_DRAIN_TIMEOUT_S = "ballista.executor.drain_timeout_seconds"
EXECUTOR_QUARANTINE_THRESHOLD = "ballista.executor.quarantine_threshold"
EXECUTOR_QUARANTINE_WINDOW_S = "ballista.executor.quarantine_window_seconds"
EXECUTOR_QUARANTINE_BACKOFF_S = "ballista.executor.quarantine_backoff_seconds"
CLIENT_JOB_TIMEOUT_S = "ballista.client.job_timeout_seconds"
CLIENT_POLL_INTERVAL_S = "ballista.client.poll_interval_seconds"
CLIENT_POLL_MAX_INTERVAL_S = "ballista.client.poll_max_interval_seconds"
CLIENT_RPC_RETRIES = "ballista.client.rpc_retries"
# Multi-tenant admission control (see docs/user-guide/multi-tenancy.md)
TENANT_ID = "ballista.tenant.id"
TENANT_PRIORITY = "ballista.tenant.priority"
TENANT_WEIGHT = "ballista.tenant.weight"
TENANT_MAX_RUNNING_JOBS = "ballista.tenant.max_running_jobs"
ADMISSION_ENABLED = "ballista.admission.enabled"
ADMISSION_MAX_RUNNING_JOBS = "ballista.admission.max_running_jobs"
ADMISSION_MAX_QUEUED_JOBS = "ballista.admission.max_queued_jobs"
ADMISSION_MAX_QUEUE_WAIT_S = "ballista.admission.max_queue_wait_seconds"
ADMISSION_SHED_POLICY = "ballista.admission.shed_policy"
ADMISSION_MAX_INTERACTIVE_BYPASS = "ballista.admission.max_interactive_bypass"
ADMISSION_INTERACTIVE_HEADROOM = "ballista.admission.interactive_headroom"
# Observability (see docs/user-guide/observability.md)
OBS_ENABLED = "ballista.obs.enabled"
OBS_SAMPLE_RATE = "ballista.obs.sample_rate"
OBS_BUFFER_SPANS = "ballista.obs.buffer_spans"
# per-session job-latency SLO: completed jobs slower than this feed
# slo_breaches_total + the burn-rate gauge (0 = untracked)
OBS_SLO_JOB_LATENCY_S = "ballista.obs.slo.job_latency_seconds"
# Elastic executor lifecycle (see docs/user-guide/autoscaling.md)
AUTOSCALER_ENABLED = "ballista.autoscaler.enabled"
AUTOSCALER_MIN_EXECUTORS = "ballista.autoscaler.min_executors"
AUTOSCALER_MAX_EXECUTORS = "ballista.autoscaler.max_executors"
AUTOSCALER_SCALE_OUT_SUSTAIN_S = "ballista.autoscaler.scale_out_sustain_seconds"
AUTOSCALER_SCALE_IN_IDLE_S = "ballista.autoscaler.scale_in_idle_seconds"
AUTOSCALER_COOLDOWN_S = "ballista.autoscaler.cooldown_seconds"
AUTOSCALER_LAUNCH_TIMEOUT_S = "ballista.autoscaler.launch_timeout_seconds"
AUTOSCALER_SLO_BURN_THRESHOLD = "ballista.autoscaler.slo_burn_threshold"
# Plan-fingerprint result/shuffle cache + learned per-plan policy
# (see docs/user-guide/plan-cache.md)
CACHE_ENABLED = "ballista.cache.enabled"
CACHE_MAX_BYTES = "ballista.cache.max_bytes"
CACHE_TTL_S = "ballista.cache.ttl_seconds"
CACHE_POLICY_ENABLED = "ballista.cache.policy.enabled"
CACHE_POLICY_SHADOW_FRACTION = "ballista.cache.policy.shadow_fraction"


class TaskSchedulingPolicy(str, Enum):
    """Reference: core/src/config.rs (TaskSchedulingPolicy enum)."""

    PULL_STAGED = "pull-staged"
    PUSH_STAGED = "push-staged"


def _parse_bool(v: str) -> bool:
    if v.lower() in ("true", "1", "yes"):
        return True
    if v.lower() in ("false", "0", "no"):
        return False
    raise ValueError(f"not a boolean: {v!r}")


def _parse_compression(v: str) -> str:
    codec = v.lower()
    if codec not in ("none", "lz4", "zstd"):
        raise ValueError(f"compression must be none|lz4|zstd, got {v!r}")
    return codec


def _parse_shuffle_store(v: str) -> str:
    kind = v.lower()
    if kind not in ("local", "mem", "external"):
        raise ValueError(f"shuffle store must be local|mem|external, got {v!r}")
    return kind


def _parse_replication(v: str) -> str:
    mode = v.lower()
    if mode not in ("none", "async", "sync"):
        raise ValueError(f"replication must be none|async|sync, got {v!r}")
    return mode


def _parse_local_transport(v: str) -> str:
    mode = v.lower()
    if mode not in ("auto", "off"):
        raise ValueError(f"local_transport must be auto|off, got {v!r}")
    return mode


def _parse_min_fraction(v: str) -> float:
    f = float(v)
    if not (0.0 < f <= 1.0):
        raise ValueError(f"min fraction must be in (0, 1], got {v!r}")
    return f


def _parse_priority(v: str) -> str:
    lane = v.lower()
    if lane not in ("interactive", "batch"):
        raise ValueError(f"tenant priority must be interactive|batch, got {v!r}")
    return lane


def _parse_shed_policy(v: str) -> str:
    policy = v.lower()
    if policy not in ("reject", "oldest"):
        raise ValueError(f"shed policy must be reject|oldest, got {v!r}")
    return policy


def _parse_weight(v: str) -> float:
    w = float(v)
    if w <= 0:
        raise ValueError(f"tenant weight must be > 0, got {v!r}")
    return w


def _parse_highcard_mode(v: str) -> str:
    mode = v.lower()
    if mode not in ("auto", "device", "cpu", "gid"):
        raise ValueError(
            f"highcard_mode must be auto|cpu|device|gid, got {v!r}"
        )
    return mode


@dataclass(frozen=True)
class ConfigEntry:
    key: str
    description: str
    parse: Callable[[str], Any]
    default: str


_ENTRIES: dict[str, ConfigEntry] = {
    e.key: e
    for e in [
        ConfigEntry(
            SHUFFLE_PARTITIONS,
            "number of output partitions for shuffle stages",
            int,
            "2",
        ),
        ConfigEntry(BATCH_SIZE, "rows per record batch", int, "8192"),
        ConfigEntry(
            REPARTITION_JOINS, "repartition inputs of joins", _parse_bool, "true"
        ),
        ConfigEntry(
            REPARTITION_AGGREGATIONS,
            "repartition inputs of aggregations",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            REPARTITION_WINDOWS, "repartition inputs of windows", _parse_bool, "true"
        ),
        ConfigEntry(PARQUET_PRUNING, "enable parquet row-group pruning", _parse_bool, "true"),
        ConfigEntry(
            WITH_INFORMATION_SCHEMA,
            "provide information_schema tables (SHOW ...)",
            _parse_bool,
            "false",
        ),
        ConfigEntry(PLUGIN_DIR, "directory of UDF plugins", str, ""),
        ConfigEntry(
            TPU_ENABLE,
            "compile eligible stage subplans to fused XLA kernels on TPU",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            TPU_SEGMENT_CAPACITY,
            "initial group-table capacity for on-device hash aggregation "
            "(grows 4x, with state padding, up to tpu.max_capacity)",
            int,
            # matmul-path FLOPs scale with capacity (rows x cap x cols):
            # start small, let 4x growth track real cardinality
            "1024",
        ),
        ConfigEntry(
            TPU_MAX_CAPACITY,
            "group-table ceiling; cardinality beyond it falls back to the "
            "CPU operator path",
            int,
            str(1 << 21),
        ),
        ConfigEntry(
            TPU_BATCH_ROWS,
            "row count each fused device invocation is padded/bucketed to",
            int,
            "1048576",
        ),
        ConfigEntry(TPU_DTYPE, "accumulation dtype on device", str, "float64"),
        ConfigEntry(
            TPU_MIN_ROWS,
            "partitions with fewer input rows than this run the CPU operator "
            "path instead of launching a device kernel (kernel-launch and "
            "compile latency dominate below it); 0 disables the fallback",
            int,
            "16384",
        ),
        ConfigEntry(
            TPU_CACHE_COLUMNS,
            "pin prepared scan inputs (columns, masks, group ids) in device "
            "memory so repeated queries skip host→HBM transfer",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            TPU_HIGHCARD_MODE,
            "aggregate routing when the first batch shows groups ~ rows: "
            "'auto' resolves by platform — accelerator backends run the "
            "device-KEYED aggregation (group ids assigned by the device "
            "sort, no host hash encode), the cpu backend hands to the "
            "C++ hash aggregate (measured winner there: h2o q10 4x); "
            "'device' pins the keyed path anywhere, 'cpu' pins the hash "
            "handoff (A/B baseline), 'gid' pins the gid-table device "
            "path even at high cardinality (A/B: capacity must fit)",
            _parse_highcard_mode,
            "auto",
        ),
        ConfigEntry(
            TPU_DEVICE_ENCODE,
            "encode group keys ON DEVICE inside the fused keyed kernel "
            "(raw key columns cross the bridge once; codes derive "
            "bit-identically to the host encoders and the "
            "encode→packed-u64-sort→segment-reduce pipeline runs as one "
            "jitted dispatch); false pins the host-encode keyed path "
            "(A/B baseline).  Keys without a device encoding (strings) "
            "keep the host dictionary handoff either way",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            TPU_KEYED_BUFFER_MB,
            "HBM budget (MiB) for the keyed path's buffered scan columns; "
            "past it the buffered block reduces to [distinct]-sized keyed "
            "states and a host merge combines blocks (median/corr cannot "
            "chunk-merge and fall back to the CPU operator instead of "
            "risking device OOM); 0 disables chunking",
            int,
            # v5e has 16 GiB HBM; the sort's working set runs ~2-3x the
            # buffered bytes, so 2 GiB of buffer keeps peak well clear
            "2048",
        ),
        ConfigEntry(
            TPU_READAHEAD,
            "background source-batch prefetch depth for device stages "
            "(overlaps scan/decode IO with device compute); 0 disables",
            int,
            "2",
        ),
        ConfigEntry(
            TPU_WHOLE_STAGE_FUSION,
            "compile a fusion-eligible map stage (scan→filter→project→"
            "partial-agg, plus the shuffle partition-id column when a "
            "shuffle hint is installed) into ONE jitted dispatch instead "
            "of per-operator dispatches; segment boundaries come from the "
            "measured routing table (fusion_max_ops/fusion_min_rows) and "
            "any trace failure degrades segment-by-segment to the "
            "per-operator path; off keeps today's dispatch sequence "
            "byte-identical",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            MESH_ENABLE,
            "run eligible stages as single gang tasks over the device mesh, "
            "replacing the shuffle hop with ICI collectives",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            MESH_DEVICES,
            "mesh width for gang stages (0 = all visible devices)",
            int,
            "0",
        ),
        ConfigEntry(
            MESH_EXCHANGE_MAX_ROWS,
            "row ceiling for the ICI repartition exchange (it buffers the "
            "stage input in host memory); beyond it the writer falls back "
            "to the streaming hash-split path",
            int,
            str(1 << 26),
        ),
        ConfigEntry(
            SHUFFLE_TO_MEMORY,
            "hold shuffle partitions in executor memory (served via Flight) "
            "instead of Arrow IPC files on disk",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            SHUFFLE_FETCH_CONCURRENCY,
            "map-side locations each shuffle reader fetches concurrently "
            "(local file, memory store and Flight sources alike); 1 runs a "
            "single fetch worker that walks locations in order",
            int,
            "8",
        ),
        ConfigEntry(
            SHUFFLE_PREFETCH_BYTES,
            "byte budget of fetched-but-unconsumed shuffle batches per "
            "reader partition; fetch workers block (backpressure) once the "
            "queue holds this much",
            int,
            str(64 << 20),
        ),
        ConfigEntry(
            SHUFFLE_FETCH_RETRIES,
            "per-location fetch retries before the stage fails; each failed "
            "attempt drops the cached Flight connection so the retry "
            "reconnects",
            int,
            "3",
        ),
        ConfigEntry(
            SHUFFLE_FETCH_BACKOFF_MS,
            "base backoff between fetch retries (doubles per attempt)",
            int,
            "50",
        ),
        ConfigEntry(
            SHUFFLE_COALESCE_ROWS,
            "target row count for host-side coalescing of fetched shuffle "
            "batches before device transfer (small map fragments combine "
            "into one device dispatch); 0 follows ballista.batch.size, "
            "negative disables coalescing",
            int,
            "0",
        ),
        ConfigEntry(
            SHUFFLE_WRITE_COALESCE_ROWS,
            "target row count per slab flush on the shuffle WRITE side: "
            "hash-split row runs coalesce in per-output-partition slab "
            "buffers until this many rows, so IPC files hold few large "
            "batches instead of one fragment per (input batch, output "
            "partition); 0 follows 4 x ballista.batch.size, negative "
            "writes every split run straight through",
            int,
            "0",
        ),
        ConfigEntry(
            SHUFFLE_WRITE_QUEUE_BYTES,
            "byte budget of coalesced-but-unwritten shuffle batches per "
            "write task; the compute thread blocks (backpressure) once "
            "the writer pool's queues hold this much",
            int,
            str(32 << 20),
        ),
        ConfigEntry(
            SHUFFLE_WRITE_CONCURRENCY,
            "writer-pool threads per shuffle write task (output "
            "partitions are sharded across them, so per-sink batch order "
            "is deterministic); serialization and sink I/O run there "
            "instead of on the compute thread",
            int,
            "2",
        ),
        ConfigEntry(
            SHUFFLE_WRITE_PIPELINED,
            "false pins the pre-pipelining map-side path (argsort-based "
            "permutation, synchronous uncoalesced per-run sink writes, "
            "no compression — shuffle.compression only applies to the "
            "pipelined path) — the A/B baseline for "
            "benchmarks/shuffle_write.py",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            SHUFFLE_COMPRESSION,
            "IPC body compression for written shuffle partitions "
            "(none|lz4|zstd); pyarrow readers and the Flight server "
            "decompress transparently, so only the write side pays",
            _parse_compression,
            "none",
        ),
        ConfigEntry(
            SHUFFLE_STORE,
            "where written shuffle partitions live: 'local' (Arrow IPC "
            "files under the executor work_dir, served over Flight — the "
            "fast path), 'mem' (executor-memory store, equivalent to "
            "ballista.shuffle.to_memory=true), or 'external' (the shared "
            "directory at ballista.shuffle.external_path, standing in for "
            "an object store: partitions survive their producer, so "
            "executor loss never triggers recompute)",
            _parse_shuffle_store,
            "local",
        ),
        ConfigEntry(
            SHUFFLE_REPLICATION,
            "upload a replica of each finished local/mem shuffle partition "
            "to the external store: 'none' (off), 'async' (writer-pool "
            "thread hands the finished partition to a background uploader "
            "— task completion never waits), 'sync' (upload completes "
            "before the task reports; a failed upload degrades to single "
            "copy, never fails the task).  Requires "
            "ballista.shuffle.external_path; ignored when the store IS "
            "external",
            _parse_replication,
            "none",
        ),
        ConfigEntry(
            SHUFFLE_EXTERNAL_PATH,
            "shared directory (object-store stand-in) holding external "
            "shuffle partitions and replicas; must be reachable from "
            "every executor and the scheduler",
            str,
            "",
        ),
        ConfigEntry(
            SHUFFLE_LOCAL_TRANSPORT,
            "same-host zero-copy shuffle transport: 'auto' serves a "
            "partition via pa.memory_map (zero-copy, no gRPC) whenever "
            "the serving executor's HOST IDENTITY matches this process's "
            "registered executors (never a bare path-existence probe — "
            "on a multi-host cluster a coincidentally-existing path must "
            "not be read as shuffle input); 'off' forces every "
            "non-memory fetch over Flight (the forced-remote A/B leg of "
            "benchmarks/shuffle_locality.py)",
            _parse_local_transport,
            "auto",
        ),
        ConfigEntry(
            SHUFFLE_FETCH_BATCHED,
            "fetch many map partitions per Flight round trip: locations "
            "on one remote executor group into a single multi-partition "
            "DoGet (ticket lists the paths; the server interleaves "
            "mmap-backed streams, tagging batches with their partition "
            "index) instead of one round trip per location; false "
            "restores per-partition DoGets",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            SHUFFLE_LOCALITY_ENABLED,
            "locality-aware reduce-task placement: prefer executors on "
            "the hosts holding the most bytes of each reduce task's "
            "input partitions (exact per-partition sizes from the "
            "map-side write stats), waiting up to "
            "ballista.shuffle.locality_wait_seconds for a preferred "
            "slot before falling back to any host — makes the same-host "
            "zero-copy transport the common case on multi-executor "
            "clusters.  Off by default: placement is unchanged",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            SHUFFLE_LOCALITY_WAIT_S,
            "how long a reduce task may hold out for a slot on its "
            "preferred host before any executor may take it (the soft "
            "half of locality placement; 0 = prefer but never wait)",
            float,
            "1.0",
        ),
        ConfigEntry(
            SHUFFLE_PIPELINED,
            "streaming pipelined execution: a downstream stage whose "
            "shuffle inputs are all streamable (no sort / hash-join "
            "build between the shuffle read and the stage root) starts "
            "once ballista.shuffle.pipelined_min_fraction of each "
            "input's map tasks have COMMITTED, tailing the remaining "
            "map output as it lands instead of waiting for the stage "
            "barrier.  Committed-task granularity: only first-"
            "completion-wins winners are ever streamed from, so "
            "speculation/retry semantics are unchanged.  Off by "
            "default: stage transitions, dispatch order and wire "
            "traffic are byte-identical to the barrier scheduler",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            SHUFFLE_PIPELINED_MIN_FRACTION,
            "fraction of each input's map tasks that must have "
            "committed before a streamable consumer stage starts on "
            "partial input (pipelined execution); lower starts "
            "consumers earlier but holds their slots longer while they "
            "stall on producers",
            _parse_min_fraction,
            "0.25",
        ),
        ConfigEntry(
            AQE_ENABLED,
            "adaptive query execution: when a stage completes, its "
            "observed per-partition shuffle sizes re-plan not-yet-"
            "resolved consumer stages (partition coalescing, shuffle→"
            "broadcast join conversion, skew splitting — each with its "
            "own toggle below); false restores fully static plans",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            AQE_COALESCE_ENABLED,
            "AQE rewrite 1: pack adjacent tiny reduce partitions into "
            "fewer tasks until each reads ~aqe.target_partition_bytes",
            _parse_bool,
            "true",
        ),
        ConfigEntry(
            AQE_BROADCAST_ENABLED,
            "AQE rewrite 2: when one side of a partitioned inner join "
            "measures under aqe.broadcast_threshold_bytes before the "
            "probe side has started, convert to a collect-left "
            "broadcast join and strip the probe-side shuffle stage",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            AQE_SKEW_ENABLED,
            "AQE rewrite 3: split a reduce partition whose observed "
            "input exceeds aqe.skew_factor x median across several "
            "tasks, each reading a disjoint subset of the map-side "
            "fragments (joins duplicate the companion side's partition; "
            "final aggregates re-merge partial states downstream)",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            AQE_TARGET_PARTITION_BYTES,
            "coalescing packs reduce partitions up to this many "
            "observed wire bytes per task; skew splitting sizes its "
            "chunk count against it",
            int,
            str(16 << 20),
        ),
        ConfigEntry(
            AQE_BROADCAST_THRESHOLD_BYTES,
            "a completed build side smaller than this (total wire "
            "bytes) qualifies for shuffle→broadcast join conversion",
            int,
            str(10 << 20),
        ),
        ConfigEntry(
            AQE_SKEW_FACTOR,
            "a reduce partition is skewed when its observed bytes "
            "exceed this multiple of the stage's median partition "
            "(and aqe.target_partition_bytes)",
            float,
            "4.0",
        ),
        ConfigEntry(
            AQE_MAX_SPLITS,
            "ceiling on the tasks one skewed partition splits into "
            "(also bounded by its map-side fragment count)",
            int,
            "8",
        ),
        ConfigEntry(
            AQE_COALESCE_MIN_PARTITIONS,
            "shuffles with at most this many reduce partitions keep "
            "their static layout — scheduling a handful of tasks costs "
            "less than second-guessing them",
            int,
            "8",
        ),
        ConfigEntry(
            EXECUTOR_DRAIN_TIMEOUT_S,
            "graceful-decommission budget (seconds): a draining executor "
            "finishes its running tasks within this window (past it they "
            "are cancelled and handed off without consuming retry "
            "budget), uploads un-replicated shuffle partitions to the "
            "external store, then exits",
            float,
            "30",
        ),
        ConfigEntry(
            TASK_MAX_ATTEMPTS,
            "total attempts per task (first run + retries of transient "
            "failures) before the job fails with the accumulated error "
            "history; 1 disables retries",
            int,
            "4",
        ),
        ConfigEntry(
            TASK_TIMEOUT_S,
            "hard deadline (seconds) for one task attempt: a 'running' "
            "task older than this on a live-but-wedged executor is "
            "cancelled and re-queued through the normal transient path "
            "WITHOUT consuming its attempt budget; 0 disables",
            float,
            "0",
        ),
        ConfigEntry(
            STAGE_MAX_ATTEMPTS,
            "executor-loss rollbacks per stage before the job fails "
            "instead of looping against a flapping executor",
            int,
            "4",
        ),
        ConfigEntry(
            SPECULATION_ENABLED,
            "launch a duplicate attempt of a straggling task on a "
            "DIFFERENT executor once enough of its stage has finished; "
            "first completion wins, the loser is cancelled and its late "
            "status dropped as stale",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            SPECULATION_INTERVAL_S,
            "how often (seconds) the scheduler's speculation scan visits "
            "this job's running stages (the scan thread ticks at the "
            "scheduler-level speculation_interval_seconds; a larger "
            "per-session value skips intermediate ticks)",
            float,
            "1.0",
        ),
        ConfigEntry(
            SPECULATION_MULTIPLIER,
            "a running task becomes a speculation candidate once its "
            "elapsed time exceeds multiplier x median(completed task "
            "runtimes in its stage)",
            float,
            "1.5",
        ),
        ConfigEntry(
            SPECULATION_MIN_COMPLETED_FRACTION,
            "fraction of a stage's tasks that must have completed before "
            "the runtime median is trusted for speculation",
            float,
            "0.75",
        ),
        ConfigEntry(
            SPECULATION_MIN_RUNTIME_S,
            "floor (seconds) under which a task is never speculated, "
            "whatever the median says — duplicating sub-second tasks "
            "wastes slots",
            float,
            "1.0",
        ),
        ConfigEntry(
            SPECULATION_MAX_COPIES_PER_STAGE,
            "total speculative duplicates one stage may launch over its "
            "lifetime (bounds wasted work on a generally-slow cluster)",
            int,
            "2",
        ),
        ConfigEntry(
            EXECUTOR_QUARANTINE_THRESHOLD,
            "task/launch failures inside the sliding window that exclude "
            "an executor from new reservations; 0 disables quarantine",
            int,
            "5",
        ),
        ConfigEntry(
            EXECUTOR_QUARANTINE_WINDOW_S,
            "sliding-window length (seconds) for the per-executor "
            "failure count",
            float,
            "60",
        ),
        ConfigEntry(
            EXECUTOR_QUARANTINE_BACKOFF_S,
            "how long (seconds) a quarantined executor is excluded from "
            "slot reservations",
            float,
            "30",
        ),
        ConfigEntry(
            CLIENT_JOB_TIMEOUT_S,
            "FlightSQL front-end poll deadline (seconds) per statement",
            float,
            "300",
        ),
        ConfigEntry(
            CLIENT_POLL_INTERVAL_S,
            "initial GetJobStatus poll interval (seconds); subsequent "
            "polls back off exponentially with jitter so hundreds of "
            "concurrent waiting clients stop hammering the scheduler in "
            "lockstep",
            float,
            "0.1",
        ),
        ConfigEntry(
            CLIENT_POLL_MAX_INTERVAL_S,
            "cap (seconds) of the jittered exponential poll backoff — "
            "the worst-case extra latency a client adds to noticing its "
            "job finished",
            float,
            "2.0",
        ),
        ConfigEntry(
            CLIENT_RPC_RETRIES,
            "extra attempts for a transient (UNAVAILABLE / "
            "DEADLINE_EXCEEDED) scheduler RPC failure before the error "
            "surfaces; with multiple endpoints each retry also rotates "
            "to the next scheduler",
            int,
            "3",
        ),
        ConfigEntry(
            TENANT_ID,
            "tenant pool this session's jobs belong to for admission "
            "control and weighted fair scheduling; empty = the shared "
            "'default' pool",
            str,
            "",
        ),
        ConfigEntry(
            TENANT_PRIORITY,
            "admission lane for this session's jobs: 'interactive' jobs "
            "release ahead of batch work across every pool (bounded by "
            "ballista.admission.max_interactive_bypass so batch is "
            "delayed, never starved) and dispatch first among running "
            "jobs; 'batch' is the default lane",
            _parse_priority,
            "batch",
        ),
        ConfigEntry(
            TENANT_WEIGHT,
            "fair-share weight of this session's tenant pool: queued "
            "jobs release by deficit-weighted round robin, so pools "
            "with weights 2:1 admit 2:1 whenever both have work queued",
            _parse_weight,
            "1",
        ),
        ConfigEntry(
            TENANT_MAX_RUNNING_JOBS,
            "cap on concurrently admitted jobs of this tenant pool "
            "(0 = bounded only by the cluster-wide admission gate)",
            int,
            "0",
        ),
        ConfigEntry(
            ADMISSION_ENABLED,
            "multi-tenant admission control: jobs past the cluster's "
            "running-job capacity wait PRE-PLANNING in a bounded "
            "per-pool queue (no ExecutionGraph built, no memory "
            "pinned) and release by weighted fair share as capacity "
            "frees; past the queue bounds the scheduler sheds with a "
            "structured, retryable ClusterSaturated error.  false "
            "(default) keeps submit/dispatch byte-identical to the "
            "pre-admission scheduler",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            ADMISSION_MAX_RUNNING_JOBS,
            "cluster-wide cap on concurrently admitted jobs; 0 derives "
            "one admitted job per task slot across alive executors",
            int,
            "0",
        ),
        ConfigEntry(
            ADMISSION_MAX_QUEUED_JOBS,
            "admission queue bound across all pools; a submission past "
            "it sheds per ballista.admission.shed_policy (0 = "
            "unbounded — every admission transits the queue, so the "
            "bound can never mean 'no queue')",
            int,
            "100",
        ),
        ConfigEntry(
            ADMISSION_MAX_QUEUE_WAIT_S,
            "a job queued longer than this sheds with ClusterSaturated "
            "instead of waiting forever (0 = unbounded wait)",
            float,
            "0",
        ),
        ConfigEntry(
            ADMISSION_SHED_POLICY,
            "which job pays when the admission queue is full: 'reject' "
            "sheds the NEWEST submission (the one arriving now), "
            "'oldest' sheds the longest-queued job and queues the "
            "newcomer — both with the structured ClusterSaturated error",
            _parse_shed_policy,
            "reject",
        ),
        ConfigEntry(
            ADMISSION_MAX_INTERACTIVE_BYPASS,
            "consecutive interactive-lane releases allowed to jump a "
            "waiting batch job before the batch head must go (bounded "
            "bypass: interactive is fast, batch never starves)",
            int,
            "4",
        ),
        ConfigEntry(
            ADMISSION_INTERACTIVE_HEADROOM,
            "bounded express lane: up to this many interactive jobs may "
            "run ABOVE the cluster's admission cap, so a short "
            "interactive query never waits a whole long batch job's "
            "completion for its admission slot (job-granular admission "
            "would otherwise make it SLOWER than task-granular FIFO); "
            "their tasks then dispatch first among running jobs.  "
            "Running interactive jobs charge this headroom BEFORE they "
            "count against base capacity, so express traffic never "
            "consumes batch's share.  0 makes interactive queue like "
            "everything else",
            int,
            "2",
        ),
        ConfigEntry(
            OBS_ENABLED,
            "distributed tracing + span recording for this session's jobs "
            "(scheduler, executors and shuffle fetch stitch under one "
            "trace id); off = the span API is a near-zero-cost no-op",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            OBS_SAMPLE_RATE,
            "fraction of jobs that get a trace when obs is enabled "
            "(sampling decided once per job at submit)",
            float,
            "1.0",
        ),
        ConfigEntry(
            OBS_BUFFER_SPANS,
            "per-process finished-span ring-buffer capacity; overflow "
            "drops the oldest spans (observability never grows unbounded)",
            int,
            "4096",
        ),
        ConfigEntry(
            OBS_SLO_JOB_LATENCY_S,
            "job-latency SLO for this session (seconds): a completed job "
            "slower than this counts into slo_breaches_total and the "
            "slo_burn_rate gauge on the scheduler; 0 disables tracking",
            float,
            "0",
        ),
        ConfigEntry(
            AUTOSCALER_ENABLED,
            "closed-loop executor autoscaling on the scheduler: a policy "
            "engine on the timer cadence reads admission queue depth, "
            "slot deficit and SLO burn rate and launches/drains "
            "executors through an ExecutorProvider; off = the scheduler "
            "never manages capacity (the KEDA stub behavior)",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            AUTOSCALER_MIN_EXECUTORS,
            "floor for the autoscaler's total-alive-executor target; the "
            "loop launches up to this many at startup and never drains "
            "below it",
            int,
            "1",
        ),
        ConfigEntry(
            AUTOSCALER_MAX_EXECUTORS,
            "ceiling for the autoscaler's total-alive-executor target; "
            "scale-out decisions clamp here no matter the backlog",
            int,
            "4",
        ),
        ConfigEntry(
            AUTOSCALER_SCALE_OUT_SUSTAIN_S,
            "pressure (slot deficit / queued jobs / SLO burn) must "
            "persist this many seconds before a scale-out fires — "
            "hysteresis so a one-tick blip never launches an executor",
            float,
            "3",
        ),
        ConfigEntry(
            AUTOSCALER_SCALE_IN_IDLE_S,
            "the cluster must be completely idle (no running, pending or "
            "queued work) this many seconds before a scale-in drains one "
            "executor",
            float,
            "15",
        ),
        ConfigEntry(
            AUTOSCALER_COOLDOWN_S,
            "minimum seconds between successive scale-out decisions (and "
            "separately between scale-ins) so the loop never flaps",
            float,
            "10",
        ),
        ConfigEntry(
            AUTOSCALER_LAUNCH_TIMEOUT_S,
            "a provider launch that has not registered within this many "
            "seconds is abandoned, terminated, and counted against the "
            "consecutive-launch-failure window",
            float,
            "60",
        ),
        ConfigEntry(
            AUTOSCALER_SLO_BURN_THRESHOLD,
            "scale out when the SLO burn-rate gauge sustains at or above "
            "this value even without a slot deficit; 0 ignores burn rate",
            float,
            "0",
        ),
        ConfigEntry(
            CACHE_ENABLED,
            "scheduler-side plan-fingerprint result/shuffle cache: a "
            "stage whose producer subtree's canonical fingerprint (plus "
            "source snapshot identity) matches a cached entry resolves "
            "against the cached partitions in the external store and the "
            "producer subtree is never dispatched; off = planning and "
            "dispatch are byte-identical to a cache-less scheduler",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            CACHE_MAX_BYTES,
            "total bytes the plan cache may pin in the external store; "
            "exceeding it evicts least-recently-used entries until under "
            "budget (0 = unbounded)",
            int,
            "1073741824",
        ),
        ConfigEntry(
            CACHE_TTL_S,
            "seconds a plan-cache entry stays servable after its last "
            "store/hit; expired entries are evicted lazily at lookup and "
            "store time (0 = no TTL)",
            float,
            "3600",
        ),
        ConfigEntry(
            CACHE_POLICY_ENABLED,
            "self-tuning per-plan policy store: after each job the "
            "doctor's findings are recorded under the plan's shape "
            "fingerprint, and the next submit of a matching plan merges "
            "the learned knob overrides BENEATH explicit session "
            "settings; a shadow fraction stays at baseline and an "
            "override whose measured latency regresses vs the shadow "
            "population is rolled back automatically",
            _parse_bool,
            "false",
        ),
        ConfigEntry(
            CACHE_POLICY_SHADOW_FRACTION,
            "fraction of matching submits the policy store leaves at "
            "baseline (no overrides) to keep an unbiased comparison "
            "population for rollback decisions",
            float,
            "0.1",
        ),
    ]
}


@dataclass
class BallistaConfig:
    """Validated k/v session settings (reference: core/src/config.rs:96-130)."""

    settings: dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for k, v in self.settings.items():
            entry = _ENTRIES.get(k)
            if entry is None:
                # Unknown keys are preserved (forward compatibility) but not
                # validated, mirroring the reference's behavior for
                # extension settings.
                continue
            try:
                entry.parse(v)
            except Exception as e:  # noqa: BLE001
                raise ConfigError(f"invalid value for {k}: {v!r} ({e})") from e

    @staticmethod
    def builder() -> "BallistaConfigBuilder":
        return BallistaConfigBuilder()

    def _get(self, key: str) -> Any:
        entry = _ENTRIES[key]
        raw = self.settings.get(key, entry.default)
        return entry.parse(raw)

    # Typed accessors
    @property
    def shuffle_partitions(self) -> int:
        return self._get(SHUFFLE_PARTITIONS)

    @property
    def batch_size(self) -> int:
        return self._get(BATCH_SIZE)

    @property
    def repartition_joins(self) -> bool:
        return self._get(REPARTITION_JOINS)

    @property
    def repartition_aggregations(self) -> bool:
        return self._get(REPARTITION_AGGREGATIONS)

    @property
    def parquet_pruning(self) -> bool:
        return self._get(PARQUET_PRUNING)

    @property
    def with_information_schema(self) -> bool:
        return self._get(WITH_INFORMATION_SCHEMA)

    @property
    def tpu_enable(self) -> bool:
        return self._get(TPU_ENABLE)

    @property
    def tpu_segment_capacity(self) -> int:
        return self._get(TPU_SEGMENT_CAPACITY)

    @property
    def tpu_max_capacity(self) -> int:
        return self._get(TPU_MAX_CAPACITY)

    @property
    def tpu_batch_rows(self) -> int:
        return self._get(TPU_BATCH_ROWS)

    @property
    def tpu_cache_columns(self) -> bool:
        return self._get(TPU_CACHE_COLUMNS)

    @property
    def tpu_highcard_mode(self) -> str:
        return self._get(TPU_HIGHCARD_MODE)

    @property
    def tpu_device_encode(self) -> bool:
        return self._get(TPU_DEVICE_ENCODE)

    @property
    def tpu_keyed_buffer_mb(self) -> int:
        return self._get(TPU_KEYED_BUFFER_MB)

    @property
    def tpu_readahead(self) -> int:
        return self._get(TPU_READAHEAD)

    @property
    def tpu_whole_stage_fusion(self) -> bool:
        return self._get(TPU_WHOLE_STAGE_FUSION)

    @property
    def tpu_min_rows(self) -> int:
        return self._get(TPU_MIN_ROWS)

    @property
    def mesh_enable(self) -> bool:
        return self._get(MESH_ENABLE)

    @property
    def mesh_devices(self) -> int:
        return self._get(MESH_DEVICES)

    @property
    def mesh_exchange_max_rows(self) -> int:
        return self._get(MESH_EXCHANGE_MAX_ROWS)

    @property
    def shuffle_to_memory(self) -> bool:
        return self._get(SHUFFLE_TO_MEMORY)

    @property
    def shuffle_fetch_concurrency(self) -> int:
        return self._get(SHUFFLE_FETCH_CONCURRENCY)

    @property
    def shuffle_prefetch_bytes(self) -> int:
        return self._get(SHUFFLE_PREFETCH_BYTES)

    @property
    def shuffle_fetch_retries(self) -> int:
        return self._get(SHUFFLE_FETCH_RETRIES)

    @property
    def shuffle_fetch_backoff_ms(self) -> int:
        return self._get(SHUFFLE_FETCH_BACKOFF_MS)

    @property
    def shuffle_coalesce_rows(self) -> int:
        return self._get(SHUFFLE_COALESCE_ROWS)

    @property
    def shuffle_write_coalesce_rows(self) -> int:
        return self._get(SHUFFLE_WRITE_COALESCE_ROWS)

    @property
    def shuffle_write_queue_bytes(self) -> int:
        return self._get(SHUFFLE_WRITE_QUEUE_BYTES)

    @property
    def shuffle_write_concurrency(self) -> int:
        return self._get(SHUFFLE_WRITE_CONCURRENCY)

    @property
    def shuffle_write_pipelined(self) -> bool:
        return self._get(SHUFFLE_WRITE_PIPELINED)

    @property
    def shuffle_compression(self) -> str:
        return self._get(SHUFFLE_COMPRESSION)

    @property
    def shuffle_store(self) -> str:
        return self._get(SHUFFLE_STORE)

    @property
    def shuffle_replication(self) -> str:
        return self._get(SHUFFLE_REPLICATION)

    @property
    def shuffle_external_path(self) -> str:
        return self._get(SHUFFLE_EXTERNAL_PATH)

    @property
    def shuffle_local_transport(self) -> str:
        return self._get(SHUFFLE_LOCAL_TRANSPORT)

    @property
    def shuffle_fetch_batched(self) -> bool:
        return self._get(SHUFFLE_FETCH_BATCHED)

    @property
    def shuffle_locality_enabled(self) -> bool:
        return self._get(SHUFFLE_LOCALITY_ENABLED)

    @property
    def shuffle_locality_wait_seconds(self) -> float:
        return self._get(SHUFFLE_LOCALITY_WAIT_S)

    @property
    def shuffle_pipelined(self) -> bool:
        return self._get(SHUFFLE_PIPELINED)

    @property
    def shuffle_pipelined_min_fraction(self) -> float:
        return self._get(SHUFFLE_PIPELINED_MIN_FRACTION)

    @property
    def aqe_enabled(self) -> bool:
        return self._get(AQE_ENABLED)

    @property
    def aqe_coalesce_enabled(self) -> bool:
        return self._get(AQE_COALESCE_ENABLED)

    @property
    def aqe_broadcast_enabled(self) -> bool:
        return self._get(AQE_BROADCAST_ENABLED)

    @property
    def aqe_skew_enabled(self) -> bool:
        return self._get(AQE_SKEW_ENABLED)

    @property
    def aqe_target_partition_bytes(self) -> int:
        return self._get(AQE_TARGET_PARTITION_BYTES)

    @property
    def aqe_broadcast_threshold_bytes(self) -> int:
        return self._get(AQE_BROADCAST_THRESHOLD_BYTES)

    @property
    def aqe_skew_factor(self) -> float:
        return self._get(AQE_SKEW_FACTOR)

    @property
    def aqe_max_splits(self) -> int:
        return self._get(AQE_MAX_SPLITS)

    @property
    def aqe_coalesce_min_partitions(self) -> int:
        return self._get(AQE_COALESCE_MIN_PARTITIONS)

    @property
    def executor_drain_timeout_seconds(self) -> float:
        return self._get(EXECUTOR_DRAIN_TIMEOUT_S)

    @property
    def task_max_attempts(self) -> int:
        return self._get(TASK_MAX_ATTEMPTS)

    @property
    def task_timeout_seconds(self) -> float:
        return self._get(TASK_TIMEOUT_S)

    @property
    def speculation_enabled(self) -> bool:
        return self._get(SPECULATION_ENABLED)

    @property
    def speculation_interval_seconds(self) -> float:
        return self._get(SPECULATION_INTERVAL_S)

    @property
    def speculation_multiplier(self) -> float:
        return self._get(SPECULATION_MULTIPLIER)

    @property
    def speculation_min_completed_fraction(self) -> float:
        return self._get(SPECULATION_MIN_COMPLETED_FRACTION)

    @property
    def speculation_min_runtime_seconds(self) -> float:
        return self._get(SPECULATION_MIN_RUNTIME_S)

    @property
    def speculation_max_copies_per_stage(self) -> int:
        return self._get(SPECULATION_MAX_COPIES_PER_STAGE)

    @property
    def stage_max_attempts(self) -> int:
        return self._get(STAGE_MAX_ATTEMPTS)

    @property
    def executor_quarantine_threshold(self) -> int:
        return self._get(EXECUTOR_QUARANTINE_THRESHOLD)

    @property
    def executor_quarantine_window_s(self) -> float:
        return self._get(EXECUTOR_QUARANTINE_WINDOW_S)

    @property
    def executor_quarantine_backoff_s(self) -> float:
        return self._get(EXECUTOR_QUARANTINE_BACKOFF_S)

    @property
    def client_job_timeout_seconds(self) -> float:
        return self._get(CLIENT_JOB_TIMEOUT_S)

    @property
    def client_poll_interval_seconds(self) -> float:
        return self._get(CLIENT_POLL_INTERVAL_S)

    @property
    def client_poll_max_interval_seconds(self) -> float:
        return self._get(CLIENT_POLL_MAX_INTERVAL_S)

    @property
    def client_rpc_retries(self) -> int:
        return self._get(CLIENT_RPC_RETRIES)

    @property
    def tenant_id(self) -> str:
        return self._get(TENANT_ID)

    @property
    def tenant_priority(self) -> str:
        return self._get(TENANT_PRIORITY)

    @property
    def tenant_weight(self) -> float:
        return self._get(TENANT_WEIGHT)

    @property
    def tenant_max_running_jobs(self) -> int:
        return self._get(TENANT_MAX_RUNNING_JOBS)

    @property
    def admission_enabled(self) -> bool:
        return self._get(ADMISSION_ENABLED)

    @property
    def admission_max_running_jobs(self) -> int:
        return self._get(ADMISSION_MAX_RUNNING_JOBS)

    @property
    def admission_max_queued_jobs(self) -> int:
        return self._get(ADMISSION_MAX_QUEUED_JOBS)

    @property
    def admission_max_queue_wait_seconds(self) -> float:
        return self._get(ADMISSION_MAX_QUEUE_WAIT_S)

    @property
    def admission_shed_policy(self) -> str:
        return self._get(ADMISSION_SHED_POLICY)

    @property
    def admission_max_interactive_bypass(self) -> int:
        return self._get(ADMISSION_MAX_INTERACTIVE_BYPASS)

    @property
    def admission_interactive_headroom(self) -> int:
        return self._get(ADMISSION_INTERACTIVE_HEADROOM)

    @property
    def obs_enabled(self) -> bool:
        return self._get(OBS_ENABLED)

    @property
    def obs_sample_rate(self) -> float:
        return self._get(OBS_SAMPLE_RATE)

    @property
    def obs_buffer_spans(self) -> int:
        return self._get(OBS_BUFFER_SPANS)

    @property
    def obs_slo_job_latency_seconds(self) -> float:
        return self._get(OBS_SLO_JOB_LATENCY_S)

    @property
    def autoscaler_enabled(self) -> bool:
        return self._get(AUTOSCALER_ENABLED)

    @property
    def autoscaler_min_executors(self) -> int:
        return self._get(AUTOSCALER_MIN_EXECUTORS)

    @property
    def autoscaler_max_executors(self) -> int:
        return self._get(AUTOSCALER_MAX_EXECUTORS)

    @property
    def cache_enabled(self) -> bool:
        return self._get(CACHE_ENABLED)

    @property
    def cache_max_bytes(self) -> int:
        return self._get(CACHE_MAX_BYTES)

    @property
    def cache_ttl_seconds(self) -> float:
        return self._get(CACHE_TTL_S)

    @property
    def cache_policy_enabled(self) -> bool:
        return self._get(CACHE_POLICY_ENABLED)

    @property
    def cache_policy_shadow_fraction(self) -> float:
        return self._get(CACHE_POLICY_SHADOW_FRACTION)

    def to_dict(self) -> dict[str, str]:
        return dict(self.settings)

    @staticmethod
    def from_dict(d: dict[str, str]) -> "BallistaConfig":
        return BallistaConfig(dict(d))


class BallistaConfigBuilder:
    def __init__(self) -> None:
        self._settings: dict[str, str] = {}

    def set(self, key: str, value: str) -> "BallistaConfigBuilder":
        self._settings[key] = str(value)
        return self

    def build(self) -> BallistaConfig:
        return BallistaConfig(self._settings)
