"""User-defined function registry and plugin loading.

Counterpart of the reference's UDF plugin system
(``core/src/plugin/mod.rs:36-82`` trait + declare_plugin! dlopen machinery,
``core/src/plugin/udf.rs:29-55`` UDFPlugin trait + manager,
``core/src/plugin/plugin_manager.rs`` GlobalPluginManager singleton) and of
the Python bindings' UDF/UDAF wrappers (``python/src/udf.rs``, ``udaf.rs``).

Rust plugins are ``.so`` files exposing a registrar; the Python-native
equivalent here is a *plugin directory* of ``.py`` modules each exposing
``register_udfs(registry)``, loaded by :func:`load_udf_plugins` — the role
``ballista.plugin_dir`` plays in the reference (``core/src/config.rs:36``).

Resolution model (mirrors the reference): the client/scheduler session
resolves names at planning time from its session registry; executors
resolve at evaluation time from the process-global registry, which their
binary populates from the plugin dir.  Plans ship only the UDF *name*
(``UdfNode`` in ballista.proto), never code.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

import pyarrow as pa

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class ScalarUDF:
    """A vectorized scalar function: ``fn(*arrays) -> array``.

    ``fn`` receives one ``pa.Array`` per argument (full batch column) and
    must return a ``pa.Array`` of ``return_type`` with the same length.
    """

    name: str
    fn: Callable[..., pa.Array]
    input_types: tuple
    return_type: pa.DataType

    def __post_init__(self):
        object.__setattr__(self, "name", self.name.lower())


@dataclass(frozen=True)
class AggregateUDF:
    """A user aggregate: ``fn(values: pa.Array) -> python scalar`` applied
    to each group's values (nulls included; filter inside if undesired).

    Executed single-stage after a hash repartition on the group keys (the
    same strategy the engine uses for ``count_distinct``), so the function
    never needs a partial/merge decomposition.
    """

    name: str
    fn: Callable[[pa.Array], object]
    input_type: pa.DataType
    return_type: pa.DataType

    def __post_init__(self):
        object.__setattr__(self, "name", self.name.lower())


class UdfRegistry:
    def __init__(self, parent: Optional["UdfRegistry"] = None):
        self._scalar: dict[str, ScalarUDF] = {}
        self._aggregate: dict[str, AggregateUDF] = {}
        self._parent = parent
        self._lock = threading.Lock()

    # ---------------------------------------------------------- register
    # Last registration wins, like the reference's GlobalPluginManager
    # singleton; re-registering a name with a DIFFERENT callable is logged
    # because concurrent sessions would silently share the newest impl.
    def register_scalar(self, udf: ScalarUDF) -> None:
        with self._lock:
            old = self._scalar.get(udf.name)
            if old is not None and old.fn is not udf.fn:
                log.warning(
                    "scalar UDF %r re-registered with a different "
                    "implementation; all sessions now resolve the new one",
                    udf.name,
                )
            self._scalar[udf.name] = udf

    def register_aggregate(self, udaf: AggregateUDF) -> None:
        with self._lock:
            old = self._aggregate.get(udaf.name)
            if old is not None and old.fn is not udaf.fn:
                log.warning(
                    "aggregate UDF %r re-registered with a different "
                    "implementation; all sessions now resolve the new one",
                    udaf.name,
                )
            self._aggregate[udaf.name] = udaf

    # ------------------------------------------------------------ lookup
    def scalar(self, name: str) -> Optional[ScalarUDF]:
        with self._lock:
            u = self._scalar.get(name.lower())
        if u is None and self._parent is not None:
            return self._parent.scalar(name)
        return u

    def aggregate(self, name: str) -> Optional[AggregateUDF]:
        with self._lock:
            u = self._aggregate.get(name.lower())
        if u is None and self._parent is not None:
            return self._parent.aggregate(name)
        return u

    def scalar_names(self) -> list[str]:
        names = set(self._scalar)
        if self._parent is not None:
            names |= set(self._parent.scalar_names())
        return sorted(names)

    def aggregate_names(self) -> list[str]:
        names = set(self._aggregate)
        if self._parent is not None:
            names |= set(self._parent.aggregate_names())
        return sorted(names)


_GLOBAL = UdfRegistry()


def global_registry() -> UdfRegistry:
    """Process-wide registry (reference: GlobalPluginManager singleton)."""
    return _GLOBAL


_LOADED_DIRS: set = set()


def load_udf_plugins(plugin_dir: str, registry: Optional[UdfRegistry] = None) -> int:
    """Import every ``*.py`` in ``plugin_dir`` and call its
    ``register_udfs(registry)`` hook.  Returns the number of plugins loaded.

    Counterpart of UDFPluginManager scanning ``plugin_dir`` for ``.so``
    files (``core/src/plugin/udf.rs:45-55``).  When loading into the
    global registry, each directory is loaded at most once per process —
    sessions are created per query on the scheduler, and plugin modules
    must not re-execute on that path.
    """
    registry = registry or _GLOBAL
    if not plugin_dir or not os.path.isdir(plugin_dir):
        return 0
    if registry is _GLOBAL:
        real = os.path.realpath(plugin_dir)
        if real in _LOADED_DIRS:
            return 0
        _LOADED_DIRS.add(real)
    count = 0
    for fname in sorted(os.listdir(plugin_dir)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        path = os.path.join(plugin_dir, fname)
        mod_name = f"ballista_udf_plugin_{fname[:-3]}"
        try:
            spec = importlib.util.spec_from_file_location(mod_name, path)
            assert spec is not None and spec.loader is not None
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            hook = getattr(mod, "register_udfs", None)
            if hook is None:
                log.warning("plugin %s has no register_udfs(registry) hook", path)
                continue
            hook(registry)
            count += 1
            log.info("loaded UDF plugin %s", path)
        except Exception as e:
            log.error("failed to load UDF plugin %s: %s", path, e)
    return count
