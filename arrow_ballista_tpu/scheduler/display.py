"""Stage plan + metrics rendering on the scheduler.

Counterpart of the reference's ``scheduler/src/display.rs:31-160``:
``print_stage_metrics`` logs a completed stage's plan annotated with the
combined per-operator MetricsSets the executors reported back;
``DisplayableBallistaExecutionPlan`` is the reusable renderer.
"""

from __future__ import annotations

import logging
from typing import Dict

log = logging.getLogger(__name__)


class DisplayableBallistaExecutionPlan:
    """Renders a stage plan with the stage's combined metrics attached to
    each operator line (metrics are keyed by operator display name)."""

    def __init__(self, plan, stage_metrics: Dict[str, Dict[str, int]]):
        self.plan = plan
        self.stage_metrics = stage_metrics

    def indent(self) -> str:
        lines: list[str] = []

        def walk(op, depth: int) -> None:
            name = str(op)
            # stage metrics are keyed by operator class (collect_plan_metrics
            # in task_status.py); metrics of same-class operators in one
            # stage arrive merged
            metrics = self.stage_metrics.get(type(op).__name__) or self.stage_metrics.get(name)
            suffix = f", metrics=[{_fmt_metrics(metrics)}]" if metrics else ""
            lines.append("  " * depth + name + suffix)
            for c in op.children():
                walk(c, depth + 1)

        walk(self.plan, 0)
        return "\n".join(lines)


def _fmt_metrics(m: Dict[str, int]) -> str:
    parts = []
    for k in sorted(m):
        v = m[k]
        if k.endswith("_ns"):
            parts.append(f"{k[:-3]}={v / 1e6:.3f}ms")
        else:
            parts.append(f"{k}={v}")
    return ", ".join(parts)


def print_stage_metrics(
    job_id: str, stage_id: int, plan, stage_metrics: Dict[str, Dict[str, int]]
) -> None:
    """Log the annotated plan when a stage completes
    (reference: display.rs:31-60, called from the stage-completion path)."""
    log.info(
        "=== [%s/%s] Stage finished, physical plan with metrics ===\n%s",
        job_id,
        stage_id,
        DisplayableBallistaExecutionPlan(plan, stage_metrics).indent(),
    )
