"""SchedulerGrpc servicer: the nine RPC handlers.

Counterpart of the reference's ``scheduler/src/scheduler_server/grpc.rs``:

* ``PollWork`` (pull mode, `:56-175`) — heartbeat + piggybacked statuses +
  at most one task filled into the polling executor's slot;
* ``RegisterExecutor`` (`:177-233`) — push mode reserves every slot and
  offers them immediately;
* ``HeartBeatFromExecutor`` / ``UpdateTaskStatus`` / ``ExecutorStopped`` /
  ``CancelJob`` (`:235-292`, tail);
* ``GetFileMetadata`` (`:294-345`) — schema inference for parquet/csv;
* ``ExecuteQuery`` (`:347-460`) — session create/update, plan decode, job
  id mint, submit; an empty query only mints a session id (how
  ``BallistaContext::remote`` bootstraps);
* ``GetJobStatus``.
"""

from __future__ import annotations

import json
import logging

import grpc
import pyarrow as pa

from ..config import TaskSchedulingPolicy
from ..proto import pb
from ..serde import BallistaCodec, schema_to_bytes
from ..serde.scheduler_types import ExecutorMetadata, ExecutorSpecification
from .server import SchedulerServer
from .task_status import job_status_to_proto, task_info_from_proto

log = logging.getLogger(__name__)


def _registration_to_metadata(reg: pb.ExecutorRegistration, peer: str) -> ExecutorMetadata:
    """The executor may omit its host; fall back to the connection peer
    (reference: grpc.rs optional_host handling)."""
    host = reg.host if reg.has_host else (peer or "127.0.0.1")
    return ExecutorMetadata(
        id=reg.id,
        host=host,
        flight_port=reg.flight_port,
        grpc_port=reg.grpc_port,
        specification=ExecutorSpecification.from_proto(reg.specification),
    )


def _peer_host(context) -> str:
    try:
        peer = context.peer()  # e.g. "ipv4:127.0.0.1:53210"
        if peer.startswith(("ipv4:", "ipv6:")):
            hostport = peer.split(":", 1)[1]
            return hostport.rsplit(":", 1)[0].strip("[]")
    except Exception:  # noqa: BLE001
        pass
    return ""


class SchedulerGrpcService:
    """Bound to a grpc.Server via proto.rpc.add_scheduler_servicer."""

    def __init__(self, server: SchedulerServer):
        self.server = server

    # ------------------------------------------------------------ pull mode
    def PollWork(self, request: pb.PollWorkParams, context) -> pb.PollWorkResult:
        meta = _registration_to_metadata(request.metadata, _peer_host(context))
        statuses = [task_info_from_proto(s) for s in request.task_status]
        task = self.server.poll_work(meta, request.can_accept_task, statuses)
        result = pb.PollWorkResult()
        if task is not None:
            result.task.CopyFrom(task)
            result.has_task = True
        return result

    # ------------------------------------------------------------ push mode
    def RegisterExecutor(
        self, request: pb.RegisterExecutorParams, context
    ) -> pb.RegisterExecutorResult:
        meta = _registration_to_metadata(request.metadata, _peer_host(context))
        reserve = self.server.policy == TaskSchedulingPolicy.PUSH_STAGED
        reservations = self.server.state.executor_manager.register_executor(
            meta, reserve
        )
        if reservations:
            self.server.offer_reservation(reservations)
        log.info(
            "registered executor %s at %s:%d (%d slots, policy=%s)",
            meta.id,
            meta.host,
            meta.grpc_port or meta.flight_port,
            meta.specification.task_slots,
            self.server.policy.value,
        )
        return pb.RegisterExecutorResult(success=True)

    def HeartBeatFromExecutor(
        self, request: pb.HeartBeatParams, context
    ) -> pb.HeartBeatResult:
        import time

        from .executor_manager import ExecutorHeartbeat

        em = self.server.state.executor_manager
        # a scheduler restarted on a memory backend has heartbeats but no
        # metadata for surviving (adopted) executors: tell them to
        # re-register so slots/endpoints rebuild, instead of silently
        # heartbeating into a registry that can never dispatch to them
        reregister = False
        try:
            em.get_executor_metadata(request.executor_id)
        except Exception:  # noqa: BLE001 - unknown executor
            reregister = True
        em.save_heartbeat(
            ExecutorHeartbeat(request.executor_id, time.time(), "active")
        )
        if request.spans_json:
            from ..obs.recorder import trace_store

            trace_store().add_json(request.spans_json)
        if request.telemetry_json:
            # tolerant: an old executor ships nothing, a broken one may
            # ship garbage — the store counts a parse error and moves on
            self.server.state.telemetry.record_executor(
                request.executor_id, request.telemetry_json
            )
        return pb.HeartBeatResult(reregister=reregister)

    def UpdateTaskStatus(
        self, request: pb.UpdateTaskStatusParams, context
    ) -> pb.UpdateTaskStatusResult:
        statuses = [task_info_from_proto(s) for s in request.task_status]
        self.server.update_task_status(request.executor_id, statuses)
        return pb.UpdateTaskStatusResult(success=True)

    # ------------------------------------------------------------- queries
    def GetFileMetadata(
        self, request: pb.GetFileMetadataParams, context
    ) -> pb.GetFileMetadataResult:
        ft = (request.file_type or "parquet").lower()
        if ft == "parquet":
            import pyarrow.parquet as pq

            schema = pq.read_schema(request.path)
        elif ft == "csv":
            import pyarrow.csv as pcsv

            reader = pcsv.open_csv(request.path)
            schema = reader.schema
        else:
            context.abort(
                __import__("grpc").StatusCode.INVALID_ARGUMENT,
                f"unsupported file type {ft!r}",
            )
            return pb.GetFileMetadataResult()
        return pb.GetFileMetadataResult(schema=schema_to_bytes(schema))

    def ExecuteQuery(
        self, request: pb.ExecuteQueryParams, context
    ) -> pb.ExecuteQueryResult:
        settings = {kv.key: kv.value for kv in request.settings}
        sm = self.server.state.session_manager
        if request.session_id:
            session_ctx = sm.update_session(request.session_id, settings)
        else:
            session_ctx = sm.create_session(settings)

        which = request.WhichOneof("query")
        if which is None:
            # session-bootstrap call (reference: client context.rs:103-119)
            return pb.ExecuteQueryResult(
                job_id="", session_id=session_ctx.session_id
            )
        if which == "logical_plan":
            plan = BallistaCodec.decode_logical(request.logical_plan)
        else:
            plan = session_ctx.sql(request.sql).logical_plan()

        token = request.idempotency_token
        if token:
            # a retried submit (client failover, ISSUE 20) re-attaches to
            # the job its first attempt already created instead of
            # double-running it; the check-then-mint runs under a token-
            # scoped backend lock so two racing retries agree on one id
            from .backend import Keyspace
            from .queue_wal import lookup_token, record_token, token_key

            backend = self.server.state.backend
            with backend.lock(Keyspace.QueueWal, token_key(token)):
                prior = lookup_token(backend, token)
                if prior is not None:
                    log.info(
                        "deduplicated resubmit of job %s (token %s)",
                        prior, token,
                    )
                    return pb.ExecuteQueryResult(
                        job_id=prior, session_id=session_ctx.session_id
                    )
                job_id = self.server.state.task_manager.generate_job_id()
                record_token(backend, token, job_id)
            self._maybe_purge_tokens()
        else:
            job_id = self.server.state.task_manager.generate_job_id()
        self.server.submit_job(job_id, session_ctx.session_id, plan)
        log.info("queued job %s (session %s)", job_id, session_ctx.session_id)
        return pb.ExecuteQueryResult(
            job_id=job_id, session_id=session_ctx.session_id
        )

    _token_submits = 0

    def _maybe_purge_tokens(self) -> None:
        """Opportunistic TTL sweep of idempotency tokens — every ~100
        tokened submits, so the keyspace cannot grow unbounded."""
        self._token_submits += 1
        if self._token_submits % 100:
            return
        from .queue_wal import purge_stale_tokens

        try:
            purge_stale_tokens(self.server.state.backend)
        except Exception:  # noqa: BLE001 - sweep must not fail a submit
            log.warning("idempotency-token purge failed", exc_info=True)

    def GetShuffleLocationDelta(
        self, request: pb.ShuffleLocationDeltaParams, context
    ) -> pb.ShuffleLocationDelta:
        """Streaming pipelined execution (ISSUE 15): pull-mode executors
        poll the per-producer shuffle-location feed for their tailing
        consumer tasks (push mode gets the same deltas proactively via
        UpdateShuffleLocations)."""
        d = self.server.state.task_manager.get_shuffle_location_delta(
            request.job_id, request.stage_id, request.from_index
        )
        resp = pb.ShuffleLocationDelta(
            job_id=request.job_id,
            stage_id=request.stage_id,
            from_index=d["from_index"],
            complete=d["complete"],
            valid=d["valid"],
            epoch=d["epoch"],
        )
        for loc in d["locations"]:
            resp.locations.add().CopyFrom(loc.to_proto())
        return resp

    def GetJobStatus(
        self, request: pb.GetJobStatusParams, context
    ) -> pb.GetJobStatusResult:
        tm = self.server.state.task_manager
        status = tm.get_job_status(request.job_id)
        result = pb.GetJobStatusResult()
        if status is None:
            # unknown job: surface as queued (it may still be planning)
            result.status.queued.SetInParent()
        else:
            result.status.CopyFrom(job_status_to_proto(status))
        if request.include_progress and status is not None:
            # live progress piggybacks on the poll the client already
            # pays for (query doctor, ISSUE 13)
            progress = tm.get_job_progress(request.job_id)
            if progress is not None:
                result.progress_json = json.dumps(
                    progress, default=str
                ).encode()
        if request.include_profile and status is not None:
            report = self._job_report(request.job_id)
            if report is not None:
                result.profile_json = json.dumps(
                    report, default=str
                ).encode()
        return result

    def _job_report(self, job_id: str) -> dict | None:
        """Diagnosis bundle for ``include_profile`` — the same
        ``obs.doctor.job_report`` the REST profile/critical_path routes
        serve, so explain_analyze reads identical numbers."""
        from ..obs.doctor import job_report
        from ..obs.recorder import spans_for_job

        detail = self.server.state.task_manager.get_job_detail(job_id)
        if detail is None or "stages" not in detail:
            return None
        journal = self.server.state.events
        events = journal.for_job(job_id) if journal.enabled else []
        return job_report(
            detail, spans_for_job(job_id), events,
            cluster=self.server.doctor_cluster_context(),
        )

    # ------------------------------------------------------------ lifecycle
    def ExecutorStopped(
        self, request: pb.ExecutorStoppedParams, context
    ) -> pb.ExecutorStoppedResult:
        log.info(
            "executor %s stopped: %s", request.executor_id, request.reason
        )
        self.server.executor_lost(request.executor_id, request.reason)
        return pb.ExecutorStoppedResult()

    def CancelJob(self, request: pb.CancelJobParams, context) -> pb.CancelJobResult:
        self.server.cancel_job(request.job_id)
        return pb.CancelJobResult(cancelled=True)

    def DecommissionExecutor(
        self, request: pb.ExecutorStoppedParams, context
    ) -> pb.ExecutorStoppedResult:
        """Graceful decommission (ISSUE 6): operator-initiated drain —
        reuses the ExecutorStopped message shapes on the wire."""
        ok = self.server.decommission_executor(
            request.executor_id,
            request.reason or "decommissioned by operator",
        )
        if not ok:
            # an unknown id must not look like a successful drain: the
            # operator would terminate the instance believing its shuffle
            # data was uploaded
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"unknown executor {request.executor_id!r}",
            )
        return pb.ExecutorStoppedResult()
