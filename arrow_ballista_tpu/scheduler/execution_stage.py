"""Stage state machine.

Counterpart of the reference's
``scheduler/src/state/execution_graph/execution_stage.rs:44-58``:

              to_resolved()          start
  UnResolved ────────────▶ Resolved ──────▶ Running ──▶ Completed
      ▲                        ▲               │  ▲          │
      │ rollback (lost input)  │ reset_tasks   │  │          │ re-run
      └────────────────────────┴───────────────┘  └──────────┘
                              Failed ◀── task failure

A stage's *plan* is a ``ShuffleWriterExec`` subtree; its *tasks* are the
plan's input partitions.  ``inputs`` tracks, per producing stage, the
map-side partition locations accumulated so far.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..errors import SchedulerError
from ..exec.operators import ExecutionPlan
from ..serde.scheduler_types import (
    PartitionId,
    PartitionLocation,
    ShuffleWritePartition,
)
from ..shuffle import ShuffleWriterExec
from .planner import (
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
    rollback_resolved_shuffles,
)


# --------------------------------------------------------------- task status
@dataclass
class TaskInfo:
    """Scheduler-side view of one task attempt (reference: proto TaskStatus)."""

    partition_id: PartitionId
    state: str  # "running" | "completed" | "failed"
    executor_id: str = ""
    error: str = ""
    partitions: List[ShuffleWritePartition] = field(default_factory=list)
    metrics: List[tuple] = field(default_factory=list)  # (operator, {k: v})
    attempt: int = 0  # which attempt this status describes (0-based)
    fetch_retries: int = 0  # shuffle-fetch retries this attempt paid
    # finished spans piggybacked from the executor (obs/recorder.py span
    # dicts); absorbed into the scheduler's TraceStore, never persisted
    spans: List[dict] = field(default_factory=list)
    # True for the scheduler-launched duplicate copy racing a straggler
    # (TaskDefinition.speculative, echoed back in TaskStatus.speculative)
    speculative: bool = False


@dataclass
class StageInput:
    """Accumulated output of one producing stage, as seen by a consumer
    (reference: execution_stage.rs StageOutput)."""

    complete: bool = False
    # output partition index -> locations from each completed map task
    partition_locations: Dict[int, List[PartitionLocation]] = field(
        default_factory=dict
    )

    def add_partition(self, loc: PartitionLocation) -> None:
        self.partition_locations.setdefault(loc.partition_id.partition_id, []).append(
            loc
        )


# ------------------------------------------------------------------- stages
@dataclass
class UnresolvedStage:
    stage_id: int
    plan: ShuffleWriterExec
    output_links: List[int] = field(default_factory=list)
    inputs: Dict[int, StageInput] = field(default_factory=dict)
    # AQE decision summary (scheduler/adaptive.py): {tasks_before,
    # tasks_after, coalesced_groups, skew_splits, broadcast}.  Non-empty
    # means the plan was already rewritten — replanning is idempotent
    # across rollback/re-resolve.  Carried through every transition and
    # merged into stage_metrics as __aqe__ at to_completed so it
    # persists with the graph and surfaces in the job profile.
    aqe: Dict[str, int] = field(default_factory=dict)

    @property
    def partitions(self) -> int:
        return self.plan.output_partitioning().n

    def add_input_partitions(
        self, stage_id: int, locations: List[PartitionLocation]
    ) -> None:
        if stage_id not in self.inputs:
            raise SchedulerError(
                f"stage {self.stage_id} has no input from stage {stage_id}"
            )
        for loc in locations:
            self.inputs[stage_id].add_partition(loc)

    def remove_input_partitions(self, executor_id: str) -> None:
        """Strip locations served by a lost executor and mark those inputs
        incomplete (reference: execution_stage.rs remove_input_partitions)."""
        for inp in self.inputs.values():
            changed = False
            for p, locs in inp.partition_locations.items():
                kept = [l for l in locs if l.executor_meta.id != executor_id]
                if len(kept) != len(locs):
                    changed = True
                inp.partition_locations[p] = kept
            if changed:
                inp.complete = False

    def complete_input(self, stage_id: int) -> None:
        if stage_id in self.inputs:
            self.inputs[stage_id].complete = True

    def resolvable(self) -> bool:
        return all(i.complete for i in self.inputs.values())

    def to_resolved(
        self, tail_stage_ids: frozenset = frozenset()
    ) -> "ResolvedStage":
        """Resolve against the accumulated input locations.  With
        ``tail_stage_ids`` (pipelined execution) those producers resolve
        to TAILING readers instead — no static locations; the executor
        streams the scheduler's shuffle-location feed — and the stage
        starts while they are still running."""
        tail = frozenset(tail_stage_ids)
        locations: Dict[int, List[List[PartitionLocation]]] = {}
        for shuffle in find_unresolved_shuffles(self.plan):
            if shuffle.stage_id in tail:
                continue
            inp = self.inputs.get(shuffle.stage_id)
            if inp is None or not inp.complete:
                raise SchedulerError(
                    f"stage {self.stage_id}: input stage {shuffle.stage_id} "
                    "is not complete"
                )
            locations[shuffle.stage_id] = [
                sorted(
                    inp.partition_locations.get(p, []),
                    key=lambda l: l.path,
                )
                for p in range(shuffle.output_partition_count)
            ]
        resolved_plan = (
            remove_unresolved_shuffles(self.plan, locations, tail)
            if locations or tail
            else self.plan
        )
        return ResolvedStage(
            self.stage_id,
            resolved_plan,
            list(self.output_links),
            dict(self.inputs),
            aqe=dict(self.aqe),
            tail_inputs=set(tail),
        )


@dataclass
class ResolvedStage:
    stage_id: int
    plan: ShuffleWriterExec
    output_links: List[int] = field(default_factory=list)
    inputs: Dict[int, StageInput] = field(default_factory=dict)
    aqe: Dict[str, int] = field(default_factory=dict)
    # query-doctor anchor (ISSUE 13): wall-clock ns when this stage became
    # dispatchable (every producer committed; graph build for leaves).
    # 0 = unknown (decoded graphs) — attribution degrades, never fails.
    ready_unix_ns: int = 0
    # pipelined execution (ISSUE 15): producer stage ids this stage reads
    # through TAILING readers (resolved before the producer completed).
    # Empty on the barrier path.  In-memory only — a partially-resolved
    # stage persists as Unresolved (see ExecutionGraph.encode) so a
    # restarted scheduler re-resolves against real state.
    tail_inputs: set = field(default_factory=set)

    @property
    def partitions(self) -> int:
        return self.plan.output_partitioning().n

    def to_running(self) -> "RunningStage":
        return RunningStage(
            self.stage_id,
            self.plan,
            list(self.output_links),
            dict(self.inputs),
            [None] * self.partitions,
            aqe=dict(self.aqe),
            ready_unix_ns=self.ready_unix_ns,
            tail_inputs=set(self.tail_inputs),
            started_on_partial=bool(self.tail_inputs),
        )

    def to_unresolved(self) -> UnresolvedStage:
        """Roll back for executor-loss recovery.  The rolled-back plan
        keeps its AQE selections (rollback_resolved_shuffles) and the
        ``aqe`` marker, so re-resolution reuses the rewritten layout."""
        return UnresolvedStage(
            self.stage_id,
            rollback_resolved_shuffles(self.plan),
            list(self.output_links),
            dict(self.inputs),
            aqe=dict(self.aqe),
        )


@dataclass
class RunningStage:
    stage_id: int
    plan: ShuffleWriterExec
    output_links: List[int]
    inputs: Dict[int, StageInput]
    task_statuses: List[Optional[TaskInfo]]
    stage_metrics: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # fault tolerance (partition -> value); sparse so stage transitions
    # constructed positionally keep working
    task_attempts: Dict[int, int] = field(default_factory=dict)
    task_failures: Dict[int, List[str]] = field(default_factory=dict)
    # the executor that last failed the partition: its retry never goes
    # back there while another live executor exists
    task_exclusions: Dict[int, str] = field(default_factory=dict)
    task_fetch_retries: Dict[int, int] = field(default_factory=dict)
    # ---- speculative execution + deadlines (all transient: Running
    # stages persist as Resolved, so none of this survives restart) ----
    # partition -> the PRIMARY attempt's executor id: the speculation
    # scan flagged it a straggler; the normal dispatch path hands the
    # duplicate to any OTHER executor
    speculation_requests: Dict[int, str] = field(default_factory=dict)
    # partition -> the duplicate attempt currently running (at most one
    # shadow per partition; same attempt number as the primary)
    speculative_statuses: Dict[int, "TaskInfo"] = field(default_factory=dict)
    # monotonic dispatch anchors (primary / shadow) for runtime stats,
    # the straggler threshold and the deadline reaper
    task_started_mono: Dict[int, float] = field(default_factory=dict)
    spec_started_mono: Dict[int, float] = field(default_factory=dict)
    # runtimes (seconds) of this stage's committed completions: the
    # median feeds the speculation threshold
    completed_runtime_s: List[float] = field(default_factory=list)
    # attempts granted beyond ballista.task.max_attempts (deadline reaps
    # bump the attempt counter for staleness but must not consume the
    # task's failure budget)
    task_free_attempts: Dict[int, int] = field(default_factory=dict)
    # cumulative launched/wins/wasted rollup (carried to CompletedStage
    # for the /api/jobs/{id}/profile speculation column)
    spec_stats: Dict[str, int] = field(default_factory=dict)
    # ---- stage skew analytics (ISSUE 7): per-partition inputs for the
    # completion-time reduction.  partition -> committed runtime seconds
    # (the winner's, when a race ran) and -> written bytes {raw, wire};
    # to_completed() reduces them to p50/p99/max-over-median coefficients
    # inside stage_metrics, which already persist past cache eviction
    task_runtime_s: Dict[int, float] = field(default_factory=dict)
    task_bytes: Dict[int, Dict[str, int]] = field(default_factory=dict)
    # AQE decision summary (see UnresolvedStage.aqe)
    aqe: Dict[str, int] = field(default_factory=dict)
    # ---- locality-aware placement (ISSUE 10; populated by
    # ExecutionGraph.revive only when ballista.shuffle.locality_enabled,
    # so knob-off placement is byte-identical to the baseline) ----
    # partition -> normalized host holding the most bytes of its input
    # shuffle partitions (exact sizes from the map-side write stats)
    task_preferred_host: Dict[int, str] = field(default_factory=dict)
    # dispatch rollup: {"local": popped on the preferred host, "any":
    # popped elsewhere after/without the locality wait}
    locality_stats: Dict[str, int] = field(default_factory=dict)
    # wait anchor: tasks may hold out for their preferred host until
    # running_since_mono + locality_wait_s
    running_since_mono: float = field(default_factory=time.monotonic)
    # set when a pop DEFERRED a task for its preferred host (cleared on
    # the next successful pop): the push-mode 1s tick re-mints
    # reservations ONLY for stages that actually turned a slot away —
    # otherwise the timer would double-book slots the event-driven flow
    # already covers, every second
    locality_deferred: bool = False
    # ---- query-doctor timeline anchors (ISSUE 13): everything below is
    # wall-clock (epoch ns) because critical-path attribution subtracts
    # anchors recorded at different points in the job's life and must
    # align with the journal's timestamps; all recorded on the scheduler
    # so one clock serves the whole job.  Reduced to the __stage_timing__
    # / __task_*_us__ synthetic metrics at to_completed (persist past
    # eviction/restart like the skew analytics).
    ready_unix_ns: int = 0
    # partition -> dispatch anchor of the CURRENT attempt (reset with the
    # attempt, so a retry's breakdown reflects the attempt that committed)
    task_dispatch_unix_ns: Dict[int, int] = field(default_factory=dict)
    # ...and of the partition's racing speculative duplicate: when the
    # duplicate wins (or is promoted in place), ITS dispatch anchor
    # replaces the straggler's, so the committed attempt's window never
    # includes the straggler's dead time
    spec_dispatch_unix_ns: Dict[int, int] = field(default_factory=dict)
    # partition -> commit anchor (the winner's completion report)
    task_finish_unix_ns: Dict[int, int] = field(default_factory=dict)
    # ---- pipelined execution (ISSUE 15) ----
    # producer stage ids this stage reads through TAILING readers; fixed
    # for the stage's lifetime (producer completion flips the matching
    # StageInput.complete and the feed's complete flag instead)
    tail_inputs: set = field(default_factory=set)
    # True when the stage was dispatched on partial map output: its task
    # runtimes include stall-on-producer, so the progress ETA median
    # excludes them, and to_completed persists the __pipelined__ marker
    started_on_partial: bool = False

    @property
    def partitions(self) -> int:
        return len(self.task_statuses)

    def available_tasks(self) -> int:
        # pending speculation requests count as dispatchable work so push
        # mode mints slots for them
        return (
            sum(1 for t in self.task_statuses if t is None)
            + len(self.speculation_requests)
        )

    def bump_spec_stat(self, key: str, n: int = 1) -> None:
        self.spec_stats[key] = self.spec_stats.get(key, 0) + n

    def drop_speculative(self, p: int) -> Optional["TaskInfo"]:
        """Forget partition ``p``'s duplicate attempt (loser/failed/reset);
        returns the dropped TaskInfo so the caller can cancel it.
        Promotion sites that need the duplicate's timing anchors read
        ``spec_started_mono`` / ``spec_dispatch_unix_ns`` BEFORE calling
        this."""
        self.spec_started_mono.pop(p, None)
        self.spec_dispatch_unix_ns.pop(p, None)
        self.speculation_requests.pop(p, None)
        return self.speculative_statuses.pop(p, None)

    def update_task_status(self, info: TaskInfo) -> None:
        p = info.partition_id.partition_id
        if not (0 <= p < self.partitions):
            raise SchedulerError(
                f"stage {self.stage_id}: task partition {p} out of range"
            )
        self.task_statuses[p] = info

    def update_task_metrics(self, info: TaskInfo) -> None:
        """Merge one task's per-operator metrics into the combined stage
        metrics (reference: execution_stage.rs RunningStage::update_task_metrics)."""
        for op_name, values in info.metrics:
            agg = self.stage_metrics.setdefault(op_name, {})
            for k, v in values.items():
                agg[k] = agg.get(k, 0) + v

    def is_completed(self) -> bool:
        return all(t is not None and t.state == "completed" for t in self.task_statuses)

    def completed_tasks(self) -> int:
        return sum(
            1 for t in self.task_statuses if t is not None and t.state == "completed"
        )

    def reset_tasks(self, executor_id: str, keep_task=None) -> int:
        """Clear every task that ran on a lost executor; returns count.

        ``keep_task(t)`` (optional) exempts a status from the reset —
        the replica-aware executor-loss path keeps COMPLETED tasks whose
        every output partition has a surviving external copy, so a
        partially-finished stage on a drained executor re-runs nothing.

        Speculation interplay: a duplicate attempt ON the lost executor
        simply disappears (wasted); a duplicate running ELSEWHERE is
        promoted to primary when its primary was on the lost host — the
        partition stays covered without a re-dispatch."""
        for p, si in list(self.speculative_statuses.items()):
            if si.executor_id == executor_id:
                self.drop_speculative(p)
                self.bump_spec_stat("wasted")
        n = 0
        for i, t in enumerate(self.task_statuses):
            if t is not None and t.executor_id == executor_id:
                if keep_task is not None and keep_task(t):
                    continue
                shadow = None
                if t.state == "running":
                    spec_started = self.spec_started_mono.get(i)
                    spec_dispatch = self.spec_dispatch_unix_ns.get(i)
                    shadow = self.drop_speculative(i)
                if shadow is not None:
                    self.task_statuses[i] = shadow
                    if spec_started is not None:
                        self.task_started_mono[i] = spec_started
                    else:
                        self.task_started_mono.pop(i, None)
                    if spec_dispatch is not None:
                        self.task_dispatch_unix_ns[i] = spec_dispatch
                else:
                    self.task_statuses[i] = None
                    self.task_started_mono.pop(i, None)
                    n += 1
        return n

    def to_completed(self) -> "CompletedStage":
        from ..obs.export import (
            AQE_OP,
            stage_skew_metrics,
            stage_timing_metrics,
        )

        # reduce the per-partition runtime/bytes distributions to skew
        # coefficients NOW — stage_metrics persist in the graph proto, so
        # the profile keeps its skew column after cache eviction/restart
        metrics = dict(self.stage_metrics)
        metrics.update(stage_skew_metrics(self.task_runtime_s, self.task_bytes))
        # ...and the critical-path timeline anchors (ready/dispatch/commit
        # per partition) ride the same persistence path
        metrics.update(
            stage_timing_metrics(
                self.ready_unix_ns,
                self.task_dispatch_unix_ns,
                self.task_finish_unix_ns,
            )
        )
        if self.aqe:
            # the replan decision rides the same persistence path as the
            # skew analytics: visible in the profile after eviction/restart
            metrics[AQE_OP] = dict(self.aqe)
        if self.locality_stats:
            # placement hit-rate persists alongside the data-plane
            # local/remote fetch counters (which live in the reader
            # operator's own metrics)
            from ..obs.export import LOCALITY_OP

            metrics[LOCALITY_OP] = dict(self.locality_stats)
        if self.started_on_partial:
            # the stage ran pipelined: persist the marker so progress/ETA
            # and the doctor can tell stall-inflated runtimes apart after
            # eviction/restart
            from ..obs.export import PIPELINED_OP

            metrics[PIPELINED_OP] = {
                "partial_start": 1,
                "tail_inputs": len(self.tail_inputs),
            }
        return CompletedStage(
            self.stage_id,
            self.plan,
            list(self.output_links),
            dict(self.inputs),
            list(self.task_statuses),
            metrics,
            dict(self.task_attempts),
            dict(self.task_fetch_retries),
            spec_stats=dict(self.spec_stats),
        )

    def to_failed(self, error: str) -> "FailedStage":
        return FailedStage(
            self.stage_id,
            self.plan,
            list(self.output_links),
            error,
        )

    def to_resolved(self) -> ResolvedStage:
        """Drop in-flight work (persistence rule: Running is stored as
        Resolved so a restarted scheduler re-dispatches)."""
        return ResolvedStage(
            self.stage_id, self.plan, list(self.output_links),
            dict(self.inputs), aqe=dict(self.aqe),
            ready_unix_ns=self.ready_unix_ns,
            tail_inputs=set(self.tail_inputs),
        )


@dataclass
class CompletedStage:
    stage_id: int
    plan: ShuffleWriterExec
    output_links: List[int]
    inputs: Dict[int, StageInput]
    task_statuses: List[Optional[TaskInfo]]
    stage_metrics: Dict[str, Dict[str, int]] = field(default_factory=dict)
    task_attempts: Dict[int, int] = field(default_factory=dict)
    task_fetch_retries: Dict[int, int] = field(default_factory=dict)
    # speculation rollup inherited from the RunningStage (profile column)
    spec_stats: Dict[str, int] = field(default_factory=dict)

    @property
    def partitions(self) -> int:
        return len(self.task_statuses)

    def completed_tasks(self) -> int:
        return sum(
            1 for t in self.task_statuses if t is not None and t.state == "completed"
        )

    def output_partition_bytes(self) -> Dict[int, int]:
        """EXACT wire bytes per OUTPUT (reduce) partition this stage
        wrote, summed over the committed winners' per-fragment stats —
        the direct sizing input for adaptive re-planning.  Unlike the
        ``__task_bytes_*__`` skew maps (keyed by MAP task, reduced to
        quantiles), this is the reduce-side distribution, recomputed
        from ``task_statuses`` (which persist in the graph proto), so
        AQE never reconstructs sizes from metric rollups."""
        return self._sum_output_partitions("num_bytes")

    def output_partition_rows(self) -> Dict[int, int]:
        """Row counterpart of :meth:`output_partition_bytes`."""
        return self._sum_output_partitions("num_rows")

    def _sum_output_partitions(self, attr: str) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for t in self.task_statuses:
            if t is None:
                continue
            for p in t.partitions:
                out[p.partition_id] = out.get(p.partition_id, 0) + int(
                    getattr(p, attr) or 0
                )
        return out

    def to_running(self) -> RunningStage:
        """Re-run after its shuffle files were lost with an executor."""
        from ..obs.export import (
            AQE_OP,
            STAGE_TIMING_OP,
            TASK_BYTES_RAW_OP,
            TASK_BYTES_WIRE_OP,
            TASK_DISPATCH_OP,
            TASK_FINISH_OP,
            TASK_RUNTIME_OP,
        )

        # Seed the skew inputs from the persisted per-partition maps so
        # re-completion reduces over the FULL distribution (re-run
        # partitions overwrite their own entries) — otherwise a 1-task
        # lost-shuffle re-run would overwrite a 100-partition stage's
        # skew with partitions=1.  ms + 0.5 survives to_completed's
        # int(v * 1e3) truncation exactly (v/1e3*1e3 can land just
        # below the integer).
        runtime_s = {
            int(p): (v + 0.5) / 1e3
            for p, v in self.stage_metrics.get(TASK_RUNTIME_OP, {}).items()
        }
        wire = self.stage_metrics.get(TASK_BYTES_WIRE_OP, {})
        raw = self.stage_metrics.get(TASK_BYTES_RAW_OP, {})
        task_bytes = {
            int(p): {"wire": int(wire.get(p, 0)), "raw": int(raw.get(p, 0))}
            for p in set(wire) | set(raw)
        }
        return RunningStage(
            self.stage_id,
            self.plan,
            list(self.output_links),
            dict(self.inputs),
            list(self.task_statuses),
            dict(self.stage_metrics),
            dict(self.task_attempts),
            {},
            {},
            dict(self.task_fetch_retries),
            spec_stats=dict(self.spec_stats),
            task_runtime_s=runtime_s,
            task_bytes=task_bytes,
            aqe=dict(self.stage_metrics.get(AQE_OP, {})),
            # seed the timeline anchors back from the persisted maps so a
            # partial re-run re-reduces the FULL timing distribution (the
            # same rule the skew seeds follow); re-run partitions simply
            # overwrite their own entries at re-dispatch/re-commit
            ready_unix_ns=self.stage_metrics.get(STAGE_TIMING_OP, {}).get(
                "ready_us", 0
            )
            * 1000,
            task_dispatch_unix_ns={
                int(p): int(v) * 1000
                for p, v in self.stage_metrics.get(TASK_DISPATCH_OP, {}).items()
            },
            task_finish_unix_ns={
                int(p): int(v) * 1000
                for p, v in self.stage_metrics.get(TASK_FINISH_OP, {}).items()
            },
        )

    def reset_tasks(self, executor_id: str) -> int:
        n = 0
        for i, t in enumerate(self.task_statuses):
            if t is not None and t.executor_id == executor_id:
                self.task_statuses[i] = None
                n += 1
        return n


@dataclass
class FailedStage:
    stage_id: int
    plan: ShuffleWriterExec
    output_links: List[int]
    error: str

    @property
    def partitions(self) -> int:
        return self.plan.output_partitioning().n
