"""The scheduler's brain: an event-driven job/stage state machine.

Counterpart of the reference's
``scheduler/src/scheduler_server/query_stage_scheduler.rs:65-202`` with the
same event vocabulary (``event.rs:27-43``): JobQueued → planning →
JobSubmitted → reservations → ReservationOffering → tasks launch;
TaskUpdating drives stage transitions and re-offers freed slots;
ExecutorLost rolls affected jobs back.  All mutations run on the single
event-loop thread.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional

from ..config import TaskSchedulingPolicy
from ..errors import BallistaError
from ..plan import logical as lp
from ..serde.scheduler_types import ExecutorMetadata
from .event_loop import EventAction, EventSender
from .execution_stage import TaskInfo
from .executor_manager import ExecutorReservation
from .state import SchedulerState

log = logging.getLogger(__name__)


# ------------------------------------------------------------------ events
@dataclass
class JobQueued:
    job_id: str
    session_id: str
    plan: lp.LogicalPlan


@dataclass
class JobSubmitted:
    job_id: str


@dataclass
class JobPlanningFailed:
    job_id: str
    error: str


@dataclass
class JobFinished:
    job_id: str


@dataclass
class JobRunningFailed:
    job_id: str
    error: str


@dataclass
class JobUpdated:
    job_id: str


@dataclass
class TaskUpdating:
    executor: ExecutorMetadata
    statuses: List[TaskInfo]


@dataclass
class ReservationOffering:
    reservations: List[ExecutorReservation] = field(default_factory=list)


@dataclass
class ExecutorLost:
    executor_id: str
    reason: str = ""


@dataclass
class SpeculationScan:
    """Periodic tick from the SchedulerServer's speculation timer: run
    one straggler/deadline scan on the event-loop thread (all graph
    mutations stay on the single-thread discipline)."""


@dataclass
class AdmissionPulse:
    """Periodic tick (same 1s timer) while the admission queue is
    non-empty: shed jobs queued past max_queue_wait_seconds and retry
    the release scan — the catch-up path for capacity that freed
    without a job event (an executor registering, a cancel from a gRPC
    thread)."""


def post_job_events(state: SchedulerState, sender, events) -> None:
    """Map task-manager job events onto scheduler events; shared by the
    event-loop TaskUpdating handler and the pull-mode poll_work path."""
    for job_id, ev in events:
        if ev == "job_completed":
            sender.post(JobFinished(job_id))
        elif ev == "job_failed":
            status = state.task_manager.get_job_status(job_id) or {}
            sender.post(JobRunningFailed(job_id, status.get("error", "task failed")))
        else:
            sender.post(JobUpdated(job_id))


class QueryStageScheduler(EventAction):
    def __init__(self, state: SchedulerState):
        self.state = state
        # event-loop observability: every mutation runs on this single
        # thread, so handling latency IS scheduler responsiveness
        self._event_latency = state.metrics.histogram(
            "scheduler_event_handle_seconds",
            "query-stage event handling latency (event-loop thread)",
            buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
        )
        self._events = state.metrics.counter(
            "scheduler_events_total", "query-stage events processed"
        )

    # ---------------------------------------------------------- dispatch
    def on_receive(self, event, sender: EventSender) -> None:
        import time as _t

        t0 = _t.monotonic()
        try:
            self._dispatch(event, sender)
        finally:
            self._events.inc()
            self._event_latency.observe(_t.monotonic() - t0)

    def _dispatch(self, event, sender: EventSender) -> None:
        if isinstance(event, JobQueued):
            self._on_job_queued(event, sender)
        elif isinstance(event, JobSubmitted):
            self._on_job_submitted(event, sender)
        elif isinstance(event, JobPlanningFailed):
            log.error("job %s planning failed: %s", event.job_id, event.error)
            self.state.task_manager.fail_job(event.job_id, event.error)
            self._admit_released(sender)
        elif isinstance(event, JobFinished):
            self.state.task_manager.complete_job(event.job_id)
            # the finished job freed an admission slot: queued jobs with
            # capacity now release by deficit-weighted round robin
            self._admit_released(sender)
        elif isinstance(event, JobRunningFailed):
            log.error("job %s failed: %s", event.job_id, event.error)
            self.state.task_manager.fail_job(event.job_id, event.error)
            self._admit_released(sender)
        elif isinstance(event, AdmissionPulse):
            self._on_admission_pulse(sender)
        elif isinstance(event, JobUpdated):
            self.state.task_manager.update_job(event.job_id)
        elif isinstance(event, TaskUpdating):
            self._on_task_updating(event, sender)
        elif isinstance(event, ReservationOffering):
            self._on_reservation_offering(event, sender)
        elif isinstance(event, ExecutorLost):
            self._on_executor_lost(event, sender)
        elif isinstance(event, SpeculationScan):
            self._on_speculation_scan(sender)
        else:
            log.warning("unknown scheduler event %r", event)

    # ----------------------------------------------------------- handlers
    def _on_job_queued(self, event: JobQueued, sender: EventSender) -> None:
        session_ctx = self.state.session_manager.get_session(event.session_id)
        if session_ctx is None:
            sender.post(
                JobPlanningFailed(event.job_id, f"unknown session {event.session_id}")
            )
            return
        try:
            outcome = self.state.submit_job(event.job_id, session_ctx, event.plan)
        except BallistaError as e:
            sender.post(JobPlanningFailed(event.job_id, str(e)))
            return
        except Exception as e:  # noqa: BLE001 - planning bugs must fail the job
            sender.post(JobPlanningFailed(event.job_id, f"internal error: {e}"))
            return
        if outcome == "queued":
            # admission-managed: the job sits in the queue pre-planning;
            # the release scan (run now, and again as capacity frees)
            # plans whichever jobs fair share admits — possibly this one
            self._admit_released(sender)
            return
        sender.post(JobSubmitted(event.job_id))

    def _admit_released(self, sender: EventSender) -> None:
        """Plan + submit every job the admission controller releases at
        current capacity (deficit-weighted round robin across pools).
        Runs on the event-loop thread, so queued-job planning keeps the
        same single-thread discipline as direct submits."""
        state = self.state
        for qj in state.admission.release():
            if state.admission.take_cancel_intent(qj.job_id):
                # cancel arrived while the job was queued/mid-release:
                # fail instead of planning (the slot frees immediately)
                state.admission.job_finished(qj.job_id)
                state.task_manager.fail_job(
                    qj.job_id, "job cancelled by user"
                )
                continue
            session_ctx = state.session_manager.get_session(qj.session_id)
            if session_ctx is None:
                sender.post(
                    JobPlanningFailed(
                        qj.job_id, f"unknown session {qj.session_id}"
                    )
                )
                continue
            try:
                state.submit_admitted_job(qj.job_id, session_ctx, qj.plan)
            except BallistaError as e:
                sender.post(JobPlanningFailed(qj.job_id, str(e)))
                continue
            except Exception as e:  # noqa: BLE001 - planning bugs fail the job
                sender.post(
                    JobPlanningFailed(qj.job_id, f"internal error: {e}")
                )
                continue
            sender.post(JobSubmitted(qj.job_id))

    def _on_admission_pulse(self, sender: EventSender) -> None:
        """Shed overdue queued jobs, then retry the release scan (the
        1s catch-up for capacity freed outside job events)."""
        for qj, error in self.state.admission.expire_overdue():
            self.state.task_manager.fail_job(qj.job_id, error)
        self._admit_released(sender)

    def _on_job_submitted(self, event: JobSubmitted, sender: EventSender) -> None:
        if self.state.policy != TaskSchedulingPolicy.PUSH_STAGED:
            return
        status = self.state.task_manager.get_job_status(event.job_id)
        if status is None:
            return
        # reserve as many slots as the job has runnable tasks right now
        entry = self.state.task_manager._entry(event.job_id)
        with entry.lock:
            graph = self.state.task_manager._load(event.job_id, entry)
            n = graph.available_tasks() if graph is not None else 0
        if n <= 0:
            return
        reservations = self.state.executor_manager.reserve_slots(n, event.job_id)
        if reservations:
            sender.post(ReservationOffering(reservations))

    def _on_task_updating(self, event: TaskUpdating, sender: EventSender) -> None:
        events, reservations = self.state.update_task_statuses(
            event.executor, event.statuses
        )
        post_job_events(self.state, sender, events)
        if self.state.policy == TaskSchedulingPolicy.PUSH_STAGED:
            # a retried/requeued task must land on a DIFFERENT executor
            # than the slot freed by its failure — reserve across the
            # cluster (quarantine-reset tasks mint nothing otherwise)
            retried = sum(
                1 for _, ev in events if ev in ("task_retried", "task_requeued")
            )
            if retried:
                reservations = list(reservations)
                _pending, hosts = self.state.task_manager.locality_pending()
                reservations.extend(
                    self.state.executor_manager.reserve_slots(
                        retried, preferred_hosts=hosts or None
                    )
                )
        if reservations:
            sender.post(ReservationOffering(reservations))
        self._drain_expulsions(sender)

    def _on_reservation_offering(
        self, event: ReservationOffering, sender: EventSender
    ) -> None:
        launched, leftover = self.state.offer_reservation(event.reservations)
        if leftover:
            # nothing runnable right now (tasks in flight gate the rest):
            # give the slots back — the next TaskUpdating re-mints them.
            # Re-posting here would spin the loop.
            self.state.executor_manager.cancel_reservations(leftover)
        self._drain_expulsions(sender)

    def _on_speculation_scan(self, sender: EventSender) -> None:
        events, slots_wanted = self.state.speculation.scan()
        post_job_events(self.state, sender, events)
        if slots_wanted and self.state.policy == TaskSchedulingPolicy.PUSH_STAGED:
            # duplicates must land on a DIFFERENT executor than the
            # straggler's; reserve cluster-wide and let pop_next_task's
            # same-host guard sort the placement
            reservations = self.state.executor_manager.reserve_slots(
                slots_wanted
            )
            if reservations:
                sender.post(ReservationOffering(reservations))
        if self.state.policy == TaskSchedulingPolicy.PUSH_STAGED:
            # locality liveness: a task deferred for its preferred host
            # gave its slot back; this periodic tick (the same 1s timer
            # driving the scan) re-mints reservations — host-ordered —
            # so the task dispatches the moment a preferred slot frees
            # or its locality wait expires.  locality_pending() is empty
            # unless some job opted into ballista.shuffle.locality_*.
            pending, hosts = self.state.task_manager.locality_pending()
            if pending > 0:
                reservations = self.state.executor_manager.reserve_slots(
                    pending, preferred_hosts=hosts or None
                )
                if reservations:
                    sender.post(ReservationOffering(reservations))

    def _drain_expulsions(self, sender: EventSender) -> None:
        """Executors whose repeated launch failures crossed the threshold
        become ExecutorLost — the standard rollback path — instead of the
        scheduler silently re-dispatching into a black hole."""
        for eid in self.state.executor_manager.take_pending_expulsions():
            sender.post(ExecutorLost(eid, "repeated launch failures"))

    def _on_executor_lost(self, event: ExecutorLost, sender: EventSender) -> None:
        """ALL executor-loss paths land here on the event-loop thread —
        gRPC ExecutorStopped, repeated launch failures, heartbeat expiry
        and drain deadlines — so rollback/repoint and drain bookkeeping
        serialize instead of racing across threads."""
        log.warning("executor %s lost: %s", event.executor_id, event.reason)
        em = self.state.executor_manager
        self.state.events.emit(
            "executor_lost",
            executor=event.executor_id,
            reason=(event.reason or "")[:200],
        )
        # the lost executor's telemetry series and labeled gauges go too
        # (its last snapshot must not read as a live executor forever)
        self.state.telemetry.forget_executor(event.executor_id)
        if not em.is_draining(event.executor_id):
            # a non-draining loss (crash/expiry) gets a best-effort
            # force-stop so a half-dead process stops serving; a DRAINED
            # executor is already exiting on its own terms
            self.state.try_stop_executor(event.executor_id, event.reason)
        em.remove_executor(event.executor_id)  # concludes any drain cycle
        affected = self.state.task_manager.executor_lost(event.executor_id)
        for job_id in affected:
            # bounded rollback: a stage reset past ballista.stage.max_attempts
            # failed the graph instead of resetting again
            status = self.state.task_manager.get_job_status(job_id) or {}
            if status.get("state") == "failed":
                sender.post(
                    JobRunningFailed(
                        job_id, status.get("error", "stage reset limit")
                    )
                )
            else:
                sender.post(JobUpdated(job_id))
        if affected and self.state.policy == TaskSchedulingPolicy.PUSH_STAGED:
            total = 0
            for job_id in affected:
                entry = self.state.task_manager._entry(job_id)
                with entry.lock:
                    graph = self.state.task_manager._load(job_id, entry)
                    if graph is not None:
                        total += graph.available_tasks()
            if total > 0:
                reservations = self.state.executor_manager.reserve_slots(total)
                if reservations:
                    sender.post(ReservationOffering(reservations))
