"""Remote etcd-semantics state store: the HA substrate.

Counterpart of the reference's etcd backend
(``scheduler/src/state/backend/etcd.rs:37-345``): several schedulers share
ONE external store so any of them can take over a peer's jobs.  The python
etcd3 client isn't in this image, so the same semantics ride this repo's
own gRPC service (``KvStoreGrpc`` in ballista.proto):

* transactional multi-put (etcd Txn ↔ ``PutTxn`` over the local backend's
  ``put_txn``);
* distributed locks as LEASES with TTL auto-expiry (etcd lock + keep-alive
  ↔ ``Lock``/``Unlock`` with ``ttl_s``; a crashed holder's lease simply
  expires, `etcd.rs:333-345`);
* prefix watches as server streams (etcd watch ↔ ``Watch``).

``KvStoreServer`` wraps any local :class:`StateBackend` (sqlite for
durability); ``RemoteBackend`` implements the ``StateBackend`` ABC over
the stub so the whole scheduler state layer runs unchanged against the
shared store.  ``python -m arrow_ballista_tpu.scheduler.kvstore`` runs a
standalone store.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import grpc

from ..proto import pb
from ..proto.rpc import (
    GRPC_OPTIONS,
    KvStoreGrpcStub,
    add_kvstore_servicer,
    make_channel,
    make_server,
)
from .backend import Keyspace, StateBackend, WatchEvent, Watcher

log = logging.getLogger(__name__)

DEFAULT_LOCK_TTL_S = 30.0
DEFAULT_LOCK_WAIT_S = 20.0


class LeaseFenced(Exception):
    """A fenced transaction was rejected: the guarding lease expired or
    changed hands between the write's dispatch and its application."""


# ------------------------------------------------------------------ server
class _Lease:
    __slots__ = ("owner", "expires", "token")

    def __init__(self, owner: str, expires: float, token: int):
        self.owner = owner
        self.expires = expires
        self.token = token


class KvStoreService:
    """gRPC servicer over a local StateBackend + lease table."""

    def __init__(self, backend: StateBackend):
        self.backend = backend
        self._leases: Dict[Tuple[str, str], _Lease] = {}
        self._lease_guard = threading.Lock()
        self._next_token = 0  # guarded by _lease_guard

    # ---- kv ----
    def Get(self, req: pb.KvGetParams, ctx) -> pb.KvGetResult:
        v = self.backend.get(Keyspace(req.keyspace), req.key)
        return pb.KvGetResult(found=v is not None, value=v or b"")

    def GetFromPrefix(self, req: pb.KvScanParams, ctx) -> pb.KvScanResult:
        pairs = self.backend.get_from_prefix(Keyspace(req.keyspace), req.prefix)
        return pb.KvScanResult(
            pairs=[pb.KvPair(key=k, value=v) for k, v in pairs]
        )

    def Scan(self, req: pb.KvScanParams, ctx) -> pb.KvScanResult:
        pairs = self.backend.scan(Keyspace(req.keyspace))
        if req.prefix:
            pairs = [(k, v) for k, v in pairs if k.startswith(req.prefix)]
        return pb.KvScanResult(
            pairs=[pb.KvPair(key=k, value=v) for k, v in pairs]
        )

    def Put(self, req: pb.KvPutParams, ctx) -> pb.KvPutResult:
        self.backend.put(Keyspace(req.keyspace), req.key, req.value)
        return pb.KvPutResult()

    def PutTxn(self, req: pb.KvTxnParams, ctx) -> pb.KvTxnResult:
        if req.HasField("fence"):
            f = req.fence
            now = time.monotonic()
            with self._lease_guard:
                lease = self._leases.get((f.keyspace, f.key))
                ok = (
                    lease is not None
                    and lease.expires > now
                    and lease.owner == f.owner
                    and lease.token == f.token
                )
                if ok:
                    # apply under the guard: the lease cannot expire or
                    # be re-granted between the check and the write
                    self.backend.put_txn(
                        [
                            (Keyspace(op.keyspace), op.key, op.value)
                            for op in req.ops
                        ]
                    )
                    return pb.KvTxnResult()
            ctx.abort(
                grpc.StatusCode.ABORTED,
                f"fenced: lease {f.keyspace}/{f.key} no longer held by "
                f"{f.owner} with token {f.token}",
            )
        self.backend.put_txn(
            [(Keyspace(op.keyspace), op.key, op.value) for op in req.ops]
        )
        return pb.KvTxnResult()

    def Mv(self, req: pb.KvMvParams, ctx) -> pb.KvMvResult:
        self.backend.mv(
            Keyspace(req.from_keyspace), Keyspace(req.to_keyspace), req.key
        )
        return pb.KvMvResult()

    def Delete(self, req: pb.KvDeleteParams, ctx) -> pb.KvDeleteResult:
        self.backend.delete(Keyspace(req.keyspace), req.key)
        return pb.KvDeleteResult()

    # ---- leases ----
    def Lock(self, req: pb.KvLockParams, ctx) -> pb.KvLockResult:
        ttl = req.ttl_s or DEFAULT_LOCK_TTL_S
        wait = req.wait_s if req.wait_s > 0 else DEFAULT_LOCK_WAIT_S
        key = (req.keyspace, req.key)
        deadline = time.monotonic() + wait
        while True:
            now = time.monotonic()
            with self._lease_guard:
                lease = self._leases.get(key)
                if lease is not None and lease.owner == req.owner and (
                    lease.expires > now
                ):
                    # keep-alive refresh of a LIVE lease: extend the
                    # expiry, keep the grant's fencing token
                    lease.expires = now + ttl
                    return pb.KvLockResult(acquired=True, token=lease.token)
                if lease is None or lease.expires <= now:
                    self._next_token += 1
                    self._leases[key] = _Lease(
                        req.owner, now + ttl, self._next_token
                    )
                    return pb.KvLockResult(
                        acquired=True, token=self._next_token
                    )
            if now >= deadline:
                return pb.KvLockResult(acquired=False)
            time.sleep(0.01)

    def Unlock(self, req: pb.KvUnlockParams, ctx) -> pb.KvUnlockResult:
        key = (req.keyspace, req.key)
        with self._lease_guard:
            lease = self._leases.get(key)
            if lease is not None and lease.owner == req.owner:
                del self._leases[key]
        return pb.KvUnlockResult()

    # ---- watch ----
    def Watch(self, req: pb.KvWatchParams, ctx):
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        unsub = self.backend.watch(
            Keyspace(req.keyspace), req.prefix, q.put
        )
        try:
            while ctx.is_active():
                try:
                    ev = q.get(timeout=0.25)
                except queue.Empty:
                    continue
                yield pb.KvWatchEvent(
                    kind=ev.kind, key=ev.key, value=ev.value or b""
                )
        finally:
            unsub()


class KvStoreHandle:
    """Background KV store server with clean shutdown."""

    def __init__(self, backend: StateBackend, host: str = "127.0.0.1", port: int = 0):
        self.service = KvStoreService(backend)
        self.server = make_server()
        add_kvstore_servicer(self.server, self.service)
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        self.host = host

    def start(self) -> "KvStoreHandle":
        self.server.start()
        return self

    def stop(self) -> None:
        self.server.stop(grace=1.0)


# ------------------------------------------------------------------ client
class _RemoteLock:
    """Context-manager lock over the store's lease API (etcd lock shape:
    acquire with TTL, release explicitly, expire on crash).

    While held, a daemon refresher thread re-Locks every ``ttl/3`` —
    etcd's lease keep-alive (`etcd.rs:333-345`) — so an operation that
    outlives the TTL keeps its lease instead of silently losing it.  If a
    refresh ever comes back with a DIFFERENT token (the lease lapsed and
    was re-granted, i.e. another owner could have acted in the gap) the
    lock marks itself ``lost`` and stops refreshing; fenced writes
    carrying the original token are then rejected by the store.
    """

    def __init__(
        self, stub, keyspace: str, key: str, owner: str,
        ttl_s: float = DEFAULT_LOCK_TTL_S,
    ):
        self._stub = stub
        self._keyspace = keyspace
        self._key = key
        self._owner = owner
        self._ttl = ttl_s
        self.token: Optional[int] = None
        self.lost = False
        self._stop: Optional[threading.Event] = None

    def acquire(self, timeout: Optional[float] = None) -> bool:
        res = self._stub.Lock(
            pb.KvLockParams(
                keyspace=self._keyspace,
                key=self._key,
                owner=self._owner,
                ttl_s=self._ttl,
                wait_s=timeout or 0.0,
            )
        )
        if res.acquired:
            self.token = res.token
            self.lost = False
            self._start_keepalive()
        return res.acquired

    def _start_keepalive(self) -> None:
        self._stop = stop = threading.Event()
        interval = max(0.05, self._ttl / 3.0)

        def refresh():
            while not stop.wait(interval):
                try:
                    res = self._stub.Lock(
                        pb.KvLockParams(
                            keyspace=self._keyspace,
                            key=self._key,
                            owner=self._owner,
                            ttl_s=self._ttl,
                            wait_s=0.001,
                        )
                    )
                except Exception:  # store away: next write gets fenced
                    continue
                if not res.acquired or res.token != self.token:
                    if res.acquired:
                        # we re-won a NEW grant after a gap: release it —
                        # the original critical section must not continue
                        # under a token its fenced writes don't carry
                        try:
                            self._unlock()
                        except Exception:
                            pass
                    self.lost = True
                    return

        t = threading.Thread(
            target=refresh,
            name=f"kv-lease-{self._keyspace}/{self._key}",
            daemon=True,
        )
        t.start()

    def _unlock(self) -> None:
        self._stub.Unlock(
            pb.KvUnlockParams(
                keyspace=self._keyspace, key=self._key, owner=self._owner
            )
        )

    def release(self) -> None:
        if self._stop is not None:
            self._stop.set()
            self._stop = None
        self._unlock()

    def fence(self) -> pb.KvFence:
        return pb.KvFence(
            keyspace=self._keyspace,
            key=self._key,
            owner=self._owner,
            token=self.token or 0,
        )

    def __enter__(self):
        if not self.acquire():
            raise TimeoutError(
                f"kv lock {self._keyspace}/{self._key} not acquired"
            )
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class RemoteBackend(StateBackend):
    """StateBackend over a shared KvStoreGrpc endpoint (the etcd slot).

    ``namespace`` prefixes every key (etcd's ``/ballista/{namespace}/``
    layout, `etcd.rs:49-60`): independent clusters can share one store
    without seeing each other's state.
    """

    def __init__(
        self, host: str, port: int, owner: str = "", namespace: str = ""
    ):
        import uuid

        self._channel = make_channel(host, port)
        self._stub = KvStoreGrpcStub(self._channel)
        self._owner = owner or uuid.uuid4().hex[:12]
        self._ns = f"{namespace}/" if namespace else ""
        self._watch_threads: List[threading.Thread] = []
        self._closed = threading.Event()

    def _k(self, key: str) -> str:
        return self._ns + key

    def _strip(self, key: str) -> str:
        return key[len(self._ns):] if self._ns else key

    def get(self, keyspace: Keyspace, key: str) -> Optional[bytes]:
        r = self._stub.Get(
            pb.KvGetParams(keyspace=keyspace.value, key=self._k(key))
        )
        return r.value if r.found else None

    def get_from_prefix(self, keyspace, prefix):
        r = self._stub.GetFromPrefix(
            pb.KvScanParams(keyspace=keyspace.value, prefix=self._k(prefix))
        )
        return [(self._strip(p.key), p.value) for p in r.pairs]

    def scan(self, keyspace):
        if self._ns:
            return self.get_from_prefix(keyspace, "")
        r = self._stub.Scan(pb.KvScanParams(keyspace=keyspace.value))
        return [(p.key, p.value) for p in r.pairs]

    def put(self, keyspace, key, value):
        self._stub.Put(
            pb.KvPutParams(
                keyspace=keyspace.value, key=self._k(key), value=value
            )
        )

    def put_txn(self, ops, fence=None):
        params = pb.KvTxnParams(
            ops=[
                pb.KvTxnOp(keyspace=ks.value, key=self._k(k), value=v)
                for ks, k, v in ops
            ]
        )
        # callers pass whatever backend.lock() gave them; only remote
        # leases carry a fencing token (a threading.Lock has none)
        if fence is not None and hasattr(fence, "fence"):
            params.fence.CopyFrom(fence.fence())
        try:
            self._stub.PutTxn(params)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.ABORTED:
                raise LeaseFenced(str(e.details())) from e
            raise

    def mv(self, from_keyspace, to_keyspace, key):
        self._stub.Mv(
            pb.KvMvParams(
                from_keyspace=from_keyspace.value,
                to_keyspace=to_keyspace.value,
                key=self._k(key),
            )
        )

    def delete(self, keyspace, key):
        self._stub.Delete(
            pb.KvDeleteParams(keyspace=keyspace.value, key=self._k(key))
        )

    def lock(
        self, keyspace: Keyspace, key: str,
        ttl_s: float = DEFAULT_LOCK_TTL_S,
    ):
        return _RemoteLock(
            self._stub, keyspace.value, self._k(key),
            f"{self._owner}:{threading.get_ident()}",
            ttl_s=ttl_s,
        )

    def watch(self, keyspace: Keyspace, prefix: str, watcher: Watcher) -> Callable:
        stop = threading.Event()
        ns_prefix = self._k(prefix)

        def run():
            while not stop.is_set() and not self._closed.is_set():
                try:
                    stream = self._stub.Watch(
                        pb.KvWatchParams(
                            keyspace=keyspace.value, prefix=ns_prefix
                        )
                    )
                    for ev in stream:
                        if stop.is_set():
                            break
                        watcher(
                            WatchEvent(
                                ev.kind, self._strip(ev.key), ev.value or None
                            )
                        )
                except Exception:  # noqa: BLE001 - incl. closed-channel ValueError
                    if stop.is_set() or self._closed.is_set():
                        return
                    time.sleep(0.5)  # store restarting: retry the stream

        t = threading.Thread(target=run, name=f"kv-watch-{prefix}", daemon=True)
        t.start()
        self._watch_threads.append(t)
        return stop.set

    def close(self) -> None:
        self._closed.set()
        self._channel.close()


def main() -> None:  # pragma: no cover - thin binary wrapper
    import argparse

    from .backend import MemoryBackend, SqliteBackend

    p = argparse.ArgumentParser(prog="arrow_ballista_tpu.scheduler.kvstore")
    p.add_argument("--bind-host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=50060)
    p.add_argument("--db", default="", help="sqlite path (default: memory)")
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO)
    backend = SqliteBackend(args.db) if args.db else MemoryBackend()
    handle = KvStoreHandle(backend, args.bind_host, args.port).start()
    log.info("kv store serving on %s:%d", args.bind_host, handle.port)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        handle.stop()


if __name__ == "__main__":
    main()
