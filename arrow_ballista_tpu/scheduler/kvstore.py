"""Remote etcd-semantics state store: the HA substrate.

Counterpart of the reference's etcd backend
(``scheduler/src/state/backend/etcd.rs:37-345``): several schedulers share
ONE external store so any of them can take over a peer's jobs.  The python
etcd3 client isn't in this image, so the same semantics ride this repo's
own gRPC service (``KvStoreGrpc`` in ballista.proto):

* transactional multi-put (etcd Txn ↔ ``PutTxn`` over the local backend's
  ``put_txn``);
* distributed locks as LEASES with TTL auto-expiry (etcd lock + keep-alive
  ↔ ``Lock``/``Unlock`` with ``ttl_s``; a crashed holder's lease simply
  expires, `etcd.rs:333-345`);
* prefix watches as server streams (etcd watch ↔ ``Watch``).

``KvStoreServer`` wraps any local :class:`StateBackend` (sqlite for
durability); ``RemoteBackend`` implements the ``StateBackend`` ABC over
the stub so the whole scheduler state layer runs unchanged against the
shared store.  ``python -m arrow_ballista_tpu.scheduler.kvstore`` runs a
standalone store; ``--replica-of`` starts an async primary/backup pair
(:class:`_Replicator` — the raft-replication slot) with client-side
endpoint rotation filling the failover path.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import grpc

from ..proto import pb
from ..proto.rpc import (
    GRPC_OPTIONS,
    KvStoreGrpcStub,
    add_kvstore_servicer,
    make_channel,
    make_server,
)
from .backend import Keyspace, StateBackend, WatchEvent, Watcher

log = logging.getLogger(__name__)

DEFAULT_LOCK_TTL_S = 30.0
DEFAULT_LOCK_WAIT_S = 20.0


def parse_endpoint(ep: str) -> Tuple[str, int]:
    """One "host:port" → (host, port) with the store's defaults."""
    h, _, pt = ep.strip().partition(":")
    return h or "127.0.0.1", int(pt or 50060)


class LeaseFenced(Exception):
    """A fenced transaction was rejected: the guarding lease expired or
    changed hands between the write's dispatch and its application."""


# ------------------------------------------------------------------ server
class _Lease:
    __slots__ = ("owner", "expires", "token")

    def __init__(self, owner: str, expires: float, token: int):
        self.owner = owner
        self.expires = expires
        self.token = token


class KvStoreService:
    """gRPC servicer over a local StateBackend + lease table.

    ``role``: a store started with ``replica_of`` serves NOTHING while
    its primary lives — every RPC aborts UNAVAILABLE so rotating clients
    bounce back to the primary — and self-promotes to ``primary`` when
    the health loop loses the primary for ``promote_after_s``.  The
    lease table is deliberately NOT replicated: an empty table after
    failover means every pre-failover fenced write is rejected
    (conservative — exactly the store-restart semantics
    ``tests/test_ha_failover.py`` proves the cluster converges through).
    """

    def __init__(self, backend: StateBackend, role: str = "primary"):
        self.backend = backend
        self.role = role
        self._leases: Dict[Tuple[str, str], _Lease] = {}
        self._lease_guard = threading.Lock()
        self._next_token = 0  # guarded by _lease_guard

    def promote(self) -> None:
        if self.role != "primary":
            log.warning("kvstore replica promoting to primary")
            self.role = "primary"

    def _serving(self, ctx) -> None:
        if self.role != "primary":
            ctx.abort(
                grpc.StatusCode.UNAVAILABLE,
                "replica: not serving while the primary is alive",
            )

    # ---- kv ----
    def Get(self, req: pb.KvGetParams, ctx) -> pb.KvGetResult:
        self._serving(ctx)
        v = self.backend.get(Keyspace(req.keyspace), req.key)
        return pb.KvGetResult(found=v is not None, value=v or b"")

    def GetFromPrefix(self, req: pb.KvScanParams, ctx) -> pb.KvScanResult:
        self._serving(ctx)
        pairs = self.backend.get_from_prefix(Keyspace(req.keyspace), req.prefix)
        return pb.KvScanResult(
            pairs=[pb.KvPair(key=k, value=v) for k, v in pairs]
        )

    def Scan(self, req: pb.KvScanParams, ctx) -> pb.KvScanResult:
        self._serving(ctx)
        pairs = self.backend.scan(Keyspace(req.keyspace))
        if req.prefix:
            pairs = [(k, v) for k, v in pairs if k.startswith(req.prefix)]
        return pb.KvScanResult(
            pairs=[pb.KvPair(key=k, value=v) for k, v in pairs]
        )

    def Put(self, req: pb.KvPutParams, ctx) -> pb.KvPutResult:
        self._serving(ctx)
        self.backend.put(Keyspace(req.keyspace), req.key, req.value)
        return pb.KvPutResult()

    def PutTxn(self, req: pb.KvTxnParams, ctx) -> pb.KvTxnResult:
        self._serving(ctx)
        if req.HasField("fence"):
            f = req.fence
            now = time.monotonic()
            with self._lease_guard:
                lease = self._leases.get((f.keyspace, f.key))
                ok = (
                    lease is not None
                    and lease.expires > now
                    and lease.owner == f.owner
                    and lease.token == f.token
                )
                if ok:
                    # apply under the guard: the lease cannot expire or
                    # be re-granted between the check and the write
                    self.backend.put_txn(
                        [
                            (Keyspace(op.keyspace), op.key, op.value)
                            for op in req.ops
                        ]
                    )
                    return pb.KvTxnResult()
            ctx.abort(
                grpc.StatusCode.ABORTED,
                f"fenced: lease {f.keyspace}/{f.key} no longer held by "
                f"{f.owner} with token {f.token}",
            )
        self.backend.put_txn(
            [(Keyspace(op.keyspace), op.key, op.value) for op in req.ops]
        )
        return pb.KvTxnResult()

    def Mv(self, req: pb.KvMvParams, ctx) -> pb.KvMvResult:
        self._serving(ctx)
        self.backend.mv(
            Keyspace(req.from_keyspace), Keyspace(req.to_keyspace), req.key
        )
        return pb.KvMvResult()

    def Delete(self, req: pb.KvDeleteParams, ctx) -> pb.KvDeleteResult:
        self._serving(ctx)
        self.backend.delete(Keyspace(req.keyspace), req.key)
        return pb.KvDeleteResult()

    # ---- leases ----
    def Lock(self, req: pb.KvLockParams, ctx) -> pb.KvLockResult:
        self._serving(ctx)
        ttl = req.ttl_s or DEFAULT_LOCK_TTL_S
        wait = req.wait_s if req.wait_s > 0 else DEFAULT_LOCK_WAIT_S
        key = (req.keyspace, req.key)
        deadline = time.monotonic() + wait
        while True:
            now = time.monotonic()
            with self._lease_guard:
                lease = self._leases.get(key)
                if lease is not None and lease.owner == req.owner and (
                    lease.expires > now
                ):
                    # keep-alive refresh of a LIVE lease: extend the
                    # expiry, keep the grant's fencing token
                    lease.expires = now + ttl
                    return pb.KvLockResult(acquired=True, token=lease.token)
                if lease is None or lease.expires <= now:
                    self._next_token += 1
                    self._leases[key] = _Lease(
                        req.owner, now + ttl, self._next_token
                    )
                    return pb.KvLockResult(
                        acquired=True, token=self._next_token
                    )
            if now >= deadline:
                return pb.KvLockResult(acquired=False)
            time.sleep(0.01)

    def Unlock(self, req: pb.KvUnlockParams, ctx) -> pb.KvUnlockResult:
        self._serving(ctx)
        key = (req.keyspace, req.key)
        with self._lease_guard:
            lease = self._leases.get(key)
            if lease is not None and lease.owner == req.owner:
                del self._leases[key]
        return pb.KvUnlockResult()

    # ---- watch ----
    def Watch(self, req: pb.KvWatchParams, ctx):
        self._serving(ctx)
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        unsub = self.backend.watch(
            Keyspace(req.keyspace), req.prefix, q.put
        )
        try:
            while ctx.is_active():
                try:
                    ev = q.get(timeout=0.25)
                except queue.Empty:
                    continue
                yield pb.KvWatchEvent(
                    kind=ev.kind, key=ev.key, value=ev.value or b""
                )
        finally:
            unsub()


class _Replicator(threading.Thread):
    """Primary/backup replication (the raft-replication slot, kept
    deliberately simple): full-sync every keyspace from the primary,
    then follow its watch streams applying puts/deletes to the local
    backend; a health loop Gets a sentinel key every ``poll_s`` and
    PROMOTES the local service after ``promote_after_s`` without a
    successful round-trip.  Replication is asynchronous — a write the
    primary acknowledged in its final ``poll_s`` may be lost on
    failover, the standard async-replica contract; scheduler state is
    heartbeat/slot/graph churn that the cluster re-converges (fencing
    rejects every pre-failover lease, and restart-resume replays
    in-flight work)."""

    def __init__(
        self,
        service: KvStoreService,
        primary_host: str,
        primary_port: int,
        promote_after_s: float = 5.0,
        poll_s: float = 0.5,
    ):
        super().__init__(name="kv-replicator", daemon=True)
        self.service = service
        self.host = primary_host
        self.port = primary_port
        self.promote_after_s = promote_after_s
        self.poll_s = poll_s
        self._stop = threading.Event()
        self.synced = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def _full_sync(self, stub) -> None:
        backend = self.service.backend
        for ks in Keyspace:
            res = stub.Scan(pb.KvScanParams(keyspace=ks.value))
            remote = {p.key: p.value for p in res.pairs}
            # reconcile DELETIONS too: a resync after a stream outage
            # must not resurrect keys the primary removed in the gap
            for k in backend.scan_keys(ks):
                if k not in remote:
                    backend.delete(ks, k)
            ops = [(ks, k, v) for k, v in remote.items()]
            if ops:
                backend.put_txn(ops)

    def _follow(self, stub, ks: Keyspace) -> None:
        backend = self.service.backend
        try:
            for ev in stub.Watch(
                pb.KvWatchParams(keyspace=ks.value, prefix="")
            ):
                if self._stop.is_set() or self.service.role == "primary":
                    return
                if ev.kind == WatchEvent.PUT:
                    backend.put(ks, ev.key, ev.value)
                else:
                    backend.delete(ks, ev.key)
        except Exception:  # noqa: BLE001 - dead stream: health loop resyncs
            return

    def run(self) -> None:
        channel = make_channel(self.host, self.port)
        stub = KvStoreGrpcStub(channel)
        followers: List[threading.Thread] = []
        last_ok = time.monotonic()
        synced = False
        while not self._stop.is_set():
            # dead follower streams mean replication has stopped even if
            # health Gets succeed (e.g. the primary bounced fast):
            # resync on the next healthy tick, not only after a failure
            if synced and not all(t.is_alive() for t in followers):
                synced = False
            try:
                if not synced:
                    # watches before the scan so no event is missed; the
                    # converse race (the snapshot overwriting a newer
                    # concurrently-applied event) lasts one churn cycle
                    # of that key — acceptable for an ASYNC replica and
                    # bounded by the scheduler's constant heartbeat/slot
                    # rewrites
                    followers = [
                        threading.Thread(
                            target=self._follow, args=(stub, ks), daemon=True
                        )
                        for ks in Keyspace
                    ]
                    for t in followers:
                        t.start()
                    self._full_sync(stub)
                    synced = True
                    self.synced.set()
                stub.Get(
                    pb.KvGetParams(
                        keyspace=Keyspace.Sessions.value, key="__health__"
                    )
                )
                last_ok = time.monotonic()
            except Exception:  # noqa: BLE001 - primary unreachable
                if time.monotonic() - last_ok > self.promote_after_s:
                    if self.synced.is_set():
                        self.service.promote()
                        channel.close()
                        return
                    # NEVER promote a store that has not completed one
                    # sync this lifetime: a backup booted while the
                    # primary is down would otherwise serve an empty
                    # (or arbitrarily stale) store as the new truth
                    log.warning(
                        "kvstore replica: primary unreachable but no "
                        "sync completed yet — refusing to promote"
                    )
                    last_ok = time.monotonic()  # keep waiting
            if self._stop.wait(self.poll_s):
                break
        channel.close()


class KvStoreHandle:
    """Background KV store server with clean shutdown.

    ``replica_of`` starts the store as a follower of ``(host, port)`` —
    see :class:`_Replicator`.  ``peer`` (on the PRIMARY) closes the
    restart split-brain: before serving, the store probes its peer once
    and, if the peer is already serving as primary (a promoted backup),
    comes up as the peer's REPLICA instead — so a supervisor-restarted
    old primary demotes instead of fighting the promotion."""

    def __init__(
        self,
        backend: StateBackend,
        host: str = "127.0.0.1",
        port: int = 0,
        replica_of: Optional[Tuple[str, int]] = None,
        promote_after_s: float = 5.0,
        peer: Optional[Tuple[str, int]] = None,
    ):
        self.promote_after_s = promote_after_s
        self.service = KvStoreService(
            backend, role="replica" if replica_of else "primary"
        )
        self.server = make_server()
        add_kvstore_servicer(self.server, self.service)
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self._peer = peer
        self.replicator: Optional[_Replicator] = None
        if replica_of:
            self.replicator = _Replicator(
                self.service, replica_of[0], replica_of[1],
                promote_after_s=promote_after_s,
            )

    def _peer_is_primary(self) -> bool:
        if self._peer is None:
            return False
        channel = make_channel(*self._peer)
        try:
            KvStoreGrpcStub(channel).Get(
                pb.KvGetParams(
                    keyspace=Keyspace.Sessions.value, key="__health__"
                ),
                timeout=2.0,
            )
            return True  # peer answered: it is serving as primary
        except Exception:  # noqa: BLE001 - unreachable or replica
            return False
        finally:
            channel.close()

    def start(self) -> "KvStoreHandle":
        if self.service.role == "primary" and self._peer_is_primary():
            # the peer promoted while this store was down: demote
            log.warning(
                "kvstore: peer %s:%d is serving as primary — starting "
                "as its replica", *self._peer
            )
            self.service.role = "replica"
            self.replicator = _Replicator(
                self.service, self._peer[0], self._peer[1],
                promote_after_s=self.promote_after_s,
            )
        self.server.start()
        if self.replicator is not None:
            self.replicator.start()
        return self

    def stop(self) -> None:
        if self.replicator is not None:
            self.replicator.stop()
        self.server.stop(grace=1.0)


# ------------------------------------------------------------------ client
class _RemoteLock:
    """Context-manager lock over the store's lease API (etcd lock shape:
    acquire with TTL, release explicitly, expire on crash).

    While held, a daemon refresher thread re-Locks every ``ttl/3`` —
    etcd's lease keep-alive (`etcd.rs:333-345`) — so an operation that
    outlives the TTL keeps its lease instead of silently losing it.  If a
    refresh ever comes back with a DIFFERENT token (the lease lapsed and
    was re-granted, i.e. another owner could have acted in the gap) the
    lock marks itself ``lost`` and stops refreshing; fenced writes
    carrying the original token are then rejected by the store.
    """

    def __init__(
        self, backend, keyspace: str, key: str, owner: str,
        ttl_s: float = DEFAULT_LOCK_TTL_S,
    ):
        # `backend` is the owning RemoteBackend: lock RPCs ride its
        # endpoint-rotating _call so leases survive a store failover
        # (acquired fresh on the promoted primary; fencing covers the gap)
        self._backend = backend
        self._keyspace = keyspace
        self._key = key
        self._owner = owner
        self._ttl = ttl_s
        self.token: Optional[int] = None
        self.lost = False
        self._stop: Optional[threading.Event] = None

    def acquire(self, timeout: Optional[float] = None) -> bool:
        res = self._backend._call("Lock",
            pb.KvLockParams(
                keyspace=self._keyspace,
                key=self._key,
                owner=self._owner,
                ttl_s=self._ttl,
                wait_s=timeout or 0.0,
            )
        )
        if res.acquired:
            self.token = res.token
            self.lost = False
            self._start_keepalive()
        return res.acquired

    def _start_keepalive(self) -> None:
        self._stop = stop = threading.Event()
        interval = max(0.05, self._ttl / 3.0)

        def refresh():
            while not stop.wait(interval):
                try:
                    res = self._backend._call("Lock",
                        pb.KvLockParams(
                            keyspace=self._keyspace,
                            key=self._key,
                            owner=self._owner,
                            ttl_s=self._ttl,
                            wait_s=0.001,
                        )
                    )
                except Exception:  # store away: next write gets fenced
                    continue
                if not res.acquired or res.token != self.token:
                    if res.acquired:
                        # we re-won a NEW grant after a gap: release it —
                        # the original critical section must not continue
                        # under a token its fenced writes don't carry
                        try:
                            self._unlock()
                        except Exception:
                            pass
                    self.lost = True
                    return

        t = threading.Thread(
            target=refresh,
            name=f"kv-lease-{self._keyspace}/{self._key}",
            daemon=True,
        )
        t.start()

    def _unlock(self) -> None:
        self._backend._call(
            "Unlock",
            pb.KvUnlockParams(
                keyspace=self._keyspace, key=self._key, owner=self._owner
            ),
        )

    def release(self) -> None:
        if self._stop is not None:
            self._stop.set()
            self._stop = None
        self._unlock()

    def fence(self) -> pb.KvFence:
        return pb.KvFence(
            keyspace=self._keyspace,
            key=self._key,
            owner=self._owner,
            token=self.token or 0,
        )

    def __enter__(self):
        if not self.acquire():
            raise TimeoutError(
                f"kv lock {self._keyspace}/{self._key} not acquired"
            )
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class RemoteBackend(StateBackend):
    """StateBackend over a shared KvStoreGrpc endpoint (the etcd slot).

    ``namespace`` prefixes every key (etcd's ``/ballista/{namespace}/``
    layout, `etcd.rs:49-60`): independent clusters can share one store
    without seeing each other's state.  ``endpoints`` (list of
    ``"host:port"``) enables primary/backup failover: an UNAVAILABLE
    response rotates to the next endpoint and retries — a replica
    refuses to serve while its primary lives, so rotation naturally
    settles on whichever store is currently primary.
    """

    def __init__(
        self, host: str, port: int, owner: str = "", namespace: str = "",
        endpoints: Optional[List[str]] = None,
    ):
        import uuid

        self._endpoints: List[Tuple[str, int]] = [(host, port)]
        if endpoints:
            self._endpoints = [parse_endpoint(ep) for ep in endpoints]
        self._idx = 0
        self._chan_guard = threading.Lock()
        self._channel = make_channel(*self._endpoints[0])
        self._stub = KvStoreGrpcStub(self._channel)
        self._owner = owner or uuid.uuid4().hex[:12]
        self._ns = f"{namespace}/" if namespace else ""
        self._watch_threads: List[threading.Thread] = []
        self._closed = threading.Event()

    def _rotate_from(self, stub) -> None:
        """Advance to the next endpoint — but only if ``stub`` is still
        current: when several threads hit UNAVAILABLE together, the
        first rotation wins and the rest retry the fresh endpoint
        instead of leap-frogging past the healthy store."""
        with self._chan_guard:
            if self._stub is not stub:
                return
            self._idx = (self._idx + 1) % len(self._endpoints)
            try:
                self._channel.close()
            except Exception:  # noqa: BLE001
                pass
            self._channel = make_channel(*self._endpoints[self._idx])
            self._stub = KvStoreGrpcStub(self._channel)

    def _call(self, name: str, req):
        """One RPC with endpoint failover: UNAVAILABLE rotates through
        the endpoint list (first failure wins); CANCELLED from a
        channel a concurrent rotation closed retries on the fresh stub.
        Callers retry above this layer."""
        last = None
        for _ in range(max(2, 2 * len(self._endpoints))):
            with self._chan_guard:
                stub = self._stub
            try:
                return getattr(stub, name)(req)
            except ValueError as e:
                # "Cannot invoke RPC on closed channel!": a concurrent
                # rotation closed the channel before the call started
                last = e
                with self._chan_guard:
                    fresh = self._stub is not stub
                if fresh:
                    continue
                raise
            except grpc.RpcError as e:
                last = e
                code = e.code()
                if code == grpc.StatusCode.CANCELLED:
                    with self._chan_guard:
                        fresh = self._stub is not stub
                    if fresh:
                        continue  # rotation closed it mid-call: retry
                    raise
                if (
                    code == grpc.StatusCode.UNAVAILABLE
                    and len(self._endpoints) > 1
                ):
                    self._rotate_from(stub)
                    continue
                raise
        raise last

    def _k(self, key: str) -> str:
        return self._ns + key

    def _strip(self, key: str) -> str:
        return key[len(self._ns):] if self._ns else key

    def get(self, keyspace: Keyspace, key: str) -> Optional[bytes]:
        r = self._call(
            "Get", pb.KvGetParams(keyspace=keyspace.value, key=self._k(key))
        )
        return r.value if r.found else None

    def get_from_prefix(self, keyspace, prefix):
        r = self._call(
            "GetFromPrefix",
            pb.KvScanParams(keyspace=keyspace.value, prefix=self._k(prefix)),
        )
        return [(self._strip(p.key), p.value) for p in r.pairs]

    def scan(self, keyspace):
        if self._ns:
            return self.get_from_prefix(keyspace, "")
        r = self._call("Scan", pb.KvScanParams(keyspace=keyspace.value))
        return [(p.key, p.value) for p in r.pairs]

    def put(self, keyspace, key, value):
        self._call(
            "Put",
            pb.KvPutParams(
                keyspace=keyspace.value, key=self._k(key), value=value
            ),
        )

    def put_txn(self, ops, fence=None):
        params = pb.KvTxnParams(
            ops=[
                pb.KvTxnOp(keyspace=ks.value, key=self._k(k), value=v)
                for ks, k, v in ops
            ]
        )
        # callers pass whatever backend.lock() gave them; only remote
        # leases carry a fencing token (a threading.Lock has none)
        if fence is not None and hasattr(fence, "fence"):
            params.fence.CopyFrom(fence.fence())
        try:
            self._call("PutTxn", params)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.ABORTED:
                raise LeaseFenced(str(e.details())) from e
            raise

    def mv(self, from_keyspace, to_keyspace, key):
        self._call(
            "Mv",
            pb.KvMvParams(
                from_keyspace=from_keyspace.value,
                to_keyspace=to_keyspace.value,
                key=self._k(key),
            ),
        )

    def delete(self, keyspace, key):
        self._call(
            "Delete",
            pb.KvDeleteParams(keyspace=keyspace.value, key=self._k(key)),
        )

    def lock(
        self, keyspace: Keyspace, key: str,
        ttl_s: float = DEFAULT_LOCK_TTL_S,
    ):
        return _RemoteLock(
            self, keyspace.value, self._k(key),
            f"{self._owner}:{threading.get_ident()}",
            ttl_s=ttl_s,
        )

    def watch(self, keyspace: Keyspace, prefix: str, watcher: Watcher) -> Callable:
        stop = threading.Event()
        ns_prefix = self._k(prefix)

        def run():
            while not stop.is_set() and not self._closed.is_set():
                with self._chan_guard:
                    stub = self._stub
                try:
                    stream = stub.Watch(
                        pb.KvWatchParams(
                            keyspace=keyspace.value, prefix=ns_prefix
                        )
                    )
                    for ev in stream:
                        if stop.is_set():
                            break
                        watcher(
                            WatchEvent(
                                ev.kind, self._strip(ev.key), ev.value or None
                            )
                        )
                except Exception:  # noqa: BLE001 - incl. closed-channel ValueError
                    if stop.is_set() or self._closed.is_set():
                        return
                    if len(self._endpoints) > 1:
                        self._rotate_from(stub)  # maybe failed over
                    time.sleep(0.5)  # store restarting: retry the stream

        t = threading.Thread(target=run, name=f"kv-watch-{prefix}", daemon=True)
        t.start()
        self._watch_threads.append(t)
        return stop.set

    def close(self) -> None:
        self._closed.set()
        self._channel.close()


def main() -> None:  # pragma: no cover - thin binary wrapper
    import argparse

    from .backend import MemoryBackend, SqliteBackend

    p = argparse.ArgumentParser(prog="arrow_ballista_tpu.scheduler.kvstore")
    p.add_argument("--bind-host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=50060)
    p.add_argument("--db", default="", help="sqlite path (default: memory)")
    p.add_argument(
        "--replica-of", default="",
        help="host:port of the primary store — start as an async backup "
             "that self-promotes when the primary stays unreachable",
    )
    p.add_argument(
        "--peer", default="",
        help="host:port of the backup (set on the PRIMARY): if the peer "
             "is already serving as primary at startup, this store "
             "demotes to its replica instead of split-braining",
    )
    p.add_argument(
        "--promote-after", type=float, default=5.0,
        help="seconds without a primary round-trip before promotion",
    )
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO)
    backend = SqliteBackend(args.db) if args.db else MemoryBackend()
    handle = KvStoreHandle(
        backend, args.bind_host, args.port,
        replica_of=(
            parse_endpoint(args.replica_of) if args.replica_of else None
        ),
        promote_after_s=args.promote_after,
        peer=parse_endpoint(args.peer) if args.peer else None,
    ).start()
    log.info(
        "kv store serving on %s:%d (%s)", args.bind_host, handle.port,
        handle.service.role,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        handle.stop()


if __name__ == "__main__":
    main()
