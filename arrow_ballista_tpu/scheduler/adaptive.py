"""Adaptive query execution (AQE): re-plan stages from observed shuffle
statistics.

The Spark-AQE move applied to the Ballista stage DAG (PAPER.md §1:
``ExecutionGraph``/``UnresolvedShuffleExec`` is the natural re-planning
seam).  The scheduler resolves stages lazily, and by the time a consumer
resolves, every producer has already REPORTED exact per-reduce-partition
output sizes (``CompletedStage.output_partition_bytes``, from the PR 4
write path's per-fragment stats).  This module feeds those sizes back
into planning at two hook points:

* :func:`replan_stage` — called by ``ExecutionGraph.revive()`` on an
  ``UnresolvedStage`` the moment it becomes resolvable, BEFORE
  ``to_resolved()``.  Rewrites the not-yet-dispatched reduce-task
  layout in place:

  1. **partition coalescing** — pack adjacent tiny reduce partitions
     into fewer tasks until each reads ~``ballista.aqe.
     target_partition_bytes``, so a 64-way shuffle whose output is 3 MB
     runs 2 reduce tasks instead of 64;
  2. **skew splitting** — a reduce partition whose observed input
     exceeds ``ballista.aqe.skew_factor`` × median is split across K
     tasks, each reading a disjoint chunk of the map-side fragments.
     Joins duplicate the companion side's partition into every chunk
     task (each probe row still sees the full build rows for its hash
     partition, so the union of the chunk outputs IS the partition's
     join output).  A stage whose body is a final hash aggregate is
     rewritten to a merge-partial aggregate (states in → states out)
     and every consumer gets the original final merge injected above
     its reader, so results stay correct for non-decomposable outputs
     like avg.

* :func:`try_broadcast` — called when a stage COMPLETES, before its
  consumers can resolve.  When the completed stage is one side of a
  partitioned inner join and measured under ``ballista.aqe.
  broadcast_threshold_bytes`` — and the probe-side producer has not
  started — the join converts to the existing COLLECT_LEFT build-side
  broadcast path (``exec/joins.py``) and the probe-side shuffle stage
  is deleted outright, its subtree inlined into the consumer: the big
  side's rows never touch disk or the wire.

All rewrites are deterministic functions of persisted state (stats live
in ``CompletedStageProto``, the policy in ``ExecutionGraphProto.
aqe_settings_json``, the chosen layouts inside the stage plans), so HA
adoption and scheduler restart replay the same decisions.  Every rewrite
journals an ``aqe_replan`` event and stamps the stage's ``aqe`` summary
(surfaced as ``__aqe__`` stage metrics → ``/api/jobs/{id}/profile``).

A failure anywhere in here must never fail the job: the graph's hook
wrappers catch and fall back to the static plan.
"""

from __future__ import annotations

import json
import logging
import math
import statistics
from dataclasses import asdict, dataclass, fields
from typing import Dict, List, Optional, Tuple

from ..exec.aggregates import FINAL, PARTIAL, AggSpec, HashAggregateExec
from ..exec.expressions import Col
from ..exec.joins import COLLECT_LEFT, PARTITIONED, HashJoinExec
from ..exec.operators import ExecutionPlan, FilterExec, ProjectionExec
from ..exec.planner import RenameSchemaExec
from ..shuffle import UnresolvedShuffleExec
from .execution_stage import CompletedStage, ResolvedStage, RunningStage, UnresolvedStage
from .planner import find_unresolved_shuffles, rollback_resolved_shuffles

log = logging.getLogger(__name__)

# aggregate functions whose FINAL-stage merge decomposes into a partial
# re-merge over the state columns (sum→sum, count→sum of counts,
# min/max→min/max, avg→sum of its sum+count states).  Everything else
# (distinct/median/stddev/udaf) plans single-stage and never reaches a
# FINAL stage anyway.
_MERGEABLE_FUNCS = frozenset({"sum", "count", "min", "max", "avg"})


@dataclass(frozen=True)
class AqePolicy:
    """ballista.aqe.* knobs snapshot, persisted with the graph so a
    restarted/adopting scheduler replays the same decisions."""

    enabled: bool = False
    coalesce_enabled: bool = True
    broadcast_enabled: bool = False
    skew_enabled: bool = False
    target_partition_bytes: int = 16 << 20
    broadcast_threshold_bytes: int = 10 << 20
    skew_factor: float = 4.0
    max_splits: int = 8
    coalesce_min_partitions: int = 8

    @classmethod
    def from_config(cls, config) -> "AqePolicy":
        if config is None:
            return cls()
        return cls(
            enabled=config.aqe_enabled,
            coalesce_enabled=config.aqe_coalesce_enabled,
            broadcast_enabled=config.aqe_broadcast_enabled,
            skew_enabled=config.aqe_skew_enabled,
            target_partition_bytes=config.aqe_target_partition_bytes,
            broadcast_threshold_bytes=config.aqe_broadcast_threshold_bytes,
            skew_factor=config.aqe_skew_factor,
            max_splits=config.aqe_max_splits,
            coalesce_min_partitions=config.aqe_coalesce_min_partitions,
        )

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, raw: str) -> "AqePolicy":
        if not raw:
            return cls()
        try:
            data = json.loads(raw)
            known = {f.name for f in fields(cls)}
            return cls(**{k: v for k, v in data.items() if k in known})
        except Exception:  # noqa: BLE001 - tolerate future/garbage payloads
            return cls()


# --------------------------------------------------------------- structure
# single-child wrappers between a stage's shuffle writer and its join
# under which per-row independence holds: the union of the rewritten
# tasks' outputs equals the static plan's output (PARTIAL aggregates
# qualify because every downstream consumer merges partial states from
# an arbitrary number of map tasks anyway)
def _union_safe(node: ExecutionPlan) -> bool:
    if isinstance(node, (FilterExec, ProjectionExec, RenameSchemaExec)):
        return True
    return isinstance(node, HashAggregateExec) and node.mode == PARTIAL


def _body_below_wrappers(node: ExecutionPlan) -> ExecutionPlan:
    while _union_safe(node) and len(node.children()) == 1:
        node = node.children()[0]
    return node


def _split_sides(join: HashJoinExec) -> frozenset:
    """Which join inputs may be chunk-split: the side whose every row's
    output is independent of the other rows ON THAT SIDE.  Splitting the
    other side would recompute its unmatched/padded rows once per chunk."""
    if join.partition_mode == COLLECT_LEFT:
        return frozenset({"right"}) if join.join_type == "inner" else frozenset()
    return {
        "inner": frozenset({"left", "right"}),
        "left": frozenset({"left"}),
        "semi": frozenset({"left"}),
        "anti": frozenset({"left"}),
        "right": frozenset({"right"}),
    }.get(join.join_type, frozenset())


def _replace_node(
    plan: ExecutionPlan, old: ExecutionPlan, new: ExecutionPlan
) -> ExecutionPlan:
    """Rebuild ``plan`` with the (identity-matched) ``old`` subtree
    swapped for ``new``."""
    return _replace_nodes(plan, {id(old): new})


def _replace_nodes(
    plan: ExecutionPlan, mapping: Dict[int, ExecutionPlan]
) -> ExecutionPlan:
    """Swap several identity-matched subtrees (``id(old) -> new``) in
    ONE rebuild.  Sequential single swaps would not compose: the first
    rebuild replaces every interior node, so later identity keys taken
    against the ORIGINAL tree no longer match anything."""
    if id(plan) in mapping:
        return mapping[id(plan)]
    children = plan.children()
    if not children:
        return plan
    new_children = [_replace_nodes(c, mapping) for c in children]
    if all(a is b for a, b in zip(new_children, children)):
        return plan
    return plan.with_new_children(new_children)


# ----------------------------------------------------------- skew targets
def _join_split_candidates(
    plan_root, leaves: List[UnresolvedShuffleExec]
) -> List[UnresolvedShuffleExec]:
    """The leaves whose fragments may be chunk-split when the stage body
    is a join reachable through union-safe wrappers; [] when the shape
    does not qualify."""
    body = _body_below_wrappers(plan_root.input)
    if not isinstance(body, HashJoinExec):
        return []
    sides = _split_sides(body)
    if not sides:
        return []
    # every leaf of the stage must be a direct join input: a leaf hiding
    # elsewhere in the tree would not get the duplicate treatment
    join_leaves = {
        id(c)
        for c in (body.left, body.right)
        if isinstance(c, UnresolvedShuffleExec)
    }
    if any(id(l) not in join_leaves for l in leaves):
        return []
    candidates = []
    if "left" in sides and isinstance(body.left, UnresolvedShuffleExec):
        candidates.append(body.left)
    if "right" in sides and isinstance(body.right, UnresolvedShuffleExec):
        candidates.append(body.right)
    return candidates


def _merge_partial_specs(
    final_agg: HashAggregateExec,
) -> Optional[List[AggSpec]]:
    """Specs for a PARTIAL-mode aggregate that MERGES partial states and
    re-emits the same state schema (sum of sums, sum of counts, min of
    mins...); None when any function has no such decomposition."""
    state_schema = final_agg.input.schema
    specs: List[AggSpec] = []
    idx = len(final_agg.group_exprs)
    for a in final_agg.aggs:
        if a.func not in _MERGEABLE_FUNCS:
            return None
        if a.func == "avg":
            for suffix in ("#sum", "#count"):
                name = f"{a.name}{suffix}"
                specs.append(
                    AggSpec(
                        "sum", Col(idx, name), name, state_schema.field(idx).type
                    )
                )
                idx += 1
            continue
        func = a.func if a.func in ("min", "max") else "sum"
        specs.append(
            AggSpec(func, Col(idx, a.name), a.name, state_schema.field(idx).type)
        )
        idx += 1
    return specs


def _find_agg_split(
    graph, stage, leaves
) -> Optional[Tuple[HashAggregateExec, List[ExecutionPlan], HashAggregateExec]]:
    """(final aggregate, deferred wrapper chain, merge-partial node) when
    skew-splitting the
    stage's final hash aggregate is safe: the aggregate sits under the
    shuffle writer (through row-wise wrappers only — they defer
    downstream with the merge) over the single leaf, every function
    re-merges from partial state, the stage has downstream consumers
    (all still Unresolved) to carry the injected final merge, and the
    rewritten merge reproduces the exact state schema.  A writer with
    its own hash partitioning qualifies only when it hashes pure
    group-key columns (their position is identical in the state schema)
    and no wrapper sits in between (the hash would otherwise evaluate
    over wrapper output that no longer exists in this stage)."""
    if len(leaves) != 1:
        return None
    chain: List[ExecutionPlan] = []
    node = stage.plan.input
    while isinstance(node, (FilterExec, ProjectionExec, RenameSchemaExec)):
        chain.append(node)
        node = node.children()[0]
    if not (isinstance(node, HashAggregateExec) and node.mode == FINAL):
        return None
    if node.input is not leaves[0]:
        return None
    part = stage.plan.shuffle_output_partitioning
    if part is not None:
        if chain or part.kind != "hash":
            return None
        n_groups = len(node.group_exprs)
        for e in part.exprs:
            if not (isinstance(e, Col) and e.index < n_groups):
                return None
    if stage.stage_id == graph.final_stage_id or not stage.output_links:
        return None  # job output has no downstream seat for the merge
    for csid in stage.output_links:
        if not isinstance(graph.stages.get(csid), UnresolvedStage):
            return None
    specs = _merge_partial_specs(node)
    if specs is None:
        return None
    merge = HashAggregateExec(PARTIAL, node.group_exprs, specs, node.input)
    if not merge.schema.equals(node.input.schema):
        return None  # rewrite would change the shuffle's wire schema
    return node, chain, merge


def _leaf_parents(
    plan: ExecutionPlan, sid: int
) -> List[Tuple[ExecutionPlan, UnresolvedShuffleExec]]:
    """Every (parent node, placeholder) pair reading stage ``sid``."""
    out: List[Tuple[ExecutionPlan, UnresolvedShuffleExec]] = []

    def rec(node: ExecutionPlan) -> None:
        for c in node.children():
            if isinstance(c, UnresolvedShuffleExec) and c.stage_id == sid:
                out.append((node, c))
            else:
                rec(c)

    rec(plan)
    return out


def _inject_consumer_merges(graph, stage, final_agg, chain) -> bool:
    """Move the original final merge (plus any deferred row-wise wrapper
    chain) into every consumer, above a state-schema placeholder.

    Group rows of a split stage are NOT disjoint across its output
    partitions any more (two chunk tasks may both emit partial rows for
    one group):

    * a hash-partitioned producer still sends one group to one reduce
      partition, so the merge sits directly above the placeholder;
    * a partitioning=None producer's outputs are task-indexed — the
      merge must see ALL partitions at once, so it sits above the
      consumer's CoalescePartitionsExec (the planner always reads such
      a boundary through one; any other shape disqualifies the split).

    All-or-nothing: every rewrite is schema-verified before any consumer
    plan is touched."""
    from ..exec.operators import CoalescePartitionsExec

    state_schema = final_agg.input.schema
    part_is_none = stage.plan.shuffle_output_partitioning is None
    rewrites = []
    for csid in stage.output_links:
        consumer = graph.stages[csid]
        pairs = _leaf_parents(consumer.plan, stage.stage_id)
        if not pairs:
            return False
        for parent, old in pairs:
            new_leaf = UnresolvedShuffleExec(
                stage.stage_id,
                state_schema,
                old.input_partition_count,
                old.output_partition_count,
                selections=old.selections,
            )
            if part_is_none:
                if not isinstance(parent, CoalescePartitionsExec):
                    return False
                replaced: ExecutionPlan = parent
                subtree: ExecutionPlan = HashAggregateExec(
                    FINAL,
                    final_agg.group_exprs,
                    final_agg.aggs,
                    CoalescePartitionsExec(new_leaf),
                )
            else:
                replaced = old
                subtree = HashAggregateExec(
                    FINAL, final_agg.group_exprs, final_agg.aggs, new_leaf
                )
            for wrapper in reversed(chain):
                subtree = wrapper.with_new_children([subtree])
            if not subtree.schema.equals(replaced.schema):
                return False  # consumer expects a different row shape
            rewrites.append((consumer, replaced, subtree))
    # one rebuild per consumer: a consumer reading the split stage
    # through several parents must swap them all in a single pass
    grouped: Dict[int, Tuple[UnresolvedStage, Dict[int, ExecutionPlan]]] = {}
    for consumer, replaced, subtree in rewrites:
        grouped.setdefault(id(consumer), (consumer, {}))[1][
            id(replaced)
        ] = subtree
    for consumer, mapping in grouped.values():
        consumer.plan = _replace_nodes(consumer.plan, mapping)
    return True


# ------------------------------------------------------------ replan core
def replan_stage(graph, stage: UnresolvedStage) -> None:
    """Coalesce/skew-split rewrite of one about-to-resolve consumer stage
    (see module docstring).  Mutates ``stage`` (and, for an aggregate
    split, its consumers) in place; a no-op when nothing pays."""
    policy: AqePolicy = graph.aqe_policy
    if not policy.enabled or stage.aqe:
        return
    leaves = find_unresolved_shuffles(stage.plan)
    if not leaves or any(l.selections is not None for l in leaves):
        return  # already rewritten (rollback re-resolve) or nothing to do
    producers: Dict[int, CompletedStage] = {}
    for l in leaves:
        prod = graph.stages.get(l.stage_id)
        if not isinstance(prod, CompletedStage):
            return  # stats incomplete (mid-recovery resolve): stay static
        producers[l.stage_id] = prod
    counts = {l.output_partition_count for l in leaves}
    if len(counts) != 1:
        return  # differently-shaped inputs cannot share one task layout
    n = counts.pop()
    if n <= 1 or stage.plan.output_partitioning().n != n:
        return  # task count is not driven by the shuffle (e.g. coalesced)

    # one O(tasks x partitions) scan per producer, reused by every
    # consumer of the maps below (skew targeting included)
    bytes_by_sid = {
        sid: prod.output_partition_bytes() for sid, prod in producers.items()
    }
    leaf_bytes = [bytes_by_sid[l.stage_id] for l in leaves]
    total = {p: sum(b.get(p, 0) for b in leaf_bytes) for p in range(n)}

    # ---- skew candidates + structural target
    split_k: Dict[int, int] = {}
    split_leaf: Optional[UnresolvedShuffleExec] = None
    agg_target: Optional[
        Tuple[HashAggregateExec, List[ExecutionPlan], HashAggregateExec]
    ] = None
    if policy.skew_enabled:
        med = statistics.median([total[p] for p in range(n)])
        threshold = max(
            policy.skew_factor * med, float(policy.target_partition_bytes)
        )
        skewed = [p for p in range(n) if total[p] > threshold]
        if skewed:
            agg_target = _find_agg_split(graph, stage, leaves)
            if agg_target is not None:
                split_leaf = leaves[0]
            else:
                # split the heaviest qualifying join side at the skewed
                # partitions; the companion side duplicates into chunks
                candidates = _join_split_candidates(stage.plan, leaves)
                if candidates:
                    split_leaf = max(
                        candidates,
                        key=lambda l: sum(
                            bytes_by_sid[l.stage_id].get(p, 0) for p in skewed
                        ),
                    )
            if split_leaf is not None:
                side_bytes = bytes_by_sid[split_leaf.stage_id]
                # re-run the skew test against the SPLIT side's own
                # distribution: a partition whose weight sits on a
                # non-splittable companion side must stay whole — each
                # chunk task would re-read the heavy companion in full,
                # k-multiplying exactly the work the split meant to cut
                side_med = statistics.median(
                    [side_bytes.get(p, 0) for p in range(n)]
                )
                side_threshold = max(
                    policy.skew_factor * side_med,
                    float(policy.target_partition_bytes),
                )
                inp = stage.inputs.get(split_leaf.stage_id)
                for p in skewed:
                    if side_bytes.get(p, 0) <= side_threshold:
                        continue
                    frags = (
                        len(inp.partition_locations.get(p, []))
                        if inp is not None
                        else 0
                    )
                    k = min(
                        policy.max_splits,
                        frags,
                        max(
                            2,
                            math.ceil(
                                side_bytes.get(p, 0)
                                / max(1, policy.target_partition_bytes)
                            ),
                        ),
                    )
                    if k >= 2:
                        split_k[p] = k

    # ---- build the unified task layout (coalesce bins around splits)
    coalesce_on = (
        policy.coalesce_enabled and n > policy.coalesce_min_partitions
    )
    if not coalesce_on and not split_k:
        return

    def build_layout() -> Tuple[
        List[List[List[Tuple[int, int, int]]]], int, int, int
    ]:
        selections: List[List[List[Tuple[int, int, int]]]] = [
            [] for _ in leaves
        ]
        tasks_after = 0
        merged_groups = 0
        split_tasks = 0
        group: List[int] = []
        group_bytes = 0

        def flush_group() -> None:
            nonlocal tasks_after, merged_groups, group, group_bytes
            if not group:
                return
            row = [(p, 0, 1) for p in group]
            for sel in selections:
                sel.append(list(row))
            tasks_after += 1
            if len(group) > 1:
                merged_groups += 1
            group, group_bytes = [], 0

        for p in range(n):
            k = split_k.get(p)
            if k:
                flush_group()
                for i in range(k):
                    for sel, l in zip(selections, leaves):
                        sel.append(
                            [(p, i, k)] if l is split_leaf else [(p, 0, 1)]
                        )
                    tasks_after += 1
                    split_tasks += 1
                continue
            if (
                group
                and group_bytes + total[p] > policy.target_partition_bytes
            ):
                flush_group()
            group.append(p)
            group_bytes += total[p]
            if not coalesce_on:
                flush_group()
        flush_group()
        return selections, tasks_after, merged_groups, split_tasks

    selections, tasks_after, merged_groups, split_tasks = build_layout()
    if tasks_after == n and not split_tasks:
        return  # the static layout was already right-sized

    # ---- commit: consumer-merge injection first (all-or-nothing), then
    # the in-place leaf/selection + plan rewrites
    if split_tasks and agg_target is not None:
        final_agg, chain, merge = agg_target
        if _inject_consumer_merges(graph, stage, final_agg, chain):
            stage.plan = stage.plan.with_new_children([merge])
        else:
            # downstream seat unavailable: drop the split but keep the
            # independently valid coalesce-only layout (needs no merge)
            split_k.clear()
            if not coalesce_on:
                return
            selections, tasks_after, merged_groups, split_tasks = (
                build_layout()
            )
            if tasks_after == n and not split_tasks:
                return  # coalescing alone changes nothing: stay static
    for sel, l in zip(selections, leaves):
        l.selections = sel
    if (
        stage.plan.shuffle_output_partitioning is None
        and tasks_after != n
    ):
        # a partitioning=None stage's output-partition ids ARE its task
        # indices: consumers' placeholders must track the new task
        # count, or a split's extra output partitions would silently
        # fall outside their location range
        for csid in stage.output_links:
            consumer = graph.stages.get(csid)
            if isinstance(consumer, UnresolvedStage):
                for l in find_unresolved_shuffles(consumer.plan):
                    if l.stage_id == stage.stage_id:
                        l.output_partition_count = tasks_after
                        l.input_partition_count = tasks_after
    stage.aqe = {
        "tasks_before": n,
        "tasks_after": tasks_after,
        "coalesced_groups": merged_groups,
        "skew_splits": split_tasks,
        "skewed_partitions": len(split_k),
    }
    if stage.stage_id == graph.final_stage_id:
        graph.output_partitions = stage.plan.output_partitioning().n
    kinds = []
    if merged_groups or tasks_after < n:
        kinds.append("coalesce")
    if split_tasks:
        kinds.append("skew_split")
    graph._journal(
        "aqe_replan",
        stage=stage.stage_id,
        rewrite="+".join(kinds) or "coalesce",
        tasks_before=n,
        tasks_after=tasks_after,
        skewed_partitions=sorted(split_k),
        reason=(
            f"observed {sum(total.values())} B over {n} reduce partitions; "
            f"target {policy.target_partition_bytes} B/task"
            + (
                f"; split {len(split_k)} skewed partition(s) "
                f"(> {policy.skew_factor:g}x median)"
                if split_k
                else ""
            )
        ),
    )


# ------------------------------------------------------- broadcast rewrite
def _find_broadcast_join(
    plan_root, build_sid: int
) -> Optional[Tuple[HashJoinExec, UnresolvedShuffleExec]]:
    """(join, probe leaf) when the stage body is a partitioned inner
    join whose LEFT input reads ``build_sid`` and whose RIGHT input is a
    different stage's placeholder.  COLLECT_LEFT collects the left side,
    so only a small LEFT qualifies (swapping sides would permute the
    output schema)."""
    body = _body_below_wrappers(plan_root.input)
    if not isinstance(body, HashJoinExec):
        return None
    if body.partition_mode != PARTITIONED or body.join_type != "inner":
        return None
    left, right = body.left, body.right
    if not (
        isinstance(left, UnresolvedShuffleExec)
        and left.stage_id == build_sid
        and isinstance(right, UnresolvedShuffleExec)
        and right.stage_id != build_sid
    ):
        return None
    return body, right


def _probe_unstarted(stage) -> bool:
    """True while stripping the probe-side shuffle forfeits no work: the
    stage has dispatched nothing (a Running stage counts only before its
    first task is handed out — every graph mutation runs under the job
    entry lock, so this cannot race a pop)."""
    if isinstance(stage, (UnresolvedStage, ResolvedStage)):
        return True
    if isinstance(stage, RunningStage):
        return (
            all(t is None for t in stage.task_statuses)
            and not stage.speculative_statuses
            and not stage.task_attempts
        )
    return False


def try_broadcast(graph, completed_sid: int) -> None:
    """Shuffle→broadcast join conversion on ``completed_sid``'s
    consumers (see module docstring).  The probe-side producer stage is
    DELETED from the DAG: its subtree is inlined into the consumer, its
    inputs (with any already-accumulated locations) move to the
    consumer, and its own producers' output links re-point there."""
    policy: AqePolicy = graph.aqe_policy
    if not (policy.enabled and policy.broadcast_enabled):
        return
    completed = graph.stages.get(completed_sid)
    if not isinstance(completed, CompletedStage):
        return
    build_bytes = sum(completed.output_partition_bytes().values())
    if build_bytes >= policy.broadcast_threshold_bytes:
        return
    for csid in list(completed.output_links):
        consumer = graph.stages.get(csid)
        if not isinstance(consumer, UnresolvedStage) or consumer.aqe:
            continue
        found = _find_broadcast_join(consumer.plan, completed_sid)
        if found is None:
            continue
        join, probe_leaf = found
        rsid = probe_leaf.stage_id
        probe = graph.stages.get(rsid)
        if probe is None or probe.output_links != [csid]:
            continue  # another consumer still needs the probe shuffle
        if not _probe_unstarted(probe):
            continue  # probe work already paid for: nothing to save
        # a Resolved probe already materialized its readers' locations;
        # roll them back to placeholders (selections preserved) so the
        # consumer — which stays Unresolved, outside reset_stages' reach —
        # re-resolves against live locations after any executor loss
        probe_body = rollback_resolved_shuffles(probe.plan.input)
        from ..parallel.mesh_stage import MeshGangExec, MeshRepartitionExec

        if isinstance(probe_body, (MeshGangExec, MeshRepartitionExec)):
            continue  # gang bodies assume the writer's exchange contract
        tasks_before = consumer.partitions
        new_join = join.as_collect_left(right=probe_body)
        consumer.plan = _replace_node(consumer.plan, join, new_join)
        # DAG surgery: the consumer inherits the probe stage's inputs
        # (accumulated locations included) and its producers' links
        consumer.inputs.pop(rsid, None)
        for in_sid, inp in probe.inputs.items():
            consumer.inputs.setdefault(in_sid, inp)
            upstream = graph.stages.get(in_sid)
            if upstream is not None:
                links = [csid if x == rsid else x for x in upstream.output_links]
                seen: set = set()
                upstream.output_links[:] = [
                    x for x in links if not (x in seen or seen.add(x))
                ]
        del graph.stages[rsid]
        consumer.aqe = {
            "tasks_before": tasks_before,
            "tasks_after": consumer.partitions,
            "broadcast": 1,
        }
        if (
            consumer.plan.shuffle_output_partitioning is None
            and consumer.partitions != tasks_before
        ):
            # same fix-up as replan_stage: a partitioning=None stage's
            # output-partition ids ARE its task indices, and inlining the
            # probe subtree changed the task count — downstream
            # placeholders must track it or the extra partitions' rows
            # silently fall outside their location range
            for out_sid in consumer.output_links:
                downstream = graph.stages.get(out_sid)
                if isinstance(downstream, UnresolvedStage):
                    for l in find_unresolved_shuffles(downstream.plan):
                        if l.stage_id == csid:
                            l.output_partition_count = consumer.partitions
                            l.input_partition_count = consumer.partitions
        if csid == graph.final_stage_id:
            graph.output_partitions = consumer.partitions
        graph._journal(
            "aqe_replan",
            stage=csid,
            rewrite="broadcast",
            tasks_before=tasks_before,
            tasks_after=consumer.partitions,
            stripped_stage=rsid,
            reason=(
                f"build side (stage {completed_sid}) measured "
                f"{build_bytes} B < "
                f"{policy.broadcast_threshold_bytes} B; probe shuffle "
                f"stage {rsid} stripped and its subtree inlined"
            ),
        )
