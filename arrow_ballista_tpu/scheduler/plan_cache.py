"""Plan-fingerprint result/shuffle cache.

The scheduler's memory: a completed stage's shuffle output is pinned to the
external store and registered under a *canonical fingerprint* of the subplan
that produced it.  A later job whose producer subtree fingerprints to the
same value resolves its consumers directly against the cached partition
locations — the producer stage (and its whole upstream subtree) is never
dispatched.

Fingerprint = sha256 over a canonicalized encoding of the physical plan
object tree, hashed together with *source snapshot identity* (per-file
mtime_ns + size for file-backed tables, content digest for in-memory
tables).  Canonicalization strips naming noise that cannot change output
bytes — column aliases, output field names, commutative operand order,
IN-list item order — while preserving everything that can: literals,
operator structure, partitioning expression order, sort directions, UDF
bytecode.

Everything here is inert unless ``ballista.cache.enabled`` is set; with the
knob off no fingerprint is ever computed and planning/dispatch are
byte-identical to a build without this module.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ..catalog import MemoryTable
from ..config import BallistaConfig
from ..exec.expressions import (
    Binary,
    Case,
    Cast,
    Col,
    InList,
    IntervalLit,
    IsNull,
    Like,
    Lit,
    Negative,
    Not,
    ScalarFn,
    ScalarUdf,
)
from ..exec.aggregates import HashAggregateExec
from ..exec.joins import CrossJoinExec, HashJoinExec
from ..exec.operators import (
    CoalescePartitionsExec,
    EmptyExec,
    FilterExec,
    LimitExec,
    ProjectionExec,
    RepartitionExec,
    ScanExec,
    SortExec,
    UnionExec,
)
from ..exec.planner import RenameSchemaExec
from ..exec.window import WindowExec
from ..shuffle.execution_plans import ShuffleWriterExec, UnresolvedShuffleExec
from ..obs.registry import process_registry
from ..shuffle.store import upload_file
from ..udf import global_registry

__all__ = [
    "CacheIneligible",
    "plan_fingerprint",
    "stage_fingerprints",
    "PlanCache",
    "try_serve",
    "store_completed",
]


class CacheIneligible(Exception):
    """Raised when a (sub)plan cannot be safely fingerprinted.

    Unknown operators, nondeterministic functions, and source providers
    without a snapshot identity all land here; the caller treats the
    subtree as uncacheable and moves on.
    """


# Scalar functions whose output depends on more than their arguments.  A
# subtree containing one can never be served from cache.
_NONDETERMINISTIC_FNS = frozenset(
    {"random", "rand", "uuid", "now", "current_timestamp", "current_date"}
)

# Binary ops where operand order cannot change output bytes.
_COMMUTATIVE_OPS = frozenset({"AND", "OR", "+", "*", "=", "==", "!="})


# ---------------------------------------------------------------------------
# canonical expression encoding
# ---------------------------------------------------------------------------


def _canon_expr(e: Any) -> Any:
    """Canonical, JSON-able encoding of a physical expression.

    Column *names* are dropped (index-only) so alias noise collides;
    everything value-bearing is preserved.
    """
    if isinstance(e, Col):
        return ["col", e.index]
    if isinstance(e, Lit):
        return ["lit", repr(e.value), str(e.dtype)]
    if isinstance(e, IntervalLit):
        return ["interval", e.months, e.days]
    if isinstance(e, Binary):
        l, r = _canon_expr(e.left), _canon_expr(e.right)
        if e.op in _COMMUTATIVE_OPS:
            a, b = sorted(
                (json.dumps(l, sort_keys=True), json.dumps(r, sort_keys=True))
            )
            return ["bin", e.op, json.loads(a), json.loads(b)]
        return ["bin", e.op, l, r]
    if isinstance(e, Not):
        return ["not", _canon_expr(e.expr)]
    if isinstance(e, Negative):
        return ["neg", _canon_expr(e.expr)]
    if isinstance(e, IsNull):
        return ["isnull", _canon_expr(e.expr), e.negated]
    if isinstance(e, InList):
        return [
            "inlist",
            _canon_expr(e.expr),
            sorted(repr(v) for v in e.items),
            e.negated,
        ]
    if isinstance(e, Like):
        return ["like", _canon_expr(e.expr), e.pattern, e.negated]
    if isinstance(e, Case):
        return [
            "case",
            [[_canon_expr(w), _canon_expr(t)] for w, t in e.whens],
            _canon_expr(e.else_expr) if e.else_expr is not None else None,
            str(e.out_type),
        ]
    if isinstance(e, Cast):
        return ["cast", _canon_expr(e.expr), str(e.to_type)]
    if isinstance(e, ScalarUdf):
        return [
            "udf",
            e.fname,
            _udf_body_digest(e.fname),
            [_canon_expr(a) for a in e.args],
            str(e.out_type),
        ]
    if isinstance(e, ScalarFn):
        if e.fname.lower() in _NONDETERMINISTIC_FNS:
            raise CacheIneligible(f"nondeterministic function {e.fname}")
        return [
            "fn",
            e.fname,
            [_canon_expr(a) for a in e.args],
            str(e.out_type),
        ]
    raise CacheIneligible(f"unknown expression {type(e).__name__}")


def _udf_body_digest(fname: str) -> str:
    """Digest of a UDF's bytecode so edited bodies diverge.

    An unregistered name (scheduler never saw the UDF) gets a sentinel —
    fingerprints still work, but two different unregistered bodies under
    one name would collide, so registration is the contract.
    """
    try:
        spec = global_registry().scalar(fname)
    except Exception:
        spec = None
    if spec is None:
        return "unregistered"
    code = spec.fn.__code__
    h = hashlib.sha256()
    h.update(code.co_code)
    h.update(repr(code.co_consts).encode())
    return h.hexdigest()[:16]


def _canon_schema(schema: Any) -> list:
    """Types + nullability only — field names are alias noise."""
    return [[str(f.type), bool(f.nullable)] for f in schema]


def _canon_partitioning(p: Any) -> Any:
    if p is None:
        return None
    exprs = [_canon_expr(e) for e in (p.exprs or [])] if p.exprs else []
    # expr ORDER is load-bearing: it decides which row hashes to which
    # output partition, so two orders produce differently-laid-out bytes.
    return [p.kind, p.n, exprs]


# ---------------------------------------------------------------------------
# source snapshot identity
# ---------------------------------------------------------------------------


def _snapshot_of(provider: Any) -> Any:
    """Identity of the data behind a scan *right now*.

    File-backed: per-file (path, mtime_ns, size).  In-memory: the
    describe() already embeds the data hex, so content IS the snapshot.
    Providers exposing an ``etag`` use it directly.
    """
    etag = getattr(provider, "etag", None)
    if etag:
        return ["etag", str(etag)]
    if isinstance(provider, MemoryTable):
        return ["inline"]  # content-addressed via describe()
    files = getattr(provider, "files", None)
    if files:
        snap = []
        for f in sorted(files):
            try:
                st = os.stat(f)
                snap.append([f, st.st_mtime_ns, st.st_size])
            except OSError:
                snap.append([f, "missing", 0])
        return ["files", snap]
    path = getattr(provider, "path", None)
    if path:
        try:
            st = os.stat(path)
            return ["files", [[path, st.st_mtime_ns, st.st_size]]]
        except OSError:
            return ["files", [[path, "missing", 0]]]
    raise CacheIneligible(
        f"provider {type(provider).__name__} has no snapshot identity"
    )


# ---------------------------------------------------------------------------
# canonical plan encoding
# ---------------------------------------------------------------------------


def _canon_plan(p: Any, child_fps: dict[int, str], with_snapshot: bool) -> Any:
    # TPU wrapper nodes fingerprint as the plan they wrap
    orig = getattr(p, "original", None)
    if orig is not None and type(p).__name__ in ("TpuStageExec", "TpuWindowExec"):
        return _canon_plan(orig, child_fps, with_snapshot)
    if isinstance(p, ScanExec):
        desc = dict(p.provider.describe())
        if not with_snapshot and "data" in desc:
            # shape fingerprint: inline memory-table bytes are a
            # snapshot, not a shape — keep only the schema identity
            desc["data"] = _canon_schema(p.schema)
        node = [
            "scan",
            json.dumps(desc, sort_keys=True, default=str),
            list(p.projection) if p.projection is not None else None,
        ]
        if with_snapshot:
            node.append(_snapshot_of(p.provider))
        return node
    if isinstance(p, FilterExec):
        return [
            "filter",
            _canon_expr(p.predicate),
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, ProjectionExec):
        # output names dropped — consumers address columns by index
        return [
            "project",
            [_canon_expr(e) for e, _name in p.exprs],
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, HashAggregateExec):
        return [
            "agg",
            p.mode,
            [_canon_expr(e) for e, _name in p.group_exprs],
            [
                [
                    a.func,
                    _canon_expr(a.arg) if a.arg is not None else None,
                    _canon_expr(a.arg2) if a.arg2 is not None else None,
                    str(a.out_type),
                ]
                for a in p.aggs
            ],
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, HashJoinExec):
        return [
            "hashjoin",
            p.join_type,
            p.partition_mode,
            [[_canon_expr(l), _canon_expr(r)] for l, r in p.on],
            _canon_expr(p.filter) if p.filter is not None else None,
            _canon_plan(p.left, child_fps, with_snapshot),
            _canon_plan(p.right, child_fps, with_snapshot),
        ]
    if isinstance(p, CrossJoinExec):
        return [
            "crossjoin",
            _canon_plan(p.left, child_fps, with_snapshot),
            _canon_plan(p.right, child_fps, with_snapshot),
        ]
    if isinstance(p, SortExec):
        return [
            "sort",
            [[_canon_expr(e), bool(asc), bool(nf)] for e, asc, nf in p.sort_keys],
            p.fetch,
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, WindowExec):
        return [
            "window",
            [
                [
                    s.func,
                    _canon_expr(s.arg) if s.arg is not None else None,
                    [_canon_expr(e) for e in s.partition_by],
                    [
                        [_canon_expr(e), bool(asc), bool(nf)]
                        for e, asc, nf in s.order_by
                    ],
                    str(s.out_type),
                    s.offset,
                    list(s.frame) if s.frame is not None else None,
                ]
                for s in p.specs
            ],
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, LimitExec):
        return [
            "limit",
            p.skip,
            p.fetch,
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, UnionExec):
        # branch order is load-bearing: output partitions concatenate
        return [
            "union",
            [_canon_plan(i, child_fps, with_snapshot) for i in p.inputs],
        ]
    if isinstance(p, RepartitionExec):
        return [
            "repartition",
            _canon_partitioning(p.partitioning),
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, CoalescePartitionsExec):
        return ["coalesce", _canon_plan(p.input, child_fps, with_snapshot)]
    if isinstance(p, RenameSchemaExec):
        # pure renaming: transparent for fingerprinting
        return _canon_plan(p.input, child_fps, with_snapshot)
    if isinstance(p, EmptyExec):
        return ["empty", bool(p.produce_one_row), _canon_schema(p.schema)]
    if isinstance(p, ShuffleWriterExec):
        # job/stage ids are session noise; the partitioning decides bytes
        return [
            "shuffle_write",
            _canon_partitioning(p.shuffle_output_partitioning),
            _canon_plan(p.input, child_fps, with_snapshot),
        ]
    if isinstance(p, UnresolvedShuffleExec):
        fp = child_fps.get(p.stage_id)
        if fp is None:
            raise CacheIneligible(f"producer stage {p.stage_id} ineligible")
        return [
            "shuffle_read",
            fp,
            sorted(p.selections) if p.selections else None,
        ]
    n = type(p).__name__
    if n in ("MeshRepartitionExec", "MeshGangExec"):
        inner = _canon_plan(p.input, child_fps, with_snapshot)
        if n == "MeshRepartitionExec":
            return ["mesh_repart", _canon_partitioning(p.partitioning), inner]
        return ["mesh_gang", inner]
    raise CacheIneligible(f"unknown operator {n}")


def plan_fingerprint(
    plan: Any,
    child_fps: dict[int, str] | None = None,
    with_snapshot: bool = True,
) -> str:
    """sha256 hexdigest of the canonical encoding of ``plan``.

    ``child_fps`` maps producer stage_id → fingerprint for any
    UnresolvedShuffleExec leaves.  ``with_snapshot=False`` yields a pure
    *shape* fingerprint (used by the policy store, where knob overrides
    apply regardless of the data snapshot).

    Raises :class:`CacheIneligible` for plans that can't be fingerprinted.
    """
    tree = _canon_plan(plan, child_fps or {}, with_snapshot)
    blob = json.dumps(tree, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def stage_fingerprints(stages: dict[int, Any]) -> dict[int, str]:
    """Fingerprint every stage plan bottom-up.

    ``stages`` maps stage_id → physical plan (the stage's full plan,
    ShuffleWriterExec root for producers).  A stage whose own plan — or
    any producer it reads — is ineligible is simply absent from the
    result; its consumers become ineligible too (their shuffle_read leaf
    has no child fingerprint to substitute).
    """
    from .planner import find_unresolved_shuffles

    deps = {sid: find_unresolved_shuffles(p) for sid, p in stages.items()}
    fps: dict[int, str] = {}
    remaining = dict(stages)
    while remaining:
        progressed = False
        for sid in sorted(remaining):
            if any(d not in fps and d in stages for d in deps[sid]):
                if all(d in fps or d in remaining for d in deps[sid]):
                    continue  # wait for producers still in flight
            try:
                fps[sid] = plan_fingerprint(remaining[sid], fps)
            except CacheIneligible:
                pass
            del remaining[sid]
            progressed = True
        if not progressed:  # pragma: no cover - cycle guard
            break
    return fps


# ---------------------------------------------------------------------------
# the cache proper
# ---------------------------------------------------------------------------


def _registry_counters():
    reg = process_registry()
    return (
        reg.counter("plan_cache_hits_total", "plan-cache fingerprint hits"),
        reg.counter("plan_cache_misses_total", "plan-cache fingerprint misses"),
        reg.counter("plan_cache_stores_total", "plan-cache entries stored"),
        reg.counter("plan_cache_evictions_total", "plan-cache entries evicted"),
    )


@dataclass
class CacheEntry:
    fingerprint: str
    job_id: str
    stage_id: int
    n_tasks: int
    # tasks[k] = list of partition dicts written by producer task k:
    #   {"partition_id", "path", "num_batches", "num_rows", "num_bytes"}
    tasks: list = field(default_factory=list)
    bytes: int = 0
    created_unix: float = 0.0
    last_used_unix: float = 0.0
    hits: int = 0
    schema_names: list = field(default_factory=list)
    plan: str = ""

    def to_dict(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "job_id": self.job_id,
            "stage_id": self.stage_id,
            "n_tasks": self.n_tasks,
            "tasks": self.tasks,
            "bytes": self.bytes,
            "created_unix": self.created_unix,
            "last_used_unix": self.last_used_unix,
            "hits": self.hits,
            "schema_names": self.schema_names,
            "plan": self.plan,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CacheEntry":
        return cls(**{k: d.get(k) for k in cls.__dataclass_fields__ if k in d})


class PlanCache:
    """Durable fingerprint → cached-shuffle-output index.

    Partition files live under ``root_dir/<fp>/t<task>_p<part>.arrow``; the
    index itself is ``root_dir/index.json`` (atomic rewrite).  Thread-safe;
    one instance is shared by the scheduler's task manager.
    """

    def __init__(self, root_dir: str):
        self.root_dir = root_dir
        self._lock = threading.Lock()
        self._entries: dict[str, CacheEntry] = {}
        # fingerprints evicted by the most recent store(); the caller
        # drains them into cache_evicted journal events
        self.evicted_fps: list = []
        self._hits, self._misses, self._stores, self._evictions = (
            _registry_counters()
        )
        os.makedirs(root_dir, exist_ok=True)
        self._load()

    # -- persistence --------------------------------------------------------

    def _index_path(self) -> str:
        return os.path.join(self.root_dir, "index.json")

    def _load(self) -> None:
        try:
            with open(self._index_path()) as f:
                raw = json.load(f)
            self._entries = {
                fp: CacheEntry.from_dict(d) for fp, d in raw.items()
            }
        except (OSError, ValueError):
            self._entries = {}

    def _save_locked(self) -> None:
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {fp: e.to_dict() for fp, e in self._entries.items()}, f
            )
        os.replace(tmp, self._index_path())

    # -- lookup / store / evict --------------------------------------------

    def lookup(self, fp: str, config: BallistaConfig) -> CacheEntry | None:
        """Return a live entry for ``fp`` or None (counting hit/miss).

        Validates TTL and on-disk file existence; a stale or hollow entry
        is evicted and reported as a miss.  Existence only shrinks the
        window — a file lost *after* lookup degrades through the normal
        lost-shuffle recovery path at fetch time.
        """
        now = time.time()
        with self._lock:
            e = self._entries.get(fp)
            if e is not None and now - e.created_unix > config.cache_ttl_seconds:
                self._evict_locked(fp, reason="ttl")
                e = None
            if e is not None:
                for task in e.tasks:
                    if any(not os.path.exists(p["path"]) for p in task):
                        self._evict_locked(fp, reason="lost")
                        e = None
                        break
            if e is None:
                self._misses.inc()
                return None
            e.hits += 1
            e.last_used_unix = now
            self._hits.inc()
            self._save_locked()
            return e

    def store(
        self,
        fp: str,
        job_id: str,
        stage_id: int,
        task_partitions: list,
        schema_names: list,
        plan_summary: str,
        config: BallistaConfig,
    ) -> CacheEntry | None:
        """Pin a completed stage's output under ``fp``.

        ``task_partitions[k]`` is the list of ShuffleWritePartitions
        written by producer task ``k`` (source paths on local disk or the
        external store).  Returns the new entry, or None if any source
        file is unavailable (partial uploads are rolled back).
        """
        with self._lock:
            if fp in self._entries:
                return self._entries[fp]
        dest_dir = os.path.join(self.root_dir, fp)
        os.makedirs(dest_dir, exist_ok=True)
        tasks, total = [], 0
        try:
            for k, parts in enumerate(task_partitions):
                out = []
                for p in parts:
                    src = None
                    for cand in (p.replica_path, p.path):
                        if cand and os.path.exists(cand):
                            src = cand
                            break
                    if src is None:
                        raise FileNotFoundError(p.path)
                    dest = os.path.join(
                        dest_dir, f"t{k}_p{p.partition_id}.arrow"
                    )
                    total += upload_file(src, dest)
                    out.append(
                        {
                            "partition_id": p.partition_id,
                            "path": dest,
                            "num_batches": p.num_batches,
                            "num_rows": p.num_rows,
                            "num_bytes": p.num_bytes,
                        }
                    )
                tasks.append(out)
        except OSError:
            self._remove_dir(dest_dir)
            return None
        if total > config.cache_max_bytes:
            self._remove_dir(dest_dir)  # never fits
            return None
        now = time.time()
        entry = CacheEntry(
            fingerprint=fp,
            job_id=job_id,
            stage_id=stage_id,
            n_tasks=len(task_partitions),
            tasks=tasks,
            bytes=total,
            created_unix=now,
            last_used_unix=now,
            schema_names=list(schema_names),
            plan=plan_summary,
        )
        with self._lock:
            if fp in self._entries:  # lost a store race: keep the first
                self._remove_dir(dest_dir)
                return self._entries[fp]
            self._entries[fp] = entry
            self._stores.inc()
            self.evicted_fps = self._enforce_locked(config)
            self._save_locked()
        return entry

    def _enforce_locked(self, config: BallistaConfig) -> list[str]:
        """TTL sweep + LRU bytes eviction; returns evicted fingerprints."""
        now = time.time()
        out = []
        for fp in [
            fp
            for fp, e in self._entries.items()
            if now - e.created_unix > config.cache_ttl_seconds
        ]:
            self._evict_locked(fp, reason="ttl")
            out.append(fp)
        while (
            sum(e.bytes for e in self._entries.values())
            > config.cache_max_bytes
            and len(self._entries) > 1
        ):
            lru = min(
                self._entries.values(), key=lambda e: e.last_used_unix
            ).fingerprint
            self._evict_locked(lru, reason="bytes")
            out.append(lru)
        return out

    def _evict_locked(self, fp: str, reason: str) -> None:
        e = self._entries.pop(fp, None)
        if e is None:
            return
        self._evictions.inc()
        self._remove_dir(os.path.join(self.root_dir, fp))
        self._save_locked()

    def _remove_dir(self, d: str) -> None:
        try:
            for name in os.listdir(d):
                try:
                    os.remove(os.path.join(d, name))
                except OSError:
                    pass
            os.rmdir(d)
        except OSError:
            pass

    def invalidate(self, fp: str) -> bool:
        with self._lock:
            present = fp in self._entries
            self._evict_locked(fp, reason="explicit")
            return present

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        reg = process_registry()
        with self._lock:
            entries = [
                {
                    "fingerprint": e.fingerprint,
                    "job_id": e.job_id,
                    "stage_id": e.stage_id,
                    "n_tasks": e.n_tasks,
                    "bytes": e.bytes,
                    "hits": e.hits,
                    "created_unix": e.created_unix,
                    "last_used_unix": e.last_used_unix,
                    "plan": e.plan,
                }
                for e in sorted(
                    self._entries.values(),
                    key=lambda e: -e.last_used_unix,
                )
            ]
            total = sum(e.bytes for e in self._entries.values())
        return {
            "entries": entries,
            "entry_count": len(entries),
            "total_bytes": total,
            "hits": reg.value("plan_cache_hits_total"),
            "misses": reg.value("plan_cache_misses_total"),
            "stores": reg.value("plan_cache_stores_total"),
            "evictions": reg.value("plan_cache_evictions_total"),
        }


# ---------------------------------------------------------------------------
# graph integration: serve at submit, store at completion
# ---------------------------------------------------------------------------


def _schema_names(plan: Any) -> list[str]:
    try:
        return [f.name for f in plan.schema]
    except Exception:  # noqa: BLE001 - names are a guard, not a requirement
        return []


def _upstream_subtree(sid: int, deps: dict[int, list[int]]) -> set[int]:
    """Every stage feeding ``sid`` transitively, excluding ``sid``."""
    out: set[int] = set()
    frontier = list(deps.get(sid, []))
    while frontier:
        s = frontier.pop()
        if s in out:
            continue
        out.add(s)
        frontier.extend(deps.get(s, []))
    return out


def try_serve(graph: Any, cache: PlanCache, config: BallistaConfig) -> list[int]:
    """Resolve cache-hit subtrees of a freshly-built graph.

    Called by the task manager between graph construction and the first
    ``revive()``: every stage is still in its born state.  Iterates stages
    largest-first (the final stage has the max id) so the biggest matching
    subtree wins; a served stage becomes a fabricated CompletedStage whose
    tasks point at the cached partition files under the external sentinel
    executor, its consumers' inputs complete instantly, and its upstream
    subtree is marked elided (revive never dispatches it).

    A subtree is served only when it is *self-contained* — no interior
    stage feeds a consumer outside it.  A shared producer (diamond DAG)
    must still run for its other consumer, and half-reviving a subtree on
    cache loss would otherwise double-feed that consumer.

    Stores the full fingerprint map on ``graph.cache_fps`` (the
    completion-side store path reuses it) and returns the served sids."""
    from .execution_stage import CompletedStage, StageInput, TaskInfo
    from .planner import find_unresolved_shuffles
    from ..obs.export import CACHE_OP
    from ..serde.scheduler_types import (
        PartitionId,
        PartitionLocation,
        PartitionStats,
        ShuffleWritePartition,
    )
    from ..shuffle.store import EXTERNAL_EXECUTOR, EXTERNAL_EXECUTOR_ID

    plans = {sid: s.plan for sid, s in graph.stages.items()}
    fps = stage_fingerprints(plans)
    graph.cache_fps = fps
    graph.cache_stored = set()
    deps = {
        sid: [sh.stage_id for sh in find_unresolved_shuffles(p)]
        for sid, p in plans.items()
    }
    consumers = {sid: list(graph.stages[sid].output_links) for sid in plans}
    served: list[int] = []
    for sid in sorted(graph.stages, reverse=True):
        if sid in graph.cache_elided or sid in graph.cache_served:
            continue
        fp = fps.get(sid)
        if fp is None:
            continue
        subtree = _upstream_subtree(sid, deps)
        closed = {sid} | subtree
        if any(
            c not in closed for s in subtree for c in consumers.get(s, [])
        ):
            continue  # shared interior producer: not self-contained
        entry = cache.lookup(fp, config)
        if entry is None:
            continue
        stage = graph.stages[sid]
        is_final = sid == graph.final_stage_id
        if is_final and entry.schema_names != _schema_names(stage.plan):
            # alias-normalized fingerprints collide across output names,
            # but the FINAL stage's IPC files embed field names the
            # client surfaces — only an exact-name entry may serve it
            continue
        statuses, locations = [], []
        for k, parts in enumerate(entry.tasks):
            pid = PartitionId(graph.job_id, sid, k)
            swps = []
            for p in parts:
                swp = ShuffleWritePartition(
                    p["partition_id"],
                    p["path"],
                    p["num_batches"],
                    p["num_rows"],
                    p["num_bytes"],
                )
                swps.append(swp)
                locations.append(
                    PartitionLocation(
                        PartitionId(graph.job_id, sid, p["partition_id"]),
                        EXTERNAL_EXECUTOR,
                        PartitionStats(
                            p["num_rows"], p["num_batches"], p["num_bytes"]
                        ),
                        p["path"],
                    )
                )
            statuses.append(
                TaskInfo(pid, "completed", EXTERNAL_EXECUTOR_ID, partitions=swps)
            )
        completed = CompletedStage(
            sid,
            stage.plan,
            list(stage.output_links),
            {d: StageInput(complete=True) for d in deps.get(sid, [])},
            statuses,
            stage_metrics={
                CACHE_OP: {"cache_hit": 1, "bytes": int(entry.bytes)}
            },
        )
        graph.stages[sid] = completed
        graph.cache_served[sid] = fp
        graph.cache_elided.update(subtree)
        for link in consumers.get(sid, []):
            consumer = graph.stages.get(link)
            if hasattr(consumer, "add_input_partitions"):
                consumer.add_input_partitions(sid, locations)
                consumer.complete_input(sid)
        if is_final:
            # full-plan hit: the job is complete before a single task is
            # dispatched; the submit path routes it through complete_job
            from .execution_graph import COMPLETED

            graph.output_locations = locations
            graph.status = COMPLETED
        graph._journal(
            "cache_hit",
            stage=sid,
            fingerprint=fp,
            stages_elided=sorted(subtree),
            bytes=int(entry.bytes),
            full_plan=is_final,
        )
        served.append(sid)
    return served


def store_completed(
    graph: Any, cache: PlanCache, config: BallistaConfig
) -> list[str]:
    """Pin newly-completed eligible stages' outputs under their
    fingerprints.  Called by the task manager after task-status updates
    commit; idempotent per stage per graph (``graph.cache_stored``).
    Returns the fingerprints stored this call."""
    from .execution_stage import CompletedStage

    fps = getattr(graph, "cache_fps", None)
    if not fps:
        return []  # decoded/adopted graph: fingerprints didn't survive
    done = getattr(graph, "cache_stored", None)
    if done is None:
        done = graph.cache_stored = set()
    stored: list[str] = []
    for sid, stage in graph.stages.items():
        if (
            sid in done
            or sid in graph.cache_served
            or sid not in fps
            or not isinstance(stage, CompletedStage)
        ):
            continue
        done.add(sid)
        task_partitions = [
            list(t.partitions)
            for t in stage.task_statuses
            if t is not None
        ]
        entry = cache.store(
            fps[sid],
            graph.job_id,
            sid,
            task_partitions,
            _schema_names(stage.plan),
            f"stage {sid}: {type(stage.plan).__name__}",
            config,
        )
        if entry is None:
            continue
        stored.append(entry.fingerprint)
        graph._journal(
            "cache_store",
            stage=sid,
            fingerprint=entry.fingerprint,
            bytes=int(entry.bytes),
            tasks=entry.n_tasks,
        )
        for fp in getattr(cache, "evicted_fps", None) or []:
            graph._journal("cache_evicted", fingerprint=fp)
        cache.evicted_fps = []
    return stored
