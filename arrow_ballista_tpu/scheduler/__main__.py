"""Scheduler process binary: ``python -m arrow_ballista_tpu.scheduler``.

Counterpart of the reference's ``scheduler/src/main.rs:70-243`` +
``scheduler_config_spec.toml:23-102``.  Config precedence mirrors
configure_me: defaults < ``--config-file`` (TOML) < ``BALLISTA_SCHEDULER_*``
env vars < CLI flags.  One gRPC server carries both the SchedulerGrpc and
the KEDA ExternalScaler services (the reference muxes them on one hyper
server); REST serves on its own port (grpcio owns its socket, so
Accept-header muxing isn't possible — documented divergence), and the
FlightSQL front-end is opt-in like the reference's ``flight-sql`` feature.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import time


CONFIG_KEYS = {
    # key: (type, default, help)
    "bind_host": (str, "0.0.0.0", "local address to bind"),
    "external_host": (str, "", "address advertised to executors as curator"),
    "bind_port": (int, 50050, "scheduler gRPC port"),
    "rest_port": (int, -1, "REST API port (-1 = bind_port+1, 0 = disabled)"),
    "flight_sql_port": (int, 0, "FlightSQL port (0 = disabled)"),
    "scheduler_policy": (str, "pull-staged", "pull-staged | push-staged"),
    "config_backend": (str, "memory", "memory | sqlite | etcd"),
    "db_path": (str, "", "sqlite db path (config_backend=sqlite)"),
    "etcd_urls": (str, "localhost:2379", "etcd endpoints (config_backend=etcd)"),
    "namespace": (str, "ballista", "state key namespace"),
    "work_dir": (str, "/tmp/ballista-tpu", "scratch dir for plans"),
    "plugin_dir": (str, "", "directory of UDF plugin .py modules"),
    "executor_timeout_seconds": (int, 180, "expire executors after this"),
    "quarantine_threshold": (int, 5, "failures in-window that quarantine an executor; 0 disables"),
    "quarantine_window_seconds": (float, 60.0, "sliding window for the per-executor failure count"),
    "quarantine_backoff_seconds": (float, 30.0, "reservation exclusion period for quarantined executors"),
    "speculation_enabled": (int, 0, "1 = speculatively re-run stragglers for every session (sessions can also opt in via ballista.speculation.enabled)"),
    "speculation_interval_seconds": (float, 1.0, "period of the straggler/deadline scan on the event loop"),
    "task_timeout_seconds": (float, 0.0, "reap running tasks older than this for every session (0 = off; sessions can set ballista.task.timeout_seconds)"),
    "drain_timeout_seconds": (float, 30.0, "graceful-decommission budget handed to a draining executor (DecommissionExecutor RPC / POST /api/executors/{id}/decommission)"),
    "aqe_enabled": (int, 0, "1 = adaptive query execution (re-plan stages from observed shuffle stats) as the cluster-wide default; an explicit session ballista.aqe.* setting wins"),
    "admission_enabled": (int, 0, "1 = multi-tenant admission control (queue, weighted fair release, ClusterSaturated shed) as the cluster-wide default; an explicit session ballista.admission.* setting wins unless pinned via --admission-defaults"),
    "admission_defaults": (str, "", "comma-separated ballista.admission.* key=value pairs PINNED cluster-wide (e.g. 'ballista.admission.max_queued_jobs=200,ballista.admission.shed_policy=oldest'); pinned limits ignore session settings so no tenant can rewrite another tenant's gates"),
    "admission_wal_enabled": (int, 0, "1 = journal queued admission jobs + cancel intents through the state backend so a restarted (or adopting) scheduler re-enqueues them in submit order; durability follows the backend (sqlite/etcd survive process death)"),
    "cache_enabled": (int, 0, "1 = plan-fingerprint result/shuffle cache (serve repeat subplans from the external store without re-running their stages) as the cluster-wide default; an explicit session ballista.cache.* setting wins"),
    "cache_policy_enabled": (int, 0, "1 = learned per-plan policy (merge measured knob overrides beneath explicit session settings on repeat submissions) as the cluster-wide default"),
    "cache_settings": (str, "", "comma-separated ballista.cache.* key=value pairs seeded cluster-wide (e.g. 'ballista.cache.max_bytes=268435456,ballista.cache.ttl_seconds=600')"),
    "obs_enabled": (int, 0, "1 = trace every session's jobs even without ballista.obs.enabled"),
    "event_journal_dir": (str, "", "directory for the append-only structured event journal (empty = disabled; see /api/jobs/{id}/events and /api/events/tail)"),
    "event_journal_rotate_bytes": (int, 4 << 20, "rotate the active journal segment past this size"),
    "event_journal_segments": (int, 4, "rotated journal segments kept before the oldest is deleted"),
    "telemetry_sample_seconds": (float, 5.0, "period of the cluster-aggregate telemetry sample (queue depth, slots, shuffle backlog) feeding /api/cluster/timeseries"),
    "autoscaler_enabled": (int, 0, "1 = closed-loop executor autoscaling: launch on sustained slot deficit / queued jobs / SLO burn, drain on sustained idle, heal crashed children (see docs/user-guide/autoscaling.md)"),
    "autoscaler_settings": (str, "", "comma-separated ballista.autoscaler.* key=value pairs for the policy (e.g. 'ballista.autoscaler.min_executors=1,ballista.autoscaler.max_executors=8')"),
    "autoscaler_executor_slots": (int, 2, "task slots per autoscaler-launched executor (sizes the slot-deficit math)"),
    "autoscaler_work_dir": (str, "", "work-dir root for autoscaler-launched executors (default: a fresh temp dir); a RESTARTED scheduler pointed at the same directory adopts surviving children via their persisted pid files instead of launching a duplicate fleet"),
    "autoscaler_heartbeat_seconds": (float, 5.0, "heartbeat interval passed to autoscaler-launched executors (must be comfortably below --executor-timeout-seconds)"),
    "log_level_setting": (str, "INFO", "log filter"),
    "log_dir": (str, "", "write logs to a file here instead of stdout"),
    "log_file_name_prefix": (str, "scheduler", "log file prefix"),
}


def load_config(argv=None) -> dict:
    cfg = {k: v[1] for k, v in CONFIG_KEYS.items()}

    ap = argparse.ArgumentParser("ballista-tpu scheduler")
    ap.add_argument("--config-file", default=None, help="TOML config file")
    for k, (typ, default, hlp) in CONFIG_KEYS.items():
        ap.add_argument(f"--{k.replace('_', '-')}", type=typ, default=None, help=hlp)
    args = ap.parse_args(argv)

    if args.config_file:
        import tomllib

        with open(args.config_file, "rb") as f:
            for k, v in tomllib.load(f).items():
                k = k.replace("-", "_")
                if k in cfg:
                    cfg[k] = CONFIG_KEYS[k][0](v)
    for k in CONFIG_KEYS:
        env = os.environ.get(f"BALLISTA_SCHEDULER_{k.upper()}")
        if env is not None:
            cfg[k] = CONFIG_KEYS[k][0](env)
    for k in CONFIG_KEYS:
        v = getattr(args, k, None)
        if v is not None:
            cfg[k] = v
    return cfg


def init_logging(cfg: dict, prefix_key: str = "log_file_name_prefix") -> None:
    """Mirror of both binaries' tracing init (scheduler main.rs:173-194)."""
    level = getattr(logging, cfg["log_level_setting"].upper(), logging.INFO)
    handlers = None
    if cfg["log_dir"]:
        os.makedirs(cfg["log_dir"], exist_ok=True)
        stamp = time.strftime("%Y-%m-%d")
        path = os.path.join(cfg["log_dir"], f"{cfg[prefix_key]}.{stamp}.log")
        handlers = [logging.FileHandler(path)]
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(threadName)s %(name)s: %(message)s",
        handlers=handlers,
        force=True,
    )


def _parse_admission_defaults(raw: str) -> dict:
    """``k=v,k=v`` → dict of operator-pinned ballista.admission.* keys;
    validation (key names, value types) happens in SchedulerState."""
    out = {}
    for pair in (raw or "").split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, sep, value = pair.partition("=")
        if not sep:
            raise SystemExit(
                f"--admission-defaults entry {pair!r} is not key=value"
            )
        out[key.strip()] = value.strip()
    return out


def make_backend(cfg: dict):
    from .backend import EtcdBackend, MemoryBackend, SqliteBackend

    kind = cfg["config_backend"].lower()
    if kind == "memory":
        return MemoryBackend()
    if kind == "sqlite":
        path = cfg["db_path"] or os.path.join(cfg["work_dir"], "scheduler.db")
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return SqliteBackend(path)
    if kind == "etcd":
        return EtcdBackend(cfg["etcd_urls"], cfg["namespace"])
    raise SystemExit(f"unknown config backend {kind!r}")


def main(argv=None) -> None:
    from ..utils import apply_jax_platform_env

    apply_jax_platform_env()
    cfg = load_config(argv)
    init_logging(cfg)
    log = logging.getLogger("ballista.scheduler")

    from ..config import TaskSchedulingPolicy
    from ..proto.rpc import add_scheduler_servicer, make_server
    from .api import ApiServerHandle
    from .external_scaler import ExternalScalerService, add_external_scaler_servicer
    from .grpc_service import SchedulerGrpcService
    from .server import SchedulerServer

    if cfg["plugin_dir"]:
        from ..udf import load_udf_plugins

        n = load_udf_plugins(cfg["plugin_dir"])
        log.info("loaded %d UDF plugin(s) from %s", n, cfg["plugin_dir"])

    policy = (
        TaskSchedulingPolicy.PUSH_STAGED
        if cfg["scheduler_policy"] == "push-staged"
        else TaskSchedulingPolicy.PULL_STAGED
    )
    if cfg["obs_enabled"]:
        from ..obs import get_recorder, trace, trace_store

        trace.configure(enabled=True, process="scheduler")
        get_recorder().set_forward(trace_store().add)
        log.info("observability forced on (--obs-enabled)")

    backend = make_backend(cfg)
    # the curator address executors dial back: must be reachable, never
    # the 0.0.0.0 wildcard.  It is also the STABLE scheduler identity —
    # fixed before init() so the first liveness heartbeat, active-job
    # recovery and admission-WAL replay all run under the same id a
    # previous incarnation used (a uuid-suffixed id would strand its
    # heartbeats and WAL entries every restart).
    external = cfg["external_host"] or cfg["bind_host"]
    if external == "0.0.0.0":
        external = "127.0.0.1"
    scheduler_id = f"{external}:{cfg['bind_port']}"
    server = SchedulerServer(
        scheduler_id,
        backend,
        policy,
        work_dir=cfg["work_dir"],
        executor_timeout_s=cfg["executor_timeout_seconds"],
        quarantine_threshold=cfg["quarantine_threshold"],
        quarantine_window_s=cfg["quarantine_window_seconds"],
        quarantine_backoff_s=cfg["quarantine_backoff_seconds"],
        speculation_interval_s=cfg["speculation_interval_seconds"],
        speculation_force_enabled=bool(cfg["speculation_enabled"]),
        task_timeout_force_s=cfg["task_timeout_seconds"],
        aqe_force_enabled=bool(cfg["aqe_enabled"]),
        admission_force_enabled=bool(cfg["admission_enabled"]),
        admission_defaults=_parse_admission_defaults(cfg["admission_defaults"]),
        admission_wal_enabled=bool(cfg["admission_wal_enabled"]),
        cache_force_enabled=bool(cfg["cache_enabled"]),
        cache_policy_force_enabled=bool(cfg["cache_policy_enabled"]),
        cache_settings=_parse_admission_defaults(cfg["cache_settings"]),
        drain_timeout_s=cfg["drain_timeout_seconds"],
        telemetry_sample_s=cfg["telemetry_sample_seconds"],
        event_journal_dir=cfg["event_journal_dir"],
        event_journal_rotate_bytes=cfg["event_journal_rotate_bytes"],
        event_journal_segments=cfg["event_journal_segments"],
    ).init()

    # elastic lifecycle: the flag (or an explicit settings key) turns the
    # loop on; the subprocess provider launches executors that dial the
    # advertised curator address
    autoscaler_settings = _parse_admission_defaults(cfg["autoscaler_settings"])
    if cfg["autoscaler_enabled"]:
        autoscaler_settings.setdefault("ballista.autoscaler.enabled", "true")
    from .autoscaler import AutoscalerPolicy

    if AutoscalerPolicy.enabled_in(autoscaler_settings):
        from .autoscaler import LocalProcessProvider

        provider = LocalProcessProvider(
            external,
            cfg["bind_port"],
            task_slots=cfg["autoscaler_executor_slots"],
            work_dir_root=cfg["autoscaler_work_dir"],
            heartbeat_interval_s=cfg["autoscaler_heartbeat_seconds"],
        )
        server.attach_autoscaler(provider, autoscaler_settings)
        log.info(
            "autoscaler enabled: %s", server.autoscaler.snapshot(),
        )

    grpc_server = make_server()
    add_scheduler_servicer(grpc_server, SchedulerGrpcService(server))
    add_external_scaler_servicer(grpc_server, ExternalScalerService(server))
    bound = grpc_server.add_insecure_port(f"{cfg['bind_host']}:{cfg['bind_port']}")
    if bound == 0:
        raise SystemExit(f"cannot bind {cfg['bind_host']}:{cfg['bind_port']}")
    grpc_server.start()
    log.info("scheduler gRPC (+KEDA scaler) on %s:%d, policy=%s, backend=%s",
             cfg["bind_host"], bound, policy.value, cfg["config_backend"])

    rest_port = cfg["rest_port"] if cfg["rest_port"] >= 0 else bound + 1
    api = None
    if rest_port:
        api = ApiServerHandle(server, cfg["bind_host"], rest_port).start()
        log.info("REST API on %s:%d (/api/state)", cfg["bind_host"], api.port)

    fsql = None
    if cfg["flight_sql_port"]:
        from .flight_sql import FlightSqlHandle

        fsql = FlightSqlHandle(server, cfg["bind_host"], cfg["flight_sql_port"]).start()
        log.info("FlightSQL on %s:%d", cfg["bind_host"], fsql.port)

    stop = {"flag": False}

    def on_signal(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop["flag"]:
            time.sleep(0.5)
    finally:
        log.info("shutting down")
        if fsql:
            fsql.stop()
        if api:
            api.stop()
        grpc_server.stop(grace=2)
        server.stop()


if __name__ == "__main__":
    main()
