"""Generic single-consumer event loop.

Counterpart of the reference's ``core/src/event_loop.rs:28-141``: a bounded
queue drained by one worker thread, an ``EventAction`` with
on_start/on_stop/on_receive/on_error hooks, and a re-entrant ``EventSender``
handed to anyone who needs to post events (including the handler itself).
All scheduler state mutations flow through this loop — the concurrency
discipline the reference relies on instead of fine-grained locking.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Generic, Optional, TypeVar

log = logging.getLogger(__name__)

E = TypeVar("E")

_STOP = object()


class EventAction(Generic[E]):
    def on_start(self) -> None:  # pragma: no cover - default no-op
        pass

    def on_stop(self) -> None:  # pragma: no cover - default no-op
        pass

    def on_receive(self, event: E, sender: "EventSender[E]") -> None:
        raise NotImplementedError

    def on_error(self, error: BaseException) -> None:
        log.error("event loop handler error: %s", error, exc_info=error)


class EventSender(Generic[E]):
    def __init__(self, q: "queue.Queue"):
        self._q = q

    def post(self, event: E) -> None:
        self._q.put(event)


class EventLoop(Generic[E]):
    def __init__(self, name: str, buffer_size: int, action: EventAction[E]):
        self.name = name
        self.action = action
        self._q: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._processed = 0  # events fully handled (drain watches this)

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self.action.on_start()
        self._thread = threading.Thread(
            target=self._run, name=f"event-loop-{self.name}", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        sender = EventSender(self._q)
        while True:
            event = self._q.get()
            if event is _STOP:
                break
            if isinstance(event, _Barrier):
                event.done.set()
                continue
            try:
                self.action.on_receive(event, sender)
            except BaseException as e:  # noqa: BLE001 - loop must survive
                self.action.on_error(e)
            finally:
                self._processed += 1
        self.action.on_stop()

    def stop(self, timeout: float = 5.0) -> None:
        if not self._started:
            return
        self._q.put(_STOP)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._started = False

    def get_sender(self) -> EventSender[E]:
        if not self._started:
            raise RuntimeError(f"event loop {self.name!r} not started")
        return EventSender(self._q)

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until the loop is quiescent: two consecutive barriers pass
        with no events processed between them and an empty queue.  Barriers
        run on the loop thread, so a passing barrier proves no handler is
        mid-flight — a bare queue-empty check would race with follow-up
        events a handler is about to post."""
        import time

        deadline = time.monotonic() + timeout
        prev = -1
        while time.monotonic() < deadline:
            b = _Barrier()
            self._q.put(b)
            if not b.done.wait(timeout=max(0.0, deadline - time.monotonic())):
                return False
            cur = self._processed
            if cur == prev and self._q.empty():
                return True
            prev = cur
        return False


class _Barrier:
    def __init__(self) -> None:
        self.done = threading.Event()
