"""FlightSQL-style front-end on the scheduler.

Counterpart of the reference's ``scheduler/src/flight_sql.rs:57-255``:
``get_flight_info`` plans the SQL statement, enqueues a job, polls until the
job completes (``check_job``, `:99-139`), then returns a ``FlightInfo``
whose endpoints are FetchPartition tickets pointing *directly at the
executors* that hold the result partitions (`:141-190`) — the client
streams results over Flight without touching the scheduler again.  A
prepared-statement cache maps handle → SQL (`:66`, uuid-keyed there).

Protocol note: the reference speaks the full Arrow FlightSQL message
envelope (CommandStatementQuery wrapped in protobuf Any).  pyarrow exposes
generic Flight but not the FlightSQL message library, so this service
accepts the SQL statement directly as the flight descriptor command bytes
(UTF-8).  ADBC/JDBC drivers won't connect, but any pyarrow Flight client
can run SQL with two calls:

    info = client.get_flight_info(FlightDescriptor.for_command(b"select 1"))
    for ep in info.endpoints:
        table = flight.connect(ep.locations[0]).do_get(ep.ticket).read_all()
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Dict, Optional

import pyarrow as pa
import pyarrow.flight as flight

from ..proto import pb
from ..serde.scheduler_types import PartitionLocation

log = logging.getLogger(__name__)

JOB_POLL_INTERVAL_S = 0.1
JOB_TIMEOUT_S = 300.0


class FlightSqlService(flight.FlightServerBase):
    def __init__(self, scheduler, host: str = "0.0.0.0", port: int = 0):
        location = f"grpc://{host}:{port}"
        super().__init__(location)
        self.scheduler = scheduler
        # ONE server-side session for all FlightSQL statements (the
        # reference's service owns a single SessionContext), so CREATE
        # EXTERNAL TABLE persists for subsequent queries
        self.session_ctx = scheduler.state.session_manager.create_session({})
        # handle → SQL text (reference: statements cache flight_sql.rs:66)
        self._prepared: Dict[str, str] = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------------- statements
    def _submit_sql(self, sql: str) -> str:
        """Plan + enqueue; returns job id (reference: flight_sql.rs:239-255).

        DDL (CREATE EXTERNAL TABLE / SET / SHOW) executes eagerly in the
        session; its result relation is then submitted like any query so
        the client still gets a normal FlightInfo back."""
        plan = self.session_ctx.sql(sql).logical_plan()
        job_id = self.scheduler.state.task_manager.generate_job_id()
        self.scheduler.submit_job(job_id, self.session_ctx.session_id, plan)
        return job_id

    def _check_job(self, job_id: str) -> list[PartitionLocation]:
        """Poll until terminal (reference: check_job flight_sql.rs:99-139)."""
        deadline = time.time() + JOB_TIMEOUT_S
        tm = self.scheduler.state.task_manager
        while True:
            status = tm.get_job_status(job_id)
            if status is not None:
                if status["state"] == "completed":
                    return list(status.get("locations", []))
                if status["state"] == "failed":
                    raise flight.FlightServerError(
                        f"job {job_id} failed: {status.get('error', 'unknown')}"
                    )
            if time.time() > deadline:
                raise flight.FlightServerError(f"job {job_id} timed out")
            time.sleep(JOB_POLL_INTERVAL_S)

    # ------------------------------------------------------------- flight
    def get_flight_info(self, context, descriptor: flight.FlightDescriptor):
        if descriptor.command:
            sql = descriptor.command.decode("utf-8", "replace")
            with self._lock:
                # a prepared-statement handle round-trips as the command too
                sql = self._prepared.get(sql, sql)
        else:
            raise flight.FlightServerError("descriptor must carry a SQL command")
        job_id = self._submit_sql(sql)
        locations = self._check_job(job_id)

        endpoints = []
        schema: Optional[pa.Schema] = None
        total_rows = 0
        total_bytes = 0
        for loc in locations:
            ticket = flight.Ticket(
                pb.FetchPartitionTicket(
                    job_id=loc.partition_id.job_id,
                    stage_id=loc.partition_id.stage_id,
                    partition_id=loc.partition_id.partition_id,
                    path=loc.path,
                ).SerializeToString()
            )
            ep_loc = flight.Location.for_grpc_tcp(
                loc.executor_meta.host, loc.executor_meta.flight_port
            )
            endpoints.append(flight.FlightEndpoint(ticket, [ep_loc]))
            total_rows += loc.partition_stats.num_rows
            total_bytes += loc.partition_stats.num_bytes
            if schema is None and loc.path:
                try:
                    with pa.OSFile(loc.path, "rb") as f:
                        schema = pa.ipc.open_file(f).schema
                except Exception:
                    pass
        if schema is None:
            schema = pa.schema([])
        return flight.FlightInfo(
            schema, descriptor, endpoints, total_rows, total_bytes
        )

    def do_action(self, context, action: flight.Action):
        """Prepared-statement lifecycle (reference: flight_sql.rs prepared
        handling): CreatePreparedStatement / ClosePreparedStatement."""
        if action.type == "CreatePreparedStatement":
            sql = action.body.to_pybytes().decode("utf-8", "replace")
            handle = uuid.uuid4().hex
            with self._lock:
                self._prepared[handle] = sql
            yield flight.Result(handle.encode())
        elif action.type == "ClosePreparedStatement":
            handle = action.body.to_pybytes().decode("utf-8", "replace")
            with self._lock:
                self._prepared.pop(handle, None)
            yield flight.Result(b"ok")
        else:
            raise flight.FlightServerError(f"unknown action {action.type!r}")

    def list_actions(self, context):
        return [
            ("CreatePreparedStatement", "register a SQL text, returns a handle"),
            ("ClosePreparedStatement", "drop a prepared handle"),
        ]


class FlightSqlHandle:
    """Background FlightSQL server with clean shutdown."""

    def __init__(self, scheduler, host: str = "0.0.0.0", port: int = 0):
        self._service = FlightSqlService(scheduler, host, port)
        self.port = self._service.port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FlightSqlHandle":
        self._thread = threading.Thread(
            target=self._service.serve, name="scheduler-flightsql", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._service.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
