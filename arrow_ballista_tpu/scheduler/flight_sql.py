"""FlightSQL-style front-end on the scheduler.

Counterpart of the reference's ``scheduler/src/flight_sql.rs:57-255``:
``get_flight_info`` plans the SQL statement, enqueues a job, polls until the
job completes (``check_job``, `:99-139`), then returns a ``FlightInfo``
whose endpoints are FetchPartition tickets pointing *directly at the
executors* that hold the result partitions (`:141-190`) — the client
streams results over Flight without touching the scheduler again.  A
prepared-statement cache maps handle → SQL (`:66`, uuid-keyed there).

Protocol note: the reference speaks the full Arrow FlightSQL message
envelope (CommandStatementQuery wrapped in protobuf Any).  pyarrow exposes
generic Flight but not the FlightSQL message library, so this service
accepts the SQL statement directly as the flight descriptor command bytes
(UTF-8).  ADBC/JDBC drivers won't connect, but any pyarrow Flight client
can run SQL with two calls:

    info = client.get_flight_info(FlightDescriptor.for_command(b"select 1"))
    for ep in info.endpoints:
        table = flight.connect(ep.locations[0]).do_get(ep.ticket).read_all()
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Dict, Optional

import pyarrow as pa
import pyarrow.flight as flight

from ..proto import pb
from ..serde.scheduler_types import PartitionLocation

log = logging.getLogger(__name__)

# fallback when the session config is unavailable; the live value comes
# from ballista.client.job_timeout_seconds (SET-able per session)
JOB_TIMEOUT_S = 300.0


class FlightSqlService(flight.FlightServerBase):
    def __init__(self, scheduler, host: str = "0.0.0.0", port: int = 0):
        location = f"grpc://{host}:{port}"
        super().__init__(location)
        self.scheduler = scheduler
        # ONE server-side session for all FlightSQL statements (the
        # reference's service owns a single SessionContext), so CREATE
        # EXTERNAL TABLE persists for subsequent queries
        self.session_ctx = scheduler.state.session_manager.create_session({})
        # handle → SQL text (reference: statements cache flight_sql.rs:66)
        self._prepared: Dict[str, str] = {}
        # handle → positional parameter values bound via DoPut (reference:
        # do_put CommandPreparedStatementQuery, flight_sql.rs:199-227)
        self._params: Dict[str, list] = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------------- statements
    def _submit_sql(self, sql: str) -> str:
        """Plan + enqueue; returns job id (reference: flight_sql.rs:239-255).

        DDL (CREATE EXTERNAL TABLE / SET / SHOW) executes eagerly in the
        shared session under the lock so its effects persist; QUERIES plan
        on a per-statement ``fork()`` of the session, so concurrent
        statements can't race each other's CTE registrations in the shared
        catalog (round-1 advisor finding: shared-session CTE race)."""
        from ..sql import ast
        from ..sql.parser import parse_sql

        stmt = parse_sql(sql)
        if isinstance(stmt, ast.Query):
            # fork() copies the catalog dict, so it must not race the DDL
            # branch's mutations — take the same lock for the (cheap) copy
            with self._lock:
                fork = self.session_ctx.fork()
            plan = fork.sql(sql, stmt=stmt).logical_plan()
        else:
            with self._lock:
                plan = self.session_ctx.sql(sql, stmt=stmt).logical_plan()
        job_id = self.scheduler.state.task_manager.generate_job_id()
        self.scheduler.submit_job(job_id, self.session_ctx.session_id, plan)
        return job_id

    def _job_timeout_s(self) -> float:
        """The ballista.client.job_timeout_seconds knob, read per call so
        ``SET`` in the shared session takes effect immediately."""
        try:
            return self.session_ctx.config.client_job_timeout_seconds
        except Exception:  # noqa: BLE001 - a broken setting must not hang DoGet
            return JOB_TIMEOUT_S

    def _check_job(self, job_id: str) -> list[PartitionLocation]:
        """Poll until terminal (reference: check_job flight_sql.rs:99-139).
        Rides the same jittered exponential backoff schedule as the
        client poll loop (``task_status.PollBackoff``) so a fleet of
        FlightSQL statements doesn't poll in lockstep either."""
        # monotonic deadline: a wall-clock jump must neither cut a
        # running statement short nor extend it
        start = time.monotonic()
        deadline = start + self._job_timeout_s()
        running_since = None
        last_queued: dict = {}
        tm = self.scheduler.state.task_manager
        backoff = self._poll_backoff()
        while True:
            status = tm.get_job_status(job_id)
            if status is not None:
                if status["state"] == "queued":
                    last_queued = status
                elif running_since is None:
                    running_since = time.monotonic()
                    backoff.reset()  # left the queue: poll tightly again
                if status["state"] == "completed":
                    return list(status.get("locations", []))
                if status["state"] == "failed":
                    raise flight.FlightServerError(
                        f"job {job_id} failed: {status.get('error', 'unknown')}"
                    )
            if time.monotonic() > deadline:
                from .task_status import poll_timeout_breakdown

                # an admission-starved statement reads differently from
                # a wedged one
                raise flight.FlightServerError(
                    f"job {job_id} timed out"
                    + poll_timeout_breakdown(start, running_since, last_queued)
                )
            backoff.sleep(deadline)

    def _poll_backoff(self):
        """Backoff schedule from the shared session's knobs, read per
        statement so ``SET`` takes effect immediately; broken settings
        degrade to the defaults rather than hanging DoGet."""
        from .task_status import PollBackoff

        try:
            return PollBackoff(
                self.session_ctx.config.client_poll_interval_seconds,
                self.session_ctx.config.client_poll_max_interval_seconds,
            )
        except Exception:  # noqa: BLE001
            return PollBackoff()

    # ------------------------------------------------------------- flight
    def get_flight_info(self, context, descriptor: flight.FlightDescriptor):
        if descriptor.command:
            sql = descriptor.command.decode("utf-8", "replace")
            with self._lock:
                # a prepared-statement handle round-trips as the command too
                handle = sql
                sql = self._prepared.get(sql, sql)
                params = self._params.get(handle)
            if params is not None:
                sql = _bind_positional(sql, params)
        else:
            raise flight.FlightServerError("descriptor must carry a SQL command")
        job_id = self._submit_sql(sql)
        locations = self._check_job(job_id)

        endpoints = []
        schema: Optional[pa.Schema] = None
        total_rows = 0
        total_bytes = 0
        for loc in locations:
            ticket = flight.Ticket(
                pb.FetchPartitionTicket(
                    job_id=loc.partition_id.job_id,
                    stage_id=loc.partition_id.stage_id,
                    partition_id=loc.partition_id.partition_id,
                    path=loc.path,
                ).SerializeToString()
            )
            ep_loc = flight.Location.for_grpc_tcp(
                loc.executor_meta.host, loc.executor_meta.flight_port
            )
            endpoints.append(flight.FlightEndpoint(ticket, [ep_loc]))
            total_rows += loc.partition_stats.num_rows
            total_bytes += loc.partition_stats.num_bytes
            if schema is None and loc.path:
                try:
                    with pa.OSFile(loc.path, "rb") as f:
                        schema = pa.ipc.open_file(f).schema
                except Exception:
                    pass
        if schema is None:
            schema = pa.schema([])
        return flight.FlightInfo(
            schema, descriptor, endpoints, total_rows, total_bytes
        )

    def do_put(self, context, descriptor, reader, writer):
        """Bind positional parameters to a prepared statement (reference:
        do_put CommandPreparedStatementQuery, flight_sql.rs:199-227): the
        descriptor command is the prepared handle, the stream is a ONE-row
        batch whose columns are the ``?`` values in order."""
        handle = (descriptor.command or b"").decode("utf-8", "replace")
        table = reader.read_all()
        if table.num_rows != 1:
            raise flight.FlightServerError(
                f"parameter batch must have exactly 1 row, got {table.num_rows}"
            )
        values = [table.column(i)[0].as_py() for i in range(table.num_columns)]
        with self._lock:
            # validate + store under ONE acquisition: a concurrent Close
            # between a check and a write would leak a permanent entry
            if handle not in self._prepared:
                raise flight.FlightServerError(
                    f"unknown prepared handle {handle!r}"
                )
            self._params[handle] = values

    def do_action(self, context, action: flight.Action):
        """Prepared-statement lifecycle (reference: flight_sql.rs prepared
        handling): CreatePreparedStatement / ClosePreparedStatement."""
        if action.type == "CreatePreparedStatement":
            sql = action.body.to_pybytes().decode("utf-8", "replace")
            handle = uuid.uuid4().hex
            with self._lock:
                self._prepared[handle] = sql
            yield flight.Result(handle.encode())
        elif action.type == "ClosePreparedStatement":
            handle = action.body.to_pybytes().decode("utf-8", "replace")
            with self._lock:
                self._prepared.pop(handle, None)
                self._params.pop(handle, None)
            yield flight.Result(b"ok")
        else:
            raise flight.FlightServerError(f"unknown action {action.type!r}")

    def list_actions(self, context):
        return [
            ("CreatePreparedStatement", "register a SQL text, returns a handle"),
            ("ClosePreparedStatement", "drop a prepared handle"),
        ]


def _bind_positional(sql: str, values: list) -> str:
    """Substitute ``?`` placeholders with SQL literals, positionally.

    Skips string literals ('' escapes), double-quoted identifiers, ``--``
    line comments and ``/* */`` block comments (all legal in this
    dialect's lexer) — a ``?`` inside any of those is content, not a
    placeholder."""
    out = []
    it = iter(values)
    state = None  # None | "str" | "ident" | "comment" | "block"
    i = 0
    while i < len(sql):
        ch = sql[i]
        if state == "str":
            out.append(ch)
            if ch == "'":
                if i + 1 < len(sql) and sql[i + 1] == "'":
                    out.append("'")
                    i += 1
                else:
                    state = None
        elif state == "ident":
            out.append(ch)
            if ch == '"':
                state = None
        elif state == "comment":
            out.append(ch)
            if ch == "\n":
                state = None
        elif state == "block":
            out.append(ch)
            if ch == "*" and i + 1 < len(sql) and sql[i + 1] == "/":
                out.append("/")
                i += 1
                state = None
        elif ch == "'":
            state = "str"
            out.append(ch)
        elif ch == '"':
            state = "ident"
            out.append(ch)
        elif ch == "-" and i + 1 < len(sql) and sql[i + 1] == "-":
            state = "comment"
            out.append(ch)
        elif ch == "/" and i + 1 < len(sql) and sql[i + 1] == "*":
            state = "block"
            out.append(ch)
        elif ch == "?":
            try:
                v = next(it)
            except StopIteration:
                raise flight.FlightServerError(
                    "more ? placeholders than bound parameters"
                )
            out.append(_sql_literal(v))
        else:
            out.append(ch)
        i += 1
    return "".join(out)


def _sql_literal(v) -> str:
    import datetime
    import decimal

    if v is None:
        return "NULL"
    if isinstance(v, decimal.Decimal):
        return str(v)  # numeric literal, not a quoted string
    if isinstance(v, (bytes, bytearray)):
        raise flight.FlightServerError(
            "binary parameters are not supported in SQL text binding"
        )
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, float) and (v != v or v in (float("inf"), float("-inf"))):
        return "NULL"  # nan/inf have no SQL literal form
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, datetime.datetime):  # before date: datetime IS a date
        return f"timestamp '{v.isoformat(sep=' ')}'"
    if isinstance(v, datetime.date):
        return f"date '{v.isoformat()}'"
    return "'" + str(v).replace("'", "''") + "'"


class FlightSqlHandle:
    """Background FlightSQL server with clean shutdown."""

    def __init__(self, scheduler, host: str = "0.0.0.0", port: int = 0):
        self._service = FlightSqlService(scheduler, host, port)
        self.port = self._service.port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FlightSqlHandle":
        self._thread = threading.Thread(
            target=self._service.serve, name="scheduler-flightsql", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._service.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
