"""Task-failure classification: transient (retry) vs fatal (fail fast).

The executor reports failures as ``"ExceptionName: message"`` strings
(``executor.py`` formats ``f"{type(e).__name__}: {e}"``), so
classification is a prefix/marker match on that string — the scheduler
never needs the exception object, which may not even exist in this
process (worker crashes, dropped connections).

Policy (mirrors what production Ballista deployments converge on):

* **fatal** — deterministic errors that re-running cannot fix: plan /
  serde / SQL / config errors, invariant violations, explicit
  cancellation.  These fail the job on attempt 1.
* **transient** — everything else: IO, Flight/gRPC transport, worker
  crashes, injected faults, and *unknown* errors.  Unknown defaults to
  transient because retries are bounded (``ballista.task.max_attempts``):
  a deterministic bug misclassified as transient costs a few wasted
  attempts, while a transient failure misclassified as fatal burns the
  whole job.
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

TRANSIENT = "transient"
FATAL = "fatal"

# Exception-name prefixes that mark a deterministic, non-retryable error.
_FATAL_PREFIXES = (
    "PlanError",
    "SqlError",
    "SerdeError",
    "ConfigError",
    "SchemaError",
    "NotImplementedYet",
    "NotImplementedError",
    "InternalError",
    "Cancelled",
    # plain-Python code bugs re-fail identically on every attempt
    "TypeError",
    "ImportError",
    "ModuleNotFoundError",
    "AttributeError",
    "NameError",
)

# Substrings anywhere in the error that force the transient class even if
# a fatal-looking exception wrapped them (e.g. an OSError str()'d into a
# SerdeError while reading a plan file off a dying disk is still IO).
_TRANSIENT_MARKERS = (
    "fault injected",
    "worker terminated",
    "connection reset",
    "connection refused",
    "unavailable",
    "deadline exceeded",
    "broken pipe",
    "timed out",
)


def classify_failure(error: str) -> str:
    """Map one task-failure string to ``"transient"`` or ``"fatal"``."""
    err = (error or "").strip()
    low = err.lower()
    for marker in _TRANSIENT_MARKERS:
        if marker in low:
            return TRANSIENT
    head = err.split(":", 1)[0].strip()
    if head in _FATAL_PREFIXES:
        return FATAL
    return TRANSIENT


def is_transient(error: str) -> bool:
    return classify_failure(error) == TRANSIENT


# ``errors.ShuffleFetchFailed.__str__`` embeds these fields; the executor
# wire-formats failures as "ExceptionName: message", so the scheduler
# recovers the structure with a match on that string (the exception object
# never crosses the process boundary).
_SHUFFLE_FETCH_RE = re.compile(
    r"stage=(\d+)\s+partition=(\d+)\s+executor=([^\s:]+)"
)


def parse_shuffle_fetch_failure(
    error: str,
) -> Optional[Tuple[int, int, str]]:
    """Decode a consumer task's structured lost-shuffle failure into
    ``(producer_stage_id, map_partition, executor_id)``; None for every
    other error.  Drives producer-partition recovery in
    ``ExecutionGraph._recover_lost_shuffle`` instead of burning the
    consumer's attempts on data that no longer exists."""
    err = (error or "").strip()
    if not err.startswith("ShuffleFetchFailed"):
        return None
    m = _SHUFFLE_FETCH_RE.search(err)
    if m is None:
        return None
    return int(m.group(1)), int(m.group(2)), m.group(3)


def indicts_reporter(error: str) -> bool:
    """Should this failure count against the REPORTING executor's
    quarantine window?  Transient infrastructure failures do; a lost
    map-output fetch does not — the consumer's host is healthy, the
    producer's data vanished."""
    return is_transient(error) and parse_shuffle_fetch_failure(error) is None
