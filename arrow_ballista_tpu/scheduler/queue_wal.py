"""Durable admission-queue WAL (ISSUE 20).

PR 12's admission queue holds submissions *pre-planning*: no
ExecutionGraph exists, nothing is persisted, and a scheduler crash
silently drops every queued job (and every buffered cancel intent).
This module closes that gap by journaling the queue through the state
backend — the same backend whose durability already carries active
jobs across restarts (sqlite single-file, or the replicated kvstore
for HA), so queued work inherits exactly the durability the operator
chose for running work.

Layout (one :class:`~.backend.Keyspace.QueueWal` keyspace, three
prefixes so a single prefix scan recovers each record class):

* ``q:{seq:016d}`` — one queued job, JSON: the serialized logical plan
  (base64 protobuf via :class:`~..serde.BallistaCodec`), pool/lane
  placement, pool parameters, enqueue wall-clock and expiry budget,
  plus the ``curator`` scheduler id that owns the entry.  The
  zero-padded sequence IS the submit order: replay sorts by key and
  re-enqueues in order, so fair-share positions survive (DRR deficits
  restart at zero — they are burst credit, not position).
* ``c:{job_id}`` — a buffered cancel intent (cancel raced the admit
  window); replay re-arms it so a cancel raced with a crash still
  wins.
* ``t:{token}`` — a client-minted submit idempotency token mapped to
  its job id, so a retried ExecuteQuery after failover re-attaches
  instead of double-running.  Token entries are written whenever a
  client sends one (independent of the WAL knob — they guard the
  retry path, not queue durability) and age out opportunistically.

Every write here is **best-effort**: a WAL failure must degrade
durability, never availability — the submit path proceeds and the job
simply behaves as pre-WAL (lost on crash).  With the WAL knob off
(the default) ``AdmissionController.wal`` stays ``None`` and every
hook is a no-op: the submit path is byte-identical to a scheduler
without this module.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .backend import Keyspace, StateBackend

logger = logging.getLogger(__name__)

QUEUE_PREFIX = "q:"
INTENT_PREFIX = "c:"
TOKEN_PREFIX = "t:"
# idempotency tokens only need to outlive the client's retry horizon;
# purge anything this old when the submit path happens to sweep
TOKEN_TTL_S = 3600.0


class AdmissionWal:
    """Write-ahead journal for the admission queue.

    ``curator_fn`` resolves the owning scheduler id lazily — the id is
    finalized after construction in ``__main__`` wiring, and takeover
    rewrites entries to the adopting scheduler.
    """

    def __init__(self, backend: StateBackend, curator_fn: Callable[[], str]):
        self.backend = backend
        self._curator_fn = curator_fn
        self._lock = threading.Lock()
        # job_id -> WAL key, so discard() needs no scan
        self._keys: Dict[str, str] = {}
        self._seq = self._init_seq()

    @property
    def curator(self) -> str:
        try:
            return str(self._curator_fn())
        except Exception:  # noqa: BLE001 - curator probe must not fail writes
            return ""

    def _init_seq(self) -> int:
        """Continue the sequence past every existing entry (any curator):
        submit order is global, and a takeover must not interleave new
        entries below adopted ones."""
        try:
            entries = self.backend.get_from_prefix(Keyspace.QueueWal, QUEUE_PREFIX)
            top = 0
            for key, _ in entries:
                try:
                    top = max(top, int(key[len(QUEUE_PREFIX):]))
                except ValueError:
                    continue
            return top
        except Exception:  # noqa: BLE001
            return 0

    # ------------------------------------------------------------- queue
    def append(self, qj, pool_weight: float, pool_max_running: int) -> None:
        """Journal one queued job (called under the admission lock,
        right after the in-memory enqueue)."""
        from ..serde import BallistaCodec

        with self._lock:
            self._seq += 1
            key = f"{QUEUE_PREFIX}{self._seq:016d}"
            self._keys[qj.job_id] = key
        try:
            rec = {
                "job_id": qj.job_id,
                "session_id": qj.session_id,
                "pool": qj.pool,
                "priority": qj.priority,
                "pool_weight": pool_weight,
                "pool_max_running": pool_max_running,
                "enqueued_unix": qj.enqueued_unix,
                "max_wait_s": qj.max_wait_s,
                "curator": self.curator,
                "plan": base64.b64encode(
                    BallistaCodec.encode_logical(qj.plan)
                ).decode("ascii"),
            }
            self.backend.put(
                Keyspace.QueueWal, key, json.dumps(rec).encode("utf-8")
            )
        except Exception:  # noqa: BLE001 - degrade durability, not availability
            logger.warning("admission WAL append failed for %s", qj.job_id,
                           exc_info=True)
            with self._lock:
                self._keys.pop(qj.job_id, None)

    def register(self, job_id: str, key: str) -> None:
        """Track an adopted/replayed entry so a later discard finds it."""
        with self._lock:
            self._keys[job_id] = key

    def discard(self, job_id: str) -> None:
        """The job left the queue *and* reached a durable downstream
        state (graph persisted, or terminal): drop its WAL entry."""
        with self._lock:
            key = self._keys.pop(job_id, None)
        if key is None:
            return
        try:
            self.backend.delete(Keyspace.QueueWal, key)
        except Exception:  # noqa: BLE001
            logger.warning("admission WAL discard failed for %s", job_id,
                           exc_info=True)

    def load(self, curator: str) -> List[Tuple[str, dict]]:
        """Every queued-job record owned by ``curator``, in submit
        order.  Undecodable entries are dropped (and deleted) rather
        than poisoning replay."""
        out: List[Tuple[str, dict]] = []
        try:
            entries = self.backend.get_from_prefix(Keyspace.QueueWal, QUEUE_PREFIX)
        except Exception:  # noqa: BLE001
            logger.warning("admission WAL scan failed", exc_info=True)
            return out
        for key, raw in sorted(entries):
            try:
                rec = json.loads(raw.decode("utf-8"))
            except Exception:  # noqa: BLE001
                logger.warning("dropping undecodable WAL entry %s", key)
                try:
                    self.backend.delete(Keyspace.QueueWal, key)
                except Exception:  # noqa: BLE001
                    pass
                continue
            if rec.get("curator") == curator:
                out.append((key, rec))
        return out

    def rewrite_curator(self, key: str, rec: dict, new_curator: str) -> dict:
        """Takeover: re-stamp an adopted entry to the new owner so a
        second failover replays it again."""
        rec = dict(rec, curator=new_curator)
        try:
            self.backend.put(
                Keyspace.QueueWal, key, json.dumps(rec).encode("utf-8")
            )
        except Exception:  # noqa: BLE001
            logger.warning("admission WAL curator rewrite failed for %s", key,
                           exc_info=True)
        return rec

    @staticmethod
    def decode_plan(rec: dict):
        from ..serde import BallistaCodec

        return BallistaCodec.decode_logical(base64.b64decode(rec["plan"]))

    # ----------------------------------------------------------- intents
    def put_intent(self, job_id: str) -> None:
        try:
            rec = {"curator": self.curator, "ts": time.time()}
            self.backend.put(
                Keyspace.QueueWal,
                f"{INTENT_PREFIX}{job_id}",
                json.dumps(rec).encode("utf-8"),
            )
        except Exception:  # noqa: BLE001
            logger.warning("cancel-intent WAL put failed for %s", job_id,
                           exc_info=True)

    def discard_intent(self, job_id: str) -> None:
        try:
            self.backend.delete(Keyspace.QueueWal, f"{INTENT_PREFIX}{job_id}")
        except Exception:  # noqa: BLE001
            logger.warning("cancel-intent WAL discard failed for %s", job_id,
                           exc_info=True)

    def load_intents(self, curator: str) -> List[str]:
        out: List[str] = []
        try:
            entries = self.backend.get_from_prefix(
                Keyspace.QueueWal, INTENT_PREFIX
            )
        except Exception:  # noqa: BLE001
            return out
        for key, raw in entries:
            try:
                rec = json.loads(raw.decode("utf-8"))
            except Exception:  # noqa: BLE001
                continue
            if rec.get("curator") == curator:
                out.append(key[len(INTENT_PREFIX):])
        return out


# --------------------------------------------------------------- tokens
# Idempotency-token helpers live at module level: grpc_service uses them
# whether or not the queue WAL is enabled (they guard the client retry
# path, which must work against a WAL-less scheduler too).

def token_key(token: str) -> str:
    return f"{TOKEN_PREFIX}{token}"


def lookup_token(backend: StateBackend, token: str) -> Optional[str]:
    """job_id previously minted for this token, if any."""
    try:
        raw = backend.get(Keyspace.QueueWal, token_key(token))
    except Exception:  # noqa: BLE001
        return None
    if raw is None:
        return None
    try:
        return raw.decode("utf-8").split(" ", 1)[0] or None
    except Exception:  # noqa: BLE001
        return None


def record_token(backend: StateBackend, token: str, job_id: str) -> None:
    try:
        backend.put(
            Keyspace.QueueWal,
            token_key(token),
            f"{job_id} {int(time.time())}".encode("utf-8"),
        )
    except Exception:  # noqa: BLE001
        logger.warning("idempotency token write failed", exc_info=True)


def purge_stale_tokens(backend: StateBackend, ttl_s: float = TOKEN_TTL_S) -> int:
    """Drop tokens older than ``ttl_s``; returns how many were removed.
    Called opportunistically from the submit path."""
    removed = 0
    cutoff = time.time() - ttl_s
    try:
        entries = backend.get_from_prefix(Keyspace.QueueWal, TOKEN_PREFIX)
    except Exception:  # noqa: BLE001
        return 0
    for key, raw in entries:
        try:
            ts = float(raw.decode("utf-8").split(" ", 1)[1])
        except Exception:  # noqa: BLE001
            ts = 0.0
        if ts < cutoff:
            try:
                backend.delete(Keyspace.QueueWal, key)
                removed += 1
            except Exception:  # noqa: BLE001
                pass
    return removed
