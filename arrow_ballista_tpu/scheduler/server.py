"""SchedulerServer: top-level scheduler object.

Counterpart of the reference's ``scheduler/src/scheduler_server/mod.rs``:
owns the :class:`SchedulerState`, the scheduling policy, and the
query-stage event loop (buffer 10,000, `:55-61`); ``init()`` starts the
loop and the dead-executor reaper (`:131-137`, `:192-253`); ``submit_job``
posts ``JobQueued`` (`:139-153`); ``update_task_status`` posts
``TaskUpdating`` after rejecting dead executors (`:157-178`).

The pull-mode fill path (``poll_work``) mutates state directly from the
RPC thread exactly like the reference's handler
(``scheduler_server/grpc.rs:56-175``): graphs are behind per-job locks, so
this is safe, and job-level consequences are still posted as events.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..config import TaskSchedulingPolicy
from ..plan import logical as lp
from ..proto import pb
from ..serde.scheduler_types import ExecutorMetadata
from .backend import Keyspace, StateBackend
from .event_loop import EventLoop
from .execution_stage import TaskInfo
from .executor_manager import (
    DEFAULT_EXECUTOR_TIMEOUT_S,
    ExecutorReservation,
)
from .query_stage_scheduler import (
    ExecutorLost,
    JobQueued,
    QueryStageScheduler,
    ReservationOffering,
    TaskUpdating,
    post_job_events,
)
from .session_manager import SessionBuilder, default_session_builder
from .state import SchedulerState
from .task_manager import TaskLauncher, TaskManager

log = logging.getLogger(__name__)

EVENT_LOOP_BUFFER = 10_000


class SchedulerServer:
    def __init__(
        self,
        scheduler_id: str,
        backend: StateBackend,
        policy: TaskSchedulingPolicy = TaskSchedulingPolicy.PULL_STAGED,
        session_builder: SessionBuilder = default_session_builder,
        launcher: Optional[TaskLauncher] = None,
        work_dir: str = "/tmp/ballista-tpu",
        liveness_window_s: float = 60.0,
        executor_timeout_s: float = DEFAULT_EXECUTOR_TIMEOUT_S,
        reaper_interval_s: Optional[float] = None,
        quarantine_threshold: Optional[int] = None,
        quarantine_window_s: Optional[float] = None,
        quarantine_backoff_s: Optional[float] = None,
        speculation_interval_s: float = 1.0,
        speculation_force_enabled: bool = False,
        task_timeout_force_s: float = 0.0,
        aqe_force_enabled: bool = False,
        admission_force_enabled: bool = False,
        admission_defaults: Optional[Dict[str, str]] = None,
        admission_wal_enabled: bool = False,
        cache_force_enabled: bool = False,
        cache_policy_force_enabled: bool = False,
        cache_settings: Optional[Dict[str, str]] = None,
        drain_timeout_s: float = 30.0,
        telemetry_sample_s: float = 5.0,
        event_journal_dir: str = "",
        event_journal_rotate_bytes: Optional[int] = None,
        event_journal_segments: Optional[int] = None,
        autoscaler_settings: Optional[Dict[str, str]] = None,
        executor_provider=None,
    ):
        self.scheduler_id = scheduler_id
        self.policy = policy
        self.state = SchedulerState(
            backend,
            scheduler_id,
            policy,
            session_builder,
            launcher,
            work_dir,
            liveness_window_s,
            quarantine_threshold=quarantine_threshold,
            quarantine_window_s=quarantine_window_s,
            quarantine_backoff_s=quarantine_backoff_s,
            speculation_force_enabled=speculation_force_enabled,
            task_timeout_force_s=task_timeout_force_s,
            aqe_force_enabled=aqe_force_enabled,
            admission_force_enabled=admission_force_enabled,
            admission_defaults=admission_defaults,
            admission_wal_enabled=admission_wal_enabled,
            cache_force_enabled=cache_force_enabled,
            cache_policy_force_enabled=cache_policy_force_enabled,
            cache_settings=cache_settings,
            event_journal_dir=event_journal_dir,
            event_journal_rotate_bytes=event_journal_rotate_bytes,
            event_journal_segments=event_journal_segments,
        )
        self.event_loop = EventLoop(
            "query_stage", EVENT_LOOP_BUFFER, QueryStageScheduler(self.state)
        )
        self.executor_timeout_s = executor_timeout_s
        self.reaper_interval_s = (
            reaper_interval_s if reaper_interval_s is not None else executor_timeout_s
        )
        # straggler/deadline scan period (tests shrink the attr live; the
        # timer re-reads it each tick)
        self.speculation_interval_s = speculation_interval_s
        # graceful-decommission drain budget handed to executors
        # (ballista.executor.drain_timeout_seconds is the session-side
        # spelling; the scheduler flag wins for operator-driven drains)
        self.drain_timeout_s = drain_timeout_s
        # cluster-aggregate sampling period (queue depth, running tasks,
        # slots free → obs/timeseries.py rings); tests shrink the attr
        self.telemetry_sample_s = telemetry_sample_s
        self._reaper: Optional[threading.Thread] = None
        self._spec_timer: Optional[threading.Thread] = None
        self._telemetry_timer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # elastic lifecycle (ISSUE 17): None unless explicitly enabled AND
        # a provider is supplied — the knob-off scheduler carries no
        # autoscaler object at all, so the default path is unchanged
        self.autoscaler = None
        from .autoscaler import AutoscalerPolicy

        if (
            executor_provider is not None
            and AutoscalerPolicy.enabled_in(autoscaler_settings)
        ):
            self.attach_autoscaler(executor_provider, autoscaler_settings)

    # ------------------------------------------------------------ lifecycle
    def init(self) -> "SchedulerServer":
        self.event_loop.start()
        # restart-resume: re-arm every persisted active job before serving
        # (Running stages were stored Resolved, so their tasks re-dispatch
        # through the normal offer/poll path)
        recovered = self.state.task_manager.recover_active_jobs()
        if recovered:
            log.info("recovered %d active job(s): %s", len(recovered), recovered)
        # queued (pre-planning) jobs + buffered cancel intents come back
        # from the admission WAL in submit order (no-op when the WAL
        # knob is off)
        # slot counts are durable: reservations held by the process that
        # died leaked with it (its re-armed tasks are pending again), so
        # rebuild every executor's count from the persisted graphs —
        # without this a small fleet restarts into a dispatch deadlock.
        # Runs before the WAL replay so a replayed admission cannot race
        # its fresh reservations against the rebuild.
        reclaimed = self.state.executor_manager.reconcile_slots(
            self.state.task_manager.running_tasks_by_executor()
        )
        if reclaimed:
            log.info("reconciled leaked executor slots: %s", reclaimed)
        requeued = self.replay_admission_wal()
        if requeued:
            log.info(
                "replayed %d queued job(s) from the admission WAL: %s",
                len(requeued), requeued,
            )
        if recovered and self.policy == TaskSchedulingPolicy.PUSH_STAGED:
            # revive is not an offer: nothing else re-offers a recovered
            # job's re-armed tasks until some unrelated event happens by
            from .query_stage_scheduler import JobSubmitted

            for job_id in recovered:
                self.event_loop.get_sender().post(JobSubmitted(job_id))
        self._reaper = threading.Thread(
            target=self._reaper_loop, name="executor-reaper", daemon=True
        )
        self._reaper.start()
        self._spec_timer = threading.Thread(
            target=self._speculation_loop, name="speculation-timer", daemon=True
        )
        self._spec_timer.start()
        self._telemetry_timer = threading.Thread(
            target=self._telemetry_loop, name="cluster-telemetry", daemon=True
        )
        self._telemetry_timer.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.autoscaler is not None:
            self.autoscaler.close()
        self.event_loop.stop()
        self.state.executor_manager.close()
        self.state.events.close()

    def attach_autoscaler(
        self, provider, settings: Optional[Dict[str, str]] = None
    ):
        """Wire the elastic lifecycle loop onto this scheduler.  Callable
        before OR after ``init()`` (the timer re-checks each tick), which
        lets standalone mode attach once its port is actually bound."""
        from .autoscaler import Autoscaler, AutoscalerPolicy

        self.autoscaler = Autoscaler(
            self, provider, AutoscalerPolicy.from_settings(settings or {})
        )
        return self.autoscaler

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until the event loop has processed everything queued (test
        aid, mirrors the reference's await_condition polling)."""
        return self.event_loop.drain(timeout)

    # ------------------------------------------------------------ job entry
    def submit_job(self, job_id: str, session_id: str, plan: lp.LogicalPlan) -> None:
        self.event_loop.get_sender().post(JobQueued(job_id, session_id, plan))

    def update_task_status(
        self, executor_id: str, statuses: List[TaskInfo]
    ) -> None:
        """Reject updates from executors already declared dead
        (reference: scheduler_server/mod.rs:157-178)."""
        if self.state.executor_manager.is_dead_executor(executor_id):
            log.warning(
                "dropping %d task status(es) from dead executor %s",
                len(statuses),
                executor_id,
            )
            return
        try:
            meta = self.state.executor_manager.get_executor_metadata(executor_id)
        except Exception:
            # unknown executor (e.g. scheduler restarted and lost state):
            # drop rather than erroring the RPC, which would make the
            # executor's status reporter retry the same batch forever
            log.warning(
                "dropping %d task status(es) from unknown executor %s",
                len(statuses),
                executor_id,
            )
            return
        self.event_loop.get_sender().post(TaskUpdating(meta, statuses))

    def offer_reservation(self, reservations: List[ExecutorReservation]) -> None:
        self.event_loop.get_sender().post(ReservationOffering(reservations))

    def executor_lost(self, executor_id: str, reason: str = "") -> None:
        self.event_loop.get_sender().post(ExecutorLost(executor_id, reason))

    # ------------------------------------------------------- decommission
    def decommission_executor(
        self,
        executor_id: str,
        reason: str = "decommissioned by operator",
        timeout_s: Optional[float] = None,
    ) -> bool:
        """Graceful decommission (ISSUE 6): mark the executor DRAINING —
        it takes no new work from this moment — and ask it to drain:
        finish (or, past the timeout, cancel-and-hand-off) its running
        tasks, upload un-replicated shuffle partitions to the external
        store, report ExecutorStopped and exit.  The ExecutorStopped (or,
        for a wedged drain, the reaper's deadline) then rides the normal
        event-loop ExecutorLost path, which re-points shuffle locations
        at replicas and only recomputes what truly has no surviving copy.

        Pull-mode executors (no gRPC port) can't receive the drain RPC:
        they are marked draining (starving them of work) and the deadline
        concludes the drain.  Returns False for unknown executors."""
        em = self.state.executor_manager
        timeout = self.drain_timeout_s if timeout_s is None else timeout_s
        try:
            meta = em.get_executor_metadata(executor_id)
        except Exception:  # noqa: BLE001
            log.warning("cannot decommission unknown executor %s", executor_id)
            return False
        em.mark_draining(executor_id, timeout)
        log.info(
            "decommissioning executor %s (drain timeout %.0fs): %s",
            executor_id, timeout, reason,
        )
        if meta.grpc_port:
            # the drain RPC returns immediately; the executor drains in
            # the background and reports ExecutorStopped when done.  Off
            # the caller's thread: a dead host costs a 5s RPC timeout.
            def _ask() -> None:
                try:
                    from ..proto.rpc import executor_stub

                    executor_stub(meta.host, meta.grpc_port).StopExecutor(
                        pb.StopExecutorParams(
                            executor_id=executor_id,
                            reason=reason,
                            force=False,
                            drain=True,
                            drain_timeout_seconds=timeout,
                        ),
                        timeout=5,
                    )
                except Exception as e:  # noqa: BLE001
                    log.warning(
                        "drain RPC to %s failed (the deadline watchdog "
                        "will conclude the drain): %s", executor_id, e,
                    )

            threading.Thread(
                target=_ask, name="drain-executor", daemon=True
            ).start()
        return True

    # ------------------------------------------------------------ pull mode
    def poll_work(
        self,
        metadata: ExecutorMetadata,
        can_accept_task: bool,
        statuses: List[TaskInfo],
    ) -> Optional[pb.TaskDefinition]:
        """Pull-mode heart of the scheduler (reference: grpc.rs:56-175):
        save metadata + heartbeat, apply piggybacked statuses, then fill at
        most one task into the polling executor's free slot."""
        em = self.state.executor_manager
        if em.is_dead_executor(metadata.id):
            log.warning("rejecting poll from dead executor %s", metadata.id)
            return None
        self._save_poll_registration(metadata)

        if statuses:
            events, _ = self.state.update_task_statuses(metadata, statuses)
            post_job_events(self.state, self.event_loop.get_sender(), events)

        if not can_accept_task:
            return None
        reservation = ExecutorReservation(metadata.id)
        tm: TaskManager = self.state.task_manager
        assignments, _free, _pending = tm.fill_reservations([reservation])
        if not assignments:
            return None
        _executor_id, task = assignments[0]
        return tm.prepare_task_definition(task)

    def _save_poll_registration(self, metadata: ExecutorMetadata) -> None:
        from .executor_manager import ExecutorHeartbeat

        em = self.state.executor_manager
        try:
            em.get_executor_metadata(metadata.id)
        except Exception:
            em.register_executor(metadata)
            return
        em.save_heartbeat(ExecutorHeartbeat(metadata.id, time.time(), "active"))

    # -------------------------------------------------------------- reaper
    def _reaper_loop(self) -> None:
        """Periodically expire executors whose heartbeats timed out
        (reference: scheduler_server/mod.rs:192-253 expire_dead_executors),
        publish this scheduler's own liveness, and adopt jobs curated by
        dead peer schedulers (HA failover over a shared backend)."""
        while not self._stop.wait(self.reaper_interval_s):
            try:
                self._expire_dead_executors()
                self._expire_overdue_drains()
            except Exception:  # noqa: BLE001 - reaper must never die
                log.exception("dead-executor reaper iteration failed")
            try:
                self.heartbeat_self()
                self.take_over_dead_schedulers()
            except Exception:  # noqa: BLE001
                log.exception("scheduler-liveness sweep failed")

    def _speculation_loop(self) -> None:
        """Periodically post a SpeculationScan onto the event loop — the
        straggler/deadline scan itself runs on the event-loop thread, so
        every graph mutation keeps the single-thread discipline.  Idle
        schedulers (no active jobs) skip the post entirely.  The same
        timer drives the AdmissionPulse while the admission queue is
        non-empty (queue-wait expiry + the release catch-up for
        capacity freed outside job events, e.g. a new executor)."""
        from .query_stage_scheduler import AdmissionPulse, SpeculationScan

        while not self._stop.wait(max(0.05, self.speculation_interval_s)):
            try:
                if self.state.task_manager.active_job_ids():
                    self.event_loop.get_sender().post(SpeculationScan())
                if self.state.admission.queued_count():
                    self.event_loop.get_sender().post(AdmissionPulse())
            except Exception:  # noqa: BLE001 - timer must never die
                log.exception("speculation timer iteration failed")
            if self.autoscaler is not None:
                # the autoscaler rides the same cadence; its own tick()
                # contains provider failures, but belt-and-braces here —
                # this thread also drives speculation and admission
                try:
                    self.autoscaler.tick()
                except Exception:  # noqa: BLE001
                    log.exception("autoscaler tick failed")

    def _telemetry_loop(self) -> None:
        """Record the cluster-aggregate series (queue depth, running
        tasks, slots free, shuffle backlog) into the bounded timeseries
        rings — the history behind /api/cluster/timeseries; the same
        values are scrape-time gauges on /api/metrics."""
        while not self._stop.wait(max(0.1, self.telemetry_sample_s)):
            try:
                self.sample_cluster_telemetry()
            except Exception:  # noqa: BLE001 - timer must never die
                log.exception("cluster telemetry sample failed")

    def sample_cluster_telemetry(self) -> Dict[str, float]:
        """One cluster-aggregate sample (also callable from tests)."""
        state = self.state
        pending, running = state.task_manager.task_counts()
        em = state.executor_manager
        latest = state.telemetry.latest()
        metrics: Dict[str, float] = {
            "pending_tasks": pending,
            "running_tasks": running,
            "available_slots": em.available_slots(),
            "alive_executors": len(em.get_alive_executors()),
            "active_jobs": len(state.task_manager.active_job_ids()),
            "executors_quarantined": len(em.quarantined_executors()),
            "executors_draining": len(em.draining_executors()),
            "admission_queued_jobs": state.admission.queued_count(),
            # shuffle backlog: queued-but-unmoved bytes + pending replica
            # uploads summed over the latest executor snapshots
            "shuffle_queue_bytes": sum(
                (s.get("fetch_queue_bytes") or 0)
                + (s.get("write_queue_bytes") or 0)
                for s in latest.values()
                if isinstance(s, dict)
            ),
            "replicator_backlog": sum(
                s.get("replicator_backlog") or 0
                for s in latest.values()
                if isinstance(s, dict)
            ),
        }
        state.telemetry.record_cluster(metrics)
        return metrics

    def doctor_cluster_context(self) -> Dict[str, object]:
        """Live capacity context for the query doctor's cluster rules
        (underprovisioned_cluster, the scale-out-in-flight note on
        admission_queued_job) — shared by the REST and gRPC report
        handlers so both surfaces diagnose from identical numbers."""
        em = self.state.executor_manager
        ctx: Dict[str, object] = {
            "alive_executors": len(em.get_alive_executors()),
            "admission_queued_jobs": self.state.admission.queued_count(),
            "autoscaler_enabled": self.autoscaler is not None,
            "max_executors": 0,
        }
        if self.autoscaler is not None:
            launching = self.autoscaler.scale_out_in_flight()
            ctx["max_executors"] = self.autoscaler.policy.max_executors
            ctx["scale_out_in_flight"] = launching
            ctx["autoscaler_launching"] = self.autoscaler._count_phase(
                "launching"
            )
        else:
            # knob off: diagnose against the default ceiling so the
            # doctor can still say "this cluster could have scaled"
            from ..config import AUTOSCALER_MAX_EXECUTORS, BallistaConfig

            ctx["max_executors"] = BallistaConfig({})._get(
                AUTOSCALER_MAX_EXECUTORS
            )
        return ctx

    # --------------------------------------------------------- HA failover
    SCHEDULER_HB_PREFIX = "scheduler:"
    # a peer is dead only after missing several sweeps: the publish period
    # IS the sweep period, so the threshold must be a clear multiple of it
    # (executors use the same shape: 60s beats, 180s expiry)
    SCHEDULER_DEAD_SWEEPS = 3.0

    def heartbeat_self(self) -> None:
        """Publish this scheduler's liveness into the shared backend (the
        peer-visible analogue of executor heartbeats; its own keyspace so
        the executor-heartbeat watch never sees it)."""
        self.state.backend.put(
            Keyspace.Schedulers,
            f"{self.SCHEDULER_HB_PREFIX}{self.scheduler_id}",
            str(time.time()).encode(),
        )

    def take_over_dead_schedulers(
        self, timeout_s: Optional[float] = None
    ) -> List[str]:
        """Adopt active jobs curated by peers whose heartbeat expired.
        With a shared etcd-style backend this is the multi-scheduler HA
        story: any survivor resumes a dead curator's jobs (reference:
        curator ids in ``execution_graph.rs:99-101`` +
        ``backend/etcd.rs`` shared state)."""
        timeout = (
            timeout_s
            if timeout_s is not None
            else self.SCHEDULER_DEAD_SWEEPS * self.reaper_interval_s
        )
        now = time.time()
        adopted: List[str] = []
        for key, raw in self.state.backend.get_from_prefix(
            Keyspace.Schedulers, self.SCHEDULER_HB_PREFIX
        ):
            peer = key[len(self.SCHEDULER_HB_PREFIX):]
            if peer == self.scheduler_id:
                continue
            try:
                ts = float(raw.decode())
            except ValueError:
                continue
            if now - ts <= timeout:
                continue
            jobs = self.state.task_manager.take_over_jobs(peer)
            # the dead peer's QUEUED jobs (never planned, graph-less)
            # come over too: replay its admission-WAL entries under this
            # scheduler's curatorship, in the peer's submit order
            requeued = self.replay_admission_wal(curator=peer)
            # one survivor wins the takeover lock; clearing the heartbeat
            # makes the adoption idempotent across sweeps
            self.state.backend.delete(Keyspace.Schedulers, key)
            if jobs or requeued:
                log.warning(
                    "adopted %d job(s) + %d queued job(s) from dead "
                    "scheduler %s: %s",
                    len(jobs), len(requeued), peer, jobs + requeued,
                )
                adopted.extend(jobs)
                adopted.extend(requeued)
            if jobs:
                # the dead peer's reservations leaked with it; its
                # adopted jobs' tasks are pending again, so rebuild the
                # slot counts and re-offer (revive alone never offers)
                reclaimed = self.state.executor_manager.reconcile_slots(
                    self.state.task_manager.running_tasks_by_executor()
                )
                if reclaimed:
                    log.info(
                        "reconciled leaked executor slots on takeover: %s",
                        reclaimed,
                    )
                if self.policy == TaskSchedulingPolicy.PUSH_STAGED:
                    from .query_stage_scheduler import JobSubmitted

                    for job_id in jobs:
                        self.event_loop.get_sender().post(JobSubmitted(job_id))
        return adopted

    def replay_admission_wal(self, curator: Optional[str] = None) -> List[str]:
        """Re-enqueue every WAL-journaled queued job owned by ``curator``
        (default: this scheduler — the restart path; the reaper passes a
        dead peer's id on takeover).  Entries replay in submit order;
        jobs that already reached a durable downstream state (graph
        persisted or terminal) are stale and dropped instead.  Buffered
        cancel intents re-arm the same way, so a cancel raced with the
        crash still wins.  No-op unless ``--admission-wal-enabled``."""
        wal = self.state.admission_wal
        if wal is None:
            return []
        me = self.state.task_manager.scheduler_id
        target = me if curator is None else curator
        admission = self.state.admission
        restored: List[str] = []
        for key, rec in wal.load(target):
            job_id = rec.get("job_id") or ""
            if not job_id:
                continue
            if any(
                self.state.backend.get(ks, job_id) is not None
                for ks in (
                    Keyspace.ActiveJobs,
                    Keyspace.CompletedJobs,
                    Keyspace.FailedJobs,
                )
            ):
                # the job made it past the queue before the crash (its
                # graph persisted / went terminal): the entry is stale
                wal.register(job_id, key)
                wal.discard(job_id)
                continue
            if target != me:
                # takeover: re-stamp so a second failover replays again
                rec = wal.rewrite_curator(key, rec, me)
            try:
                plan = wal.decode_plan(rec)
            except Exception:  # noqa: BLE001 - poison entry must not wedge boot
                log.exception("dropping undecodable admission WAL entry %s", key)
                wal.register(job_id, key)
                wal.discard(job_id)
                continue
            if admission.restore(
                job_id,
                rec.get("session_id") or "",
                plan,
                rec.get("pool") or "default",
                rec.get("priority") or "batch",
                float(rec.get("pool_weight") or 1.0),
                int(rec.get("pool_max_running") or 0),
                float(rec.get("enqueued_unix") or time.time()),
                float(rec.get("max_wait_s") or 0.0),
            ):
                wal.register(job_id, key)
                restored.append(job_id)
        for job_id in wal.load_intents(target):
            admission.restore_cancel_intent(job_id)
            if target != me:
                wal.put_intent(job_id)  # re-stamp to the adopting curator
        if restored:
            from .query_stage_scheduler import AdmissionPulse

            self.event_loop.get_sender().post(AdmissionPulse())
        return restored

    def _expire_dead_executors(self) -> None:
        """Heartbeat-timeout expiry ONLY posts ExecutorLost: the loss
        itself (state removal, StopExecutor, rollback/repoint, drain
        bookkeeping) is handled on the event-loop thread exactly like
        gRPC-reported loss, so the two paths can never interleave a
        rollback with drain handling (ISSUE 6 satellite — previously the
        StopExecutor RPC ran here on the reaper thread)."""
        expired = self.state.executor_manager.get_expired_executors(
            self.executor_timeout_s
        )
        for hb in expired:
            age = time.time() - hb.timestamp
            log.warning(
                "executor %s heartbeat is %.0fs old (timeout %.0fs); removing",
                hb.executor_id,
                age,
                self.executor_timeout_s,
            )
            self.executor_lost(hb.executor_id, "heartbeat timed out")

    def _expire_overdue_drains(self) -> None:
        """A draining executor that never reported stopped inside its
        deadline (+grace) is declared lost — same event-loop path, so its
        tasks hand off and its locations re-point exactly once.  One
        still heartbeating (mid drain-upload) is deferred up to the
        hard cap rather than interrupted mid-copy — but only push-mode
        executors, which actually received the drain RPC; a pull-mode
        drain has nothing to wait on, the deadline concludes it."""
        em = self.state.executor_manager
        draining = set()
        for eid in em.get_alive_executors():
            try:
                if em.get_executor_metadata(eid).grpc_port:
                    draining.add(eid)
            except Exception:  # noqa: BLE001 - racing a removal
                pass
        for eid in em.overdue_drains(alive=draining):
            log.warning(
                "draining executor %s missed its drain deadline; "
                "declaring it lost", eid,
            )
            self.executor_lost(eid, "drain deadline exceeded")

    # --------------------------------------------------------------- misc
    def cancel_job(self, job_id: str) -> None:
        """Fail the job and tell executors to abort its running tasks over
        the pooled channel cache — one cached channel per executor instead
        of a fresh handshake per fan-out (reference: grpc.rs CancelJob →
        task_manager.rs:225-303)."""
        running = self.state.task_manager.cancel_job(job_id)
        if self.state.admission.queued_count():
            # a cancelled running job freed an admission slot from this
            # gRPC thread; queued-job release must run on the event loop
            from .query_stage_scheduler import AdmissionPulse

            self.event_loop.get_sender().post(AdmissionPulse())
        from ..proto.rpc import executor_stub

        for meta, pids in running:
            if not meta.grpc_port:
                continue
            try:
                executor_stub(meta.host, meta.grpc_port).CancelTasks(
                    pb.CancelTasksParams(
                        partition_ids=[p.to_proto() for p in pids]
                    ),
                    timeout=5,
                )
            except Exception as e:  # noqa: BLE001
                log.warning("CancelTasks on %s failed: %s", meta.id, e)
