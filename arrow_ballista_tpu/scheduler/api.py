"""Scheduler REST API.

Counterpart of the reference's warp routes (``scheduler/src/api/handlers.rs:34-58``
and ``scheduler/src/api/mod.rs``): ``GET /api/state`` returns the registered
executors, scheduler uptime and version as JSON.  The reference multiplexes
REST and gRPC on one port via Accept-header dispatch
(``scheduler/src/main.rs:103-150``); grpcio owns its listening socket
outright, so here REST serves on its own port (``scheduler_port + 1`` by
convention in the binary).

Extra endpoints beyond the reference: ``/api/jobs`` (job table) and
``/api/metrics`` (slot accounting) — the scheduler UI needs both.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

BALLISTA_VERSION = "0.7.0-tpu"


class SchedulerApiHandler(BaseHTTPRequestHandler):
    server_version = "ballista-tpu-scheduler"
    scheduler = None  # class attr injected by make_api_server
    started_at = 0.0

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, payload: dict, code: int = 200) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        srv = type(self).scheduler
        if srv is None:
            self._json({"error": "scheduler not attached"}, 500)
            return
        path = self.path.split("?")[0].rstrip("/")
        if path == "/api/state":
            em = srv.state.executor_manager
            alive = em.get_alive_executors()
            executors = []
            for meta in em.executors():
                executors.append(
                    {
                        "id": meta.id,
                        "host": meta.host,
                        "port": meta.flight_port,
                        "grpc_port": meta.grpc_port,
                        "last_seen": em.last_seen(meta.id),
                        "alive": meta.id in alive,
                    }
                )
            self._json(
                {
                    "executors": executors,
                    "started": type(self).started_at,
                    "uptime_seconds": int(time.time() - type(self).started_at),
                    "version": BALLISTA_VERSION,
                }
            )
            return
        if path == "/api/jobs":
            tm = srv.state.task_manager
            self._json({"jobs": tm.list_jobs()})
            return
        if path == "/api/metrics":
            em = srv.state.executor_manager
            self._json(
                {
                    "available_slots": em.available_slots(),
                    "alive_executors": len(em.get_alive_executors()),
                    "active_jobs": len(srv.state.task_manager.active_job_ids()),
                }
            )
            return
        self._json({"error": f"no such route {path}"}, 404)


def make_api_server(
    scheduler, host: str = "0.0.0.0", port: int = 0
) -> ThreadingHTTPServer:
    """Build (but don't start) the REST server bound to ``host:port``."""
    handler = type(
        "BoundApiHandler",
        (SchedulerApiHandler,),
        {"scheduler": scheduler, "started_at": time.time()},
    )
    return ThreadingHTTPServer((host, port), handler)


class ApiServerHandle:
    """Background-thread REST server with clean shutdown."""

    def __init__(self, scheduler, host: str = "0.0.0.0", port: int = 0):
        self._httpd = make_api_server(scheduler, host, port)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ApiServerHandle":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="scheduler-rest", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
