"""Scheduler REST API.

Counterpart of the reference's warp routes (``scheduler/src/api/handlers.rs:34-58``
and ``scheduler/src/api/mod.rs``): ``GET /api/state`` returns the registered
executors, scheduler uptime and version as JSON.  The reference multiplexes
REST and gRPC on one port via Accept-header dispatch
(``scheduler/src/main.rs:103-150``); grpcio owns its listening socket
outright, so here REST serves on its own port (``scheduler_port + 1`` by
convention in the binary).

Extra endpoints beyond the reference: ``/api/jobs`` (job table),
``/api/metrics`` (unified registry snapshot, backward-compatible shape),
``/api/metrics/prometheus`` (text exposition, also served at
``/metrics``), ``/api/jobs/{id}/trace`` (Chrome-trace/Perfetto JSON of
the job's stitched spans), ``/api/jobs/{id}/profile``
(EXPLAIN-ANALYZE-style per-stage rollup incl. skew coefficients, doctor
findings and the wall-clock breakdown), ``/api/jobs/{id}/critical_path``
(critical-path attribution + time breakdown + doctor findings),
``/api/jobs/{id}/progress`` (live per-stage task progress + ETA),
``/api/cluster/health`` (live executors with slot/queue/resource gauges
+ cluster aggregates + SLO), ``/api/cluster/timeseries?metric=…``
(bounded downsampled history), ``/api/jobs/{id}/events`` and
``/api/events/tail`` (structured event journal) — see
docs/user-guide/observability.md — and ``/api/tenants`` (multi-tenant
admission pools: weights, lanes, queue depth, shed counts; see
docs/user-guide/multi-tenancy.md).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

BALLISTA_VERSION = "0.7.0-tpu"

# Minimal cluster dashboard (stand-in for the reference's React scheduler
# UI, ballista/ui/scheduler/): polls /api/state + /api/jobs + /api/metrics.
DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>Ballista-TPU Scheduler</title>
<style>
 body { font-family: ui-monospace, Menlo, monospace; margin: 2rem; color: #222; }
 h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.4rem; }
 table { border-collapse: collapse; margin-top: .4rem; }
 th, td { border: 1px solid #bbb; padding: .25rem .6rem; font-size: .85rem; text-align: left; }
 th { background: #f0f0f0; }
 .ok { color: #0a7d2c; } .dead { color: #b00020; }
 #meta { color: #666; font-size: .8rem; }
 .bar { background: #e4e4e4; width: 7rem; height: .6rem; display: inline-block; }
 .bar > i { background: #2b6cb0; height: 100%; display: block; }
 svg .stage rect { fill: #f7f7f7; stroke: #888; }
 svg .stage.Running rect { fill: #dbeafe; stroke: #2b6cb0; }
 svg .stage.Successful rect { fill: #dcfce7; stroke: #0a7d2c; }
 svg .stage.Failed rect { fill: #fee2e2; stroke: #b00020; }
 svg text { font: .7rem ui-monospace, Menlo, monospace; }
 svg line { stroke: #999; marker-end: url(#arr); }
 pre.plan { background: #f7f7f7; border: 1px solid #ddd; padding: .5rem;
            font-size: .75rem; overflow-x: auto; }
</style></head><body>
<h1>Ballista-TPU Scheduler</h1>
<div id="meta">loading…</div>
<h2>Executors</h2><table id="executors"><thead><tr>
 <th>id</th><th>host</th><th>flight</th><th>grpc</th><th>alive</th><th>last seen</th>
</tr></thead><tbody></tbody></table>
<h2>Jobs</h2><table id="jobs"><thead><tr>
 <th>job</th><th>state</th><th>retries</th><th></th></tr></thead><tbody></tbody></table>
<div id="detail"></div>
<script>
let openJob = null;
let openJobTerminal = false;  // completed/failed details are immutable: no re-fetch
function esc(s) {
  return String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;').replace(/>/g, '&gt;')
    .replace(/"/g, '&quot;').replace(/'/g, '&#39;');
}
async function showDetail(jobId) {
  openJob = jobId;
  const d = await fetch('/api/job/' + encodeURIComponent(jobId)).then(r => r.json());
  openJobTerminal = d.state === 'completed' || d.state === 'failed';
  if (!d.stages) {  // 404 payload; d.error on a FAILED job still has stages
    document.getElementById('detail').textContent = d.error ||
      (d.state === 'queued'
        ? `queued in pool '${d.pool}' at position ${d.queue_position}`
        : 'no such job');
    return;
  }
  // query doctor (ISSUE 13): findings + wall-clock breakdown ride the
  // profile; live ETA rides /progress while the job runs
  let prof = null, prog = null;
  try {
    prof = await fetch('/api/jobs/' + encodeURIComponent(jobId) + '/profile')
      .then(r => r.json());
  } catch (e) { /* diagnosis is optional decoration */ }
  if (d.state === 'running') {
    try {
      prog = await fetch('/api/jobs/' + encodeURIComponent(jobId) + '/progress')
        .then(r => r.json());
    } catch (e) { /* ditto */ }
  }
  let html = `<h2>Job ${esc(jobId)} — ${esc(d.state)}` +
    ` <a href="/api/job/${encodeURIComponent(jobId)}/dot">[dot]</a>` +
    ` <a href="/api/jobs/${encodeURIComponent(jobId)}/critical_path">[critical path]</a></h2>`;
  if (d.error) html += `<p class="dead">${esc(d.error)}</p>`;
  if (prog && prog.tasks_total) {
    html += `<p>${prog.tasks_done}/${prog.tasks_total} tasks done · ` +
      `${prog.tasks_running} running` +
      (prog.eta_s != null ? ` · ~${prog.eta_s}s left` : '') + `</p>`;
  }
  if (prof && prof.breakdown) {
    const parts = Object.entries(prof.breakdown)
      .filter(([, v]) => v > 0.05).sort((a, b) => b[1] - a[1])
      .map(([k, v]) => `${k.replace(/_ms$/, '').replace(/_/g, ' ')} ` +
        `${v >= 1000 ? (v / 1000).toFixed(2) + 's' : v.toFixed(1) + 'ms'}`);
    if (parts.length) html += `<p>time went to: ${esc(parts.join(' · '))}</p>`;
  }
  if (prof && prof.doctor && prof.doctor.length) {
    html += '<h2>Doctor</h2><ul>';
    for (const f of prof.doctor) {
      html += `<li class="${f.severity === 'warn' ? 'dead' : ''}">` +
        `[${esc(f.severity)}] ${esc(f.code)}` +
        (f.stage_id !== undefined ? ` (stage ${f.stage_id})` : '') +
        `: ${esc(f.summary)}</li>`;
    }
    html += '</ul>';
  }
  html += dagSvg(d.stages);
  const hist = d.attempt_histogram || {};
  const retried = Object.entries(hist).filter(([a]) => a > 0)
    .map(([a, n]) => `${n} task(s) @ ${a} retr${a > 1 ? 'ies' : 'y'}`).join(', ');
  if (retried) html += `<p>attempt histogram: ${esc(retried)}</p>`;
  html += '<table><thead><tr><th>stage</th><th>state</th><th>tasks</th>' +
          '<th>progress</th><th>retries</th><th>metrics</th></tr></thead><tbody>';
  for (const s of d.stages) {
    const done = s.completed_tasks === undefined ? '—'
      : `${s.completed_tasks}/${s.partitions}`;
    const pct = s.completed_tasks === undefined ? 0
      : Math.round(100 * s.completed_tasks / Math.max(1, s.partitions));
    const retr = (s.task_retries || s.fetch_retries)
      ? `task ${s.task_retries || 0} · fetch ${s.fetch_retries || 0}` : '—';
    // adaptive re-plan badge: observed stats reshaped this stage's tasks
    const aqe = s.aqe
      ? `aqe ${s.aqe.tasks_before}→${s.aqe.tasks_after} tasks` +
        (s.aqe.broadcast ? ' (broadcast)' : '') +
        (s.aqe.skew_splits ? ` (${s.aqe.skew_splits} skew splits)` : '')
      : '';
    // keyed device-path badge: group keys encoded on device inside the
    // fused encode→sort→segment-reduce dispatch (next to the
    // key_encode_time_ns it eliminates in the generic metrics)
    const tm = (s.metrics && Object.entries(s.metrics)
      .filter(([op]) => op.startsWith('TpuStage'))
      .reduce((acc, [, m]) => {
        for (const [k, v] of Object.entries(m)) acc[k] = (acc[k] || 0) + v;
        return acc;
      }, {})) || {};
    const keyed = (tm.device_encode_batches || tm.fused_keyed_dispatches)
      ? `device-encode ${tm.device_encode_batches || 0} batch(es) · ` +
        `${tm.fused_keyed_dispatches || 0} fused keyed dispatch(es)`
      : '';
    // whole-stage fusion badge: segments the fusion planner produced and
    // the widest fused run (fused-pid marks pid derivation in-trace)
    const fusion = tm.fused_segments
      ? `fused ${tm.fused_segments} segment(s) · ` +
        `${tm.fused_ops_per_dispatch || 0} ops/dispatch` +
        (tm.fused_pid_in_kernel ? ' · fused-pid' : '') +
        (tm.fused_degraded ? ` · ${tm.fused_degraded} degraded` : '')
      : '';
    const opMets = s.metrics
      ? esc(Object.entries(s.metrics)
          // __-prefixed operators are the skew-analytics payloads
          // (per-partition maps); the profile endpoint renders them
          .filter(([op]) => !op.startsWith('__'))
          .map(([op, m]) =>
          op + ': ' + Object.entries(m).map(([k, v]) => `${k}=${v}`).join(' ')
        ).join(' · '))
      : '';
    // plan-cache badge: this stage (and its elided upstream) was served
    // from a fingerprint-matched prior run — zero tasks dispatched
    const cached = s.cache
      ? `served from cache (${s.cache.bytes || 0} B)` : '';
    const mets = [cached, aqe, keyed, fusion, opMets].filter(Boolean).join(' · ') || '—';
    html += `<tr><td>${s.stage_id}</td><td>${esc(s.state)}</td>` +
            `<td>${done}</td>` +
            `<td><span class="bar"><i style="width:${pct}%"></i></span></td>` +
            `<td>${esc(retr)}</td>` +
            `<td>${mets}</td></tr>`;
    if (s.plan) {
      html += `<tr><td colspan="6"><details><summary>stage ${s.stage_id} ` +
              `plan</summary><pre class="plan">${esc(s.plan)}</pre>` +
              `</details></td></tr>`;
    }
  }
  html += '</tbody></table>';
  document.getElementById('detail').innerHTML = html;
}
function dagSvg(stages) {
  // layered DAG layout: producers left of consumers (output_links are
  // stage -> consumer edges); the reference UI renders this graph via
  // react-flow — here a dependency-free SVG suffices
  if (!stages || !stages.length) return '';
  const byId = {}, preds = {};
  for (const s of stages) { byId[s.stage_id] = s; preds[s.stage_id] = []; }
  for (const s of stages)
    for (const c of (s.output_links || []))
      if (preds[c] !== undefined) preds[c].push(s.stage_id);
  const layer = {};
  const depth = (id, seen) => {
    if (layer[id] !== undefined) return layer[id];
    if (seen.has(id)) return 0;  // cycle guard (never expected)
    seen.add(id);
    const ps = preds[id];
    layer[id] = ps.length ? 1 + Math.max(...ps.map(p => depth(p, seen))) : 0;
    return layer[id];
  };
  for (const s of stages) depth(s.stage_id, new Set());
  const cols = {};
  for (const s of stages) (cols[layer[s.stage_id]] ||= []).push(s);
  const W = 120, H = 46, GX = 60, GY = 18;
  const pos = {};
  let maxRow = 0;
  for (const [l, ss] of Object.entries(cols)) {
    ss.sort((a, b) => a.stage_id - b.stage_id);
    ss.forEach((s, i) => { pos[s.stage_id] = [l * (W + GX), i * (H + GY)]; });
    maxRow = Math.max(maxRow, ss.length);
  }
  const width = (Object.keys(cols).length) * (W + GX);
  const height = maxRow * (H + GY);
  let svg = `<svg width="${width}" height="${height}" ` +
    `style="margin:.5rem 0;display:block">` +
    '<defs><marker id="arr" viewBox="0 0 6 6" refX="6" refY="3" ' +
    'markerWidth="5" markerHeight="5" orient="auto">' +
    '<path d="M0,0 L6,3 L0,6 z" fill="#999"/></marker></defs>';
  for (const s of stages)
    for (const c of (s.output_links || [])) {
      if (!pos[c]) continue;
      const [x1, y1] = pos[s.stage_id], [x2, y2] = pos[c];
      svg += `<line x1="${x1 + W}" y1="${y1 + H / 2}" ` +
             `x2="${x2}" y2="${y2 + H / 2}"/>`;
    }
  for (const s of stages) {
    const [x, y] = pos[s.stage_id];
    const pct = s.completed_tasks === undefined ? 0
      : (s.completed_tasks / Math.max(1, s.partitions));
    svg += `<g class="stage ${esc(s.state)}" transform="translate(${x},${y})">` +
      `<rect width="${W}" height="${H}" rx="5"/>` +
      `<title>${esc(s.plan || '')}</title>` +
      `<text x="8" y="17">stage ${s.stage_id}</text>` +
      `<text x="8" y="31" fill="#555">${esc(s.state)}</text>` +
      `<rect x="8" y="36" width="${W - 16}" height="4" fill="#e4e4e4" stroke="none"/>` +
      `<rect x="8" y="36" width="${(W - 16) * pct}" height="4" fill="#2b6cb0" stroke="none"/>` +
      `</g>`;
  }
  return svg + '</svg>';
}
async function refresh() {
  try {
    const [state, jobs, metrics, cache] = await Promise.all([
      fetch('/api/state').then(r => r.json()),
      fetch('/api/jobs').then(r => r.json()),
      fetch('/api/metrics').then(r => r.json()),
      fetch('/api/cache').then(r => r.json()).catch(() => null),
    ]);
    document.getElementById('meta').textContent =
      `version ${state.version} · uptime ${state.uptime_seconds}s · ` +
      `${metrics.alive_executors} executor(s) · ${metrics.available_slots} slot(s) · ` +
      `${metrics.active_jobs} active job(s) · ` +
      `${metrics.task_retries || 0} task retr${metrics.task_retries === 1 ? 'y' : 'ies'} · ` +
      `${metrics.executors_quarantined || 0} quarantined · ` +
      `${metrics.admission_queued_jobs || 0} queued · ` +
      `spec ${metrics.speculative_wins || 0}/${metrics.speculative_launched || 0} won · ` +
      `${metrics.task_timeouts_total || 0} reaped` +
      (metrics.autoscaler_desired_executors !== undefined
        ? ` · autoscale ${metrics.autoscaler_alive_executors || 0}/` +
          `${metrics.autoscaler_desired_executors} desired` +
          ` (+${metrics.autoscaler_launching_executors || 0} launching, ` +
          `-${metrics.autoscaler_draining_executors || 0} draining)`
        : '') +
      (cache && cache.cache
        ? ` · plan cache ${cache.cache.entry_count} entr` +
          `${cache.cache.entry_count === 1 ? 'y' : 'ies'} · ` +
          `${cache.cache.hits} hit(s)`
        : '');
    const etb = document.querySelector('#executors tbody');
    etb.innerHTML = '';
    for (const e of state.executors) {
      const age = e.last_seen ? Math.round(Date.now()/1000 - e.last_seen) + 's ago' : '—';
      etb.insertAdjacentHTML('beforeend',
        `<tr><td>${esc(e.id)}</td><td>${esc(e.host)}</td><td>${e.port}</td>` +
        `<td>${e.grpc_port || '—'}</td>` +
        `<td class="${e.alive ? 'ok' : 'dead'}">${e.alive ? 'alive' : 'dead'}</td>` +
        `<td>${age}</td></tr>`);
    }
    const jtb = document.querySelector('#jobs tbody');
    jtb.innerHTML = '';
    for (const j of jobs.jobs) {
      // no inline handlers: the raw id rides a data- attribute (read back
      // via dataset, so escaping concerns stay purely textual)
      jtb.insertAdjacentHTML('beforeend',
        `<tr><td>${esc(j.job_id)}</td><td>${esc(j.state)}</td>` +
        `<td>${j.task_retries || 0}</td>` +
        `<td><a href="#" class="detail-link" data-job="${esc(j.job_id)}">detail</a></td></tr>`);
    }
    for (const a of jtb.querySelectorAll('a.detail-link')) {
      a.addEventListener('click', (ev) => {
        ev.preventDefault();
        showDetail(a.dataset.job);
      });
    }
    if (openJob && !openJobTerminal) showDetail(openJob);
  } catch (err) {
    document.getElementById('meta').textContent = 'scheduler unreachable: ' + err;
  }
}
refresh();
setInterval(refresh, 2000);
</script></body></html>
"""


class SchedulerApiHandler(BaseHTTPRequestHandler):
    server_version = "ballista-tpu-scheduler"
    scheduler = None  # class attr injected by make_api_server
    started_at = 0.0

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, payload: dict, code: int = 200) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        srv = type(self).scheduler
        if srv is None:
            self._json({"error": "scheduler not attached"}, 500)
            return
        path = self.path.split("?")[0].rstrip("/")
        if path == "/api/state":
            em = srv.state.executor_manager
            alive = em.get_alive_executors()
            draining = set(em.draining_executors())
            executors = []
            for meta in em.executors():
                executors.append(
                    {
                        "id": meta.id,
                        "host": meta.host,
                        "port": meta.flight_port,
                        "grpc_port": meta.grpc_port,
                        "last_seen": em.last_seen(meta.id),
                        "alive": meta.id in alive,
                        "draining": meta.id in draining,
                    }
                )
            self._json(
                {
                    "executors": executors,
                    "started": type(self).started_at,
                    "uptime_seconds": int(time.time() - type(self).started_at),
                    "version": BALLISTA_VERSION,
                }
            )
            return
        if path == "/api/jobs":
            tm = srv.state.task_manager
            self._json({"jobs": tm.list_jobs()})
            return
        if path.startswith("/api/job/"):
            self._job_routes(srv, path[len("/api/job/"):])
            return
        if path == "/api/metrics":
            # unified registry snapshot; the legacy top-level keys keep
            # their names so dashboards/tests stay compatible
            snap = srv.state.metrics.snapshot()
            snap["task_retries"] = snap.get("task_retries_total", 0)
            self._json(snap)
            return
        if path in ("/api/metrics/prometheus", "/metrics"):
            from ..obs.registry import process_registry

            text = srv.state.metrics.prometheus_text() + (
                process_registry().prometheus_text()
            )
            body = text.encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path.startswith("/api/jobs/"):
            # /api/jobs/{id}[/dot] aliases /api/job/{id}[/dot], plus the
            # observability routes /trace, /profile and /events
            self._job_routes(srv, path[len("/api/jobs/"):])
            return
        if path == "/api/cluster/health":
            self._cluster_health(srv)
            return
        if path == "/api/tenants":
            # multi-tenant admission view (scheduler/admission.py):
            # per-pool weights, lanes, queue depth, running share and
            # lifetime admitted/shed counters
            self._json(srv.state.admission.snapshot())
            return
        if path == "/api/cache":
            # plan-fingerprint result cache + learned policy store
            # (ISSUE 18): entry table with hit/byte accounting plus the
            # per-plan override/rollback ledger
            self._json(
                {
                    "cache": srv.state.plan_cache.snapshot(),
                    "policy": srv.state.policy_store.snapshot(),
                }
            )
            return
        if path == "/api/cluster/timeseries":
            self._cluster_timeseries(srv)
            return
        if path == "/api/events/tail":
            self._events_tail(srv)
            return
        if path in ("", "/", "/ui"):  # noqa: RET505 - route ladder
            self._dashboard()
            return
        self._json({"error": f"no such route {path}"}, 404)

    def do_POST(self):  # noqa: N802 (http.server API)
        """Operator actions.  ``POST /api/executors/{id}/decommission``
        gracefully drains an executor (ISSUE 6) — the REST spelling of
        the DecommissionExecutor RPC."""
        srv = type(self).scheduler
        if srv is None:
            self._json({"error": "scheduler not attached"}, 500)
            return
        path = self.path.split("?")[0].rstrip("/")
        prefix, suffix = "/api/executors/", "/decommission"
        if path.startswith(prefix) and path.endswith(suffix):
            executor_id = path[len(prefix):-len(suffix)]
            ok = srv.decommission_executor(executor_id)
            self._json(
                {"executor_id": executor_id, "draining": bool(ok)},
                200 if ok else 404,
            )
            return
        self._json({"error": f"no such route {path}"}, 404)

    def _job_routes(self, srv, rest: str) -> None:
        """Per-job routes, shared by /api/job/ and /api/jobs/:
        {id} detail, {id}/dot, {id}/trace, {id}/profile, {id}/events."""
        tm = srv.state.task_manager
        if rest.endswith("/trace"):
            self._job_trace(srv, rest[: -len("/trace")])
            return
        if rest.endswith("/profile"):
            self._job_profile(srv, rest[: -len("/profile")])
            return
        if rest.endswith("/critical_path"):
            self._job_critical_path(srv, rest[: -len("/critical_path")])
            return
        if rest.endswith("/progress"):
            job_id = rest[: -len("/progress")]
            progress = tm.get_job_progress(job_id)
            if progress is None:
                self._json({"error": "no such job"}, 404)
                return
            self._json(progress)
            return
        if rest.endswith("/events"):
            job_id = rest[: -len("/events")]
            journal = srv.state.events
            if not journal.enabled:
                self._json(
                    {"error": "event journal disabled "
                              "(start the scheduler with --event-journal-dir)"},
                    404,
                )
                return
            self._json(
                {"job_id": job_id, "events": journal.for_job(job_id)}
            )
            return
        if rest.endswith("/dot"):
            dot = tm.get_job_dot(rest[: -len("/dot")])
            if dot is None:
                self._json({"error": "no such job"}, 404)
                return
            body = dot.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/vnd.graphviz")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        detail = tm.get_job_detail(rest)
        if detail is None:
            self._json({"error": "no such job"}, 404)
            return
        self._json(detail)

    def _query(self) -> dict:
        """Parsed query-string parameters ({key: last value})."""
        from urllib.parse import parse_qs, urlsplit

        try:
            qs = parse_qs(urlsplit(self.path).query)
            return {k: v[-1] for k, v in qs.items()}
        except Exception:  # noqa: BLE001 - malformed query string
            return {}

    def _cluster_health(self, srv) -> None:
        """Live cluster view: per-executor slot/queue/resource gauges
        from the latest heartbeat telemetry, cluster aggregates, journal
        health and SLO burn (ISSUE 7 tentpole, the /api surface both
        ROADMAP consumers read)."""
        state = srv.state
        em = state.executor_manager
        alive = em.get_alive_executors()
        draining = set(em.draining_executors())
        quarantined = set(em.quarantined_executors())
        latest = state.telemetry.latest()
        pending, running = state.task_manager.task_counts()
        executors = []
        for meta in em.executors():
            row = {
                "id": meta.id,
                "host": meta.host,
                "alive": meta.id in alive,
                "draining": meta.id in draining,
                "quarantined": meta.id in quarantined,
                "last_seen": em.last_seen(meta.id),
                "slots_total": meta.specification.task_slots,
            }
            snap = latest.get(meta.id)
            if snap:
                row["telemetry"] = snap
            executors.append(row)
        self._json(
            {
                "executors": executors,
                "cluster": {
                    "alive_executors": len(alive),
                    "available_slots": em.available_slots(),
                    "pending_tasks": pending,
                    "running_tasks": running,
                    "active_jobs": len(state.task_manager.active_job_ids()),
                    "executors_quarantined": len(quarantined),
                    "executors_draining": len(draining),
                },
                "slo": state.slo.snapshot(),
                "admission": state.admission.health_summary(),
                "events": state.events.stats(),
                "autoscaler": (
                    srv.autoscaler.snapshot()
                    if getattr(srv, "autoscaler", None) is not None
                    else {"enabled": False}
                ),
                "cache": self._cache_summary(state),
            }
        )

    @staticmethod
    def _cache_summary(state) -> dict:
        """Slim plan-cache block for /api/cluster/health: the counters
        and sizes without the per-entry table (that's /api/cache)."""
        snap = state.plan_cache.snapshot()
        snap.pop("entries", None)
        snap["policy_plans"] = state.policy_store.snapshot().get(
            "plan_count", 0
        )
        return snap

    def _cluster_timeseries(self, srv) -> None:
        """``?metric=<name>[&executor=<id>]`` returns that series'
        ``[[ts, value], ...]`` points (cluster aggregate by default,
        one executor's series with ``executor=``); without ``metric``
        lists what is recorded."""
        q = self._query()
        metric = q.get("metric", "")
        telemetry = srv.state.telemetry
        if not metric:
            self._json(telemetry.metric_names())
            return
        executor = q.get("executor") or None
        points = telemetry.series(metric, executor)
        if points is None:
            self._json(
                {"error": f"no series recorded for metric {metric!r}"
                          + (f" executor {executor!r}" if executor else "")},
                404,
            )
            return
        self._json(
            {"metric": metric, "executor": executor, "points": points}
        )

    def _events_tail(self, srv) -> None:
        """``?n=100[&kind=task_retry]`` — the journal's newest events."""
        journal = srv.state.events
        if not journal.enabled:
            self._json(
                {"error": "event journal disabled "
                          "(start the scheduler with --event-journal-dir)"},
                404,
            )
            return
        q = self._query()
        try:
            n = max(1, min(10_000, int(q.get("n", "100"))))
        except ValueError:
            n = 100
        self._json({"events": journal.tail(n, kind=q.get("kind") or None)})

    def _dashboard(self) -> None:
        body = DASHBOARD_HTML.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _job_spans(self, srv, job_id: str) -> list:
        from ..obs.recorder import spans_for_job

        return spans_for_job(job_id)

    def _job_trace(self, srv, job_id: str) -> None:
        from ..obs.export import chrome_trace

        spans = self._job_spans(srv, job_id)
        if not spans:
            self._json(
                {"error": f"no trace recorded for job {job_id!r} "
                          "(is ballista.obs.enabled set?)"},
                404,
            )
            return
        self._json(chrome_trace(spans, job_id))

    def _job_events(self, srv, job_id: str) -> list:
        journal = srv.state.events
        return journal.for_job(job_id) if journal.enabled else []

    def _job_profile(self, srv, job_id: str) -> None:
        from ..obs.doctor import job_report

        detail = srv.state.task_manager.get_job_detail(job_id)
        if detail is None or "stages" not in detail:
            self._json(detail or {"error": "no such job"}, 404 if detail is None else 200)
            return
        report = job_report(
            detail, self._job_spans(srv, job_id), self._job_events(srv, job_id),
            cluster=srv.doctor_cluster_context(),
        )
        self._json(report["profile"])

    def _job_critical_path(self, srv, job_id: str) -> None:
        """Critical path + wall-clock breakdown + doctor findings — the
        (b)+(c) surface of the query doctor (ISSUE 13)."""
        from ..obs.doctor import job_report

        detail = srv.state.task_manager.get_job_detail(job_id)
        if detail is None:
            self._json({"error": "no such job"}, 404)
            return
        if "stages" not in detail:
            # admission-queued: no graph yet — report the queue state
            self._json(detail)
            return
        report = job_report(
            detail, self._job_spans(srv, job_id), self._job_events(srv, job_id),
            cluster=srv.doctor_cluster_context(),
        )
        payload = report["critical_path"]
        payload["doctor"] = report["doctor"]
        self._json(payload)


def make_api_server(
    scheduler, host: str = "0.0.0.0", port: int = 0
) -> ThreadingHTTPServer:
    """Build (but don't start) the REST server bound to ``host:port``."""
    handler = type(
        "BoundApiHandler",
        (SchedulerApiHandler,),
        {"scheduler": scheduler, "started_at": time.time()},
    )
    return ThreadingHTTPServer((host, port), handler)


class ApiServerHandle:
    """Background-thread REST server with clean shutdown."""

    def __init__(self, scheduler, host: str = "0.0.0.0", port: int = 0):
        self._httpd = make_api_server(scheduler, host, port)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ApiServerHandle":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="scheduler-rest", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
