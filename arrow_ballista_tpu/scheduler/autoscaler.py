"""Closed-loop executor autoscaler (ISSUE 17).

The reference ships only a KEDA *stub* (``external_scaler.rs:29-65``
pins inflight at 1,000,000 so the HPA saturates); nothing in the system
ever launches or retires an executor.  This module closes the loop: a
policy engine ticking on the scheduler's existing 1s timer cadence
(``SchedulerServer._speculation_loop``) reads the signals the stack
already measures —

* admission queue depth (PR 12's front door, ``admission.queued_count``),
* live slot deficit (``task_manager.task_counts`` pending vs
  ``executor_manager.available_slots`` — the live spelling of PR 13's
  per-stage ``scheduling_delay_ms``: tasks runnable with nowhere to go),
* SLO burn rate (PR 7's ``SloTracker``),

and drives an :class:`ExecutorProvider` — ``launch(spec) -> handle`` /
``terminate(handle)`` / ``poll()``.  Real deployments implement the ABC
against their fleet API; :class:`LocalProcessProvider` (subprocess-backed
``python -m arrow_ballista_tpu.executor`` children) serves tests, benches
and single-host deployments.

Policy shape:

* **Scale-out** fires only after the pressure signal SUSTAINS for
  ``scale_out_sustain_seconds`` (hysteresis: a one-tick blip never
  launches) and outside the cooldown, sized by the slot deficit and
  clamped to ``ballista.autoscaler.max_executors``.
* **Scale-in** fires only after the cluster is COMPLETELY idle for
  ``scale_in_idle_seconds``, one executor per decision, never below
  ``min_executors``.  The victim is the managed executor holding the
  fewest un-replicated shuffle bytes (cheapest to move) and retires
  through the PR 6 graceful-drain path (``decommission_executor``):
  zero recompute, zero failed tasks.
* **Healing**: a crashed child detected by ``poll()`` is capacity loss —
  the scheduler is told (``ExecutorLost``) and the next actuation
  relaunches toward ``desired``.
* **Robustness**: provider exceptions and launch timeouts are caught,
  journaled (``autoscale_decision``), fed into the ExecutorManager's
  consecutive-launch-failure window, and suspend further launches for a
  backoff — they never take down the scheduler, and a slow/wedged
  ``launch()`` (the ``executor.launch`` delay fault) runs on a detached
  thread so the tick never blocks on it.

Everything is off by default: a scheduler without
``ballista.autoscaler.enabled=true`` never constructs this object, so
the knob-off event flow is byte-identical.
"""

from __future__ import annotations

import abc
import logging
import math
import os
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..config import (
    AUTOSCALER_COOLDOWN_S,
    AUTOSCALER_ENABLED,
    AUTOSCALER_LAUNCH_TIMEOUT_S,
    AUTOSCALER_MAX_EXECUTORS,
    AUTOSCALER_MIN_EXECUTORS,
    AUTOSCALER_SCALE_IN_IDLE_S,
    AUTOSCALER_SCALE_OUT_SUSTAIN_S,
    AUTOSCALER_SLO_BURN_THRESHOLD,
    BallistaConfig,
)
from ..testing.faults import fault_point

log = logging.getLogger(__name__)

# grace past the drain budget before a draining child that neither
# exited nor was declared lost gets terminated outright (the scheduler's
# reaper has its own, longer watchdog; this only reaps the process)
DRAIN_KILL_GRACE_S = 60.0
# SIGTERM -> SIGKILL escalation for terminate()
TERMINATE_GRACE_S = 5.0


# --------------------------------------------------------------- provider
@dataclass
class ExecutorSpec:
    """What the policy asks a provider to launch.  The provider fills in
    deployment details (scheduler address, image, work dir); the spec
    carries only what the policy decides."""

    executor_id: str
    task_slots: int = 2
    env: Dict[str, str] = field(default_factory=dict)


@dataclass
class ExecutorHandle:
    """Opaque provider-side handle for one launched executor."""

    executor_id: str
    backend: object = None  # provider-private (e.g. subprocess.Popen)


class ExecutorProvider(abc.ABC):
    """The actuator ABC real deployments implement (k8s, GCE MIGs, …).

    ``launch`` may block (cold starts are real) — the autoscaler always
    calls it from a detached thread and enforces its own timeout.
    ``poll`` must be cheap: it runs every tick."""

    #: slots each launched executor offers (sizes the slot-deficit math)
    task_slots: int = 2

    @abc.abstractmethod
    def launch(self, spec: ExecutorSpec) -> ExecutorHandle:
        """Start one executor; returns once the process/VM exists (not
        necessarily registered).  Raises on failure."""

    @abc.abstractmethod
    def terminate(self, handle: ExecutorHandle) -> None:
        """Hard-stop one executor (best effort, idempotent)."""

    @abc.abstractmethod
    def poll(self) -> Dict[str, Optional[int]]:
        """Liveness of every launched-and-not-terminated executor:
        ``{executor_id: None}`` while running, exit code once dead."""


PID_FILE = "executor.pid"


class _AdoptedProcess:
    """Popen-shaped wrapper around a pid this scheduler did not spawn:
    a child that survived its parent's crash (ISSUE 20 orphan adoption).
    ``os.waitpid`` cannot reap a non-child, so ``poll`` uses signal-0
    liveness and reports a synthetic ``-1`` exit code once dead."""

    def __init__(self, pid: int):
        self.pid = pid
        self._returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._returncode is not None:
            return self._returncode
        try:
            os.kill(self.pid, 0)
        except OSError:
            self._returncode = -1  # exit code unknowable for a non-child
            return self._returncode
        return None

    def terminate(self) -> None:
        import signal

        os.kill(self.pid, signal.SIGTERM)

    def kill(self) -> None:
        import signal

        os.kill(self.pid, signal.SIGKILL)

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = time.monotonic() + (timeout if timeout is not None else 0)
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if timeout is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    cmd=f"adopted pid {self.pid}", timeout=timeout
                )
            time.sleep(0.05)


class LocalProcessProvider(ExecutorProvider):
    """Subprocess-backed provider: each ``launch`` spawns
    ``python -m arrow_ballista_tpu.executor`` in push mode on random
    ports, pre-assigned its executor id (``--executor-id``) so the
    scheduler-side handle and the registration correlate.  Child stdout
    goes to ``<work_dir>/<executor_id>/launch.log``.

    Every launch persists ``<work_dir>/<executor_id>/executor.pid`` so a
    scheduler restarted over the same ``work_dir_root`` ADOPTS surviving
    children instead of launching a duplicate fleet (ISSUE 20): the
    constructor scans for pid files, verifies liveness (and, where /proc
    exists, that the pid still runs *this* executor id — a pid-reuse
    guard), wraps live ones in :class:`_AdoptedProcess`, and reaps stale
    files for dead ones."""

    def __init__(
        self,
        scheduler_host: str,
        scheduler_port: int,
        task_slots: int = 2,
        work_dir_root: str = "",
        heartbeat_interval_s: float = 5.0,
        extra_args: Optional[List[str]] = None,
        env: Optional[Dict[str, str]] = None,
    ):
        import tempfile

        self.scheduler_host = scheduler_host
        self.scheduler_port = scheduler_port
        self.task_slots = task_slots
        self.work_dir_root = work_dir_root or tempfile.mkdtemp(
            prefix="ballista-autoscale-"
        )
        self.heartbeat_interval_s = heartbeat_interval_s
        self.extra_args = list(extra_args or [])
        self.env = dict(env or {})
        self._lock = threading.Lock()
        self._procs: Dict[str, subprocess.Popen] = {}
        self._adopted: List[str] = []
        self._adopt_orphans()

    # -------------------------------------------------- orphan adoption
    def _pid_path(self, executor_id: str) -> str:
        return os.path.join(self.work_dir_root, executor_id, PID_FILE)

    def _remove_pid_file(self, executor_id: str) -> None:
        try:
            os.unlink(self._pid_path(executor_id))
        except OSError:
            pass

    @staticmethod
    def _pid_runs_executor(pid: int, executor_id: str) -> bool:
        """True when ``pid`` is alive AND (where verifiable) still runs
        the executor module with this id — a recycled pid must not be
        adopted as a fleet member."""
        try:
            os.kill(pid, 0)
        except OSError:
            return False
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
        except OSError:
            return True  # no /proc (or raced an exit): liveness-only
        return (
            b"--executor-id" in argv
            and executor_id.encode() in argv
        )

    def _adopt_orphans(self) -> None:
        """Scan ``work_dir_root`` for pid files left by a previous
        scheduler process; adopt live children, reap dead ones."""
        try:
            entries = sorted(os.listdir(self.work_dir_root))
        except OSError:
            return
        for eid in entries:
            path = os.path.join(self.work_dir_root, eid, PID_FILE)
            try:
                with open(path, encoding="utf-8") as f:
                    pid = int(f.read().split()[0])
            except (OSError, ValueError, IndexError):
                continue
            if self._pid_runs_executor(pid, eid):
                with self._lock:
                    self._procs[eid] = _AdoptedProcess(pid)
                    self._adopted.append(eid)
                log.info("adopted orphan executor %s (pid %d)", eid, pid)
            else:
                self._remove_pid_file(eid)
                log.info(
                    "reaped stale pid file for dead executor %s (pid %d)",
                    eid, pid,
                )

    def adopted_ids(self) -> List[str]:
        """Executor ids adopted from a previous scheduler's fleet (the
        autoscaler folds these into its managed set and desired count)."""
        with self._lock:
            return list(self._adopted)

    def launch(self, spec: ExecutorSpec) -> ExecutorHandle:
        # deterministic failure/cold-start testing (ISSUE 17 satellite):
        # error faults model a fleet API refusal, delay faults a slow
        # provision — both exercised without a flaky real fleet
        fault_point("executor.launch", executor_id=spec.executor_id)
        work_dir = os.path.join(self.work_dir_root, spec.executor_id)
        os.makedirs(work_dir, exist_ok=True)
        args = [
            sys.executable,
            "-m",
            "arrow_ballista_tpu.executor",
            "--scheduler-host", self.scheduler_host,
            "--scheduler-port", str(self.scheduler_port),
            "--bind-host", "127.0.0.1",
            "--bind-port", "0",
            "--bind-grpc-port", "0",
            "--executor-id", spec.executor_id,
            "--concurrent-tasks", str(spec.task_slots or self.task_slots),
            "--task-scheduling-policy", "push-staged",
            "--work-dir", work_dir,
            "--heartbeat-interval-seconds", str(self.heartbeat_interval_s),
            "--heartbeat-sidecar", "0",
            *self.extra_args,
        ]
        env = {**os.environ, **self.env, **spec.env}
        # the parent may import the package via a sys.path edit (notebook,
        # scratch-dir driver); the child's -m lookup only sees PYTHONPATH,
        # so pin the package root or launches fail rc=1 outside the repo
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing if existing else "")
            )
        log_path = os.path.join(work_dir, "launch.log")
        with open(log_path, "ab") as sink:
            proc = subprocess.Popen(  # noqa: S603 - our own binary
                args, stdout=sink, stderr=subprocess.STDOUT, env=env
            )
        with self._lock:
            self._procs[spec.executor_id] = proc
        try:
            # handle persistence (ISSUE 20): lets a restarted scheduler
            # adopt this child instead of double-launching its capacity
            with open(self._pid_path(spec.executor_id), "w",
                      encoding="utf-8") as f:
                f.write(f"{proc.pid}\n")
        except OSError:
            log.warning("could not persist pid file for %s", spec.executor_id)
        log.info(
            "launched executor %s (pid %d, slots %d)",
            spec.executor_id, proc.pid, spec.task_slots or self.task_slots,
        )
        return ExecutorHandle(spec.executor_id, proc)

    def terminate(self, handle: ExecutorHandle) -> None:
        with self._lock:
            proc = self._procs.pop(handle.executor_id, None)
        self._remove_pid_file(handle.executor_id)
        proc = proc or handle.backend
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.terminate()
        except OSError:
            return

        def _escalate() -> None:
            try:
                proc.wait(TERMINATE_GRACE_S)
            except subprocess.TimeoutExpired:
                try:
                    proc.kill()
                except OSError:
                    pass
            # reap the zombie either way
            try:
                proc.wait(TERMINATE_GRACE_S)
            except Exception:  # noqa: BLE001
                pass

        threading.Thread(
            target=_escalate, name=f"terminate-{handle.executor_id}",
            daemon=True,
        ).start()

    def poll(self) -> Dict[str, Optional[int]]:
        with self._lock:
            procs = dict(self._procs)
        out: Dict[str, Optional[int]] = {}
        for eid, proc in procs.items():
            rc = proc.poll()
            out[eid] = rc
            if rc is not None:
                with self._lock:
                    self._procs.pop(eid, None)
                self._remove_pid_file(eid)
        return out

    def close(self) -> None:
        """Terminate every child still running (scheduler shutdown)."""
        with self._lock:
            procs = dict(self._procs)
            self._procs.clear()
        for eid in procs:
            self._remove_pid_file(eid)
        for proc in procs.values():
            try:
                proc.terminate()
            except OSError:
                continue
        for proc in procs.values():
            try:
                proc.wait(TERMINATE_GRACE_S)
            except Exception:  # noqa: BLE001
                try:
                    proc.kill()
                except OSError:
                    pass


# ----------------------------------------------------------------- policy
@dataclass
class AutoscalerPolicy:
    """The knobs (``ballista.autoscaler.*``), validated through the same
    :class:`BallistaConfig` registry as every other setting."""

    min_executors: int = 1
    max_executors: int = 4
    scale_out_sustain_s: float = 3.0
    scale_in_idle_s: float = 15.0
    cooldown_s: float = 10.0
    launch_timeout_s: float = 60.0
    slo_burn_threshold: float = 0.0  # 0 = burn rate ignored

    @staticmethod
    def from_settings(settings: Dict[str, str]) -> "AutoscalerPolicy":
        cfg = BallistaConfig(dict(settings))  # fail fast on a bad knob
        return AutoscalerPolicy(
            min_executors=cfg._get(AUTOSCALER_MIN_EXECUTORS),
            max_executors=cfg._get(AUTOSCALER_MAX_EXECUTORS),
            scale_out_sustain_s=cfg._get(AUTOSCALER_SCALE_OUT_SUSTAIN_S),
            scale_in_idle_s=cfg._get(AUTOSCALER_SCALE_IN_IDLE_S),
            cooldown_s=cfg._get(AUTOSCALER_COOLDOWN_S),
            launch_timeout_s=cfg._get(AUTOSCALER_LAUNCH_TIMEOUT_S),
            slo_burn_threshold=cfg._get(AUTOSCALER_SLO_BURN_THRESHOLD),
        )

    @staticmethod
    def enabled_in(settings: Optional[Dict[str, str]]) -> bool:
        if not settings:
            return False
        cfg = BallistaConfig(dict(settings))
        return bool(cfg._get(AUTOSCALER_ENABLED))


# phases of one managed executor
LAUNCHING = "launching"
ALIVE = "alive"
DRAINING = "draining"


@dataclass
class _Managed:
    executor_id: str
    phase: str = LAUNCHING
    started_mono: float = 0.0
    drain_started_mono: float = 0.0
    drain_timeout_s: float = 0.0
    handle: Optional[ExecutorHandle] = None
    error: str = ""
    cancelled: bool = False  # timed out before launch() returned
    adopted: bool = False  # orphan re-adopted after a scheduler restart


class Autoscaler:
    """The closed loop.  ``tick()`` rides the scheduler's speculation
    timer thread; provider launches run on detached threads; everything
    that mutates scheduler state goes through the same front doors the
    operator uses (``decommission_executor``, ``executor_lost``)."""

    def __init__(
        self,
        server,  # SchedulerServer (not typed: import cycle)
        provider: ExecutorProvider,
        policy: Optional[AutoscalerPolicy] = None,
    ):
        self.server = server
        self.state = server.state
        self.provider = provider
        self.policy = policy or AutoscalerPolicy()
        self.slots_per_executor = max(1, int(getattr(provider, "task_slots", 1)))
        self._lock = threading.Lock()
        self._managed: Dict[str, _Managed] = {}
        self.desired = max(0, self.policy.min_executors)
        # orphan adoption (ISSUE 20): children that survived a scheduler
        # crash re-enter the managed set as LAUNCHING — they count
        # against actuation immediately (no double-launch storm while
        # they re-register) and flip ALIVE on their next heartbeat/
        # registration exactly like a fresh launch.  ``desired`` is
        # re-derived from the adopted fleet so the first tick neither
        # drains nor duplicates surviving capacity.
        adopted = []
        getter = getattr(provider, "adopted_ids", None)
        if callable(getter):
            try:
                adopted = list(getter())
            except Exception:  # noqa: BLE001 - provider may be sick
                log.exception("adopted_ids() failed; adopting nothing")
        if adopted:
            now = time.monotonic()
            for eid in adopted:
                self._managed[eid] = _Managed(
                    executor_id=eid,
                    phase=LAUNCHING,
                    started_mono=now,
                    handle=ExecutorHandle(eid),
                    adopted=True,
                )
            self.desired = min(
                self.policy.max_executors,
                max(self.policy.min_executors, len(adopted)),
            )
            log.info(
                "adopted %d surviving executor(s) %s; desired=%d",
                len(adopted), sorted(adopted), self.desired,
            )
            self.state.events.emit(
                "autoscale_decision",
                action="adopt",
                executors=sorted(adopted),
                desired=self.desired,
            )
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_scale_out = float("-inf")
        self._last_scale_in = float("-inf")
        self._consecutive_launch_failures = 0
        self._backoff_until = 0.0
        self._closed = False
        self._register_gauges()

    # ------------------------------------------------------------- gauges
    def _register_gauges(self) -> None:
        m = self.state.metrics
        m.gauge(
            "autoscaler_desired_executors",
            "the policy's current total-alive-executor target",
            fn=lambda: self.desired,
        )
        m.gauge(
            "autoscaler_alive_executors",
            "provider-managed executors registered and heartbeating",
            fn=lambda: self._count_phase(ALIVE),
        )
        m.gauge(
            "autoscaler_launching_executors",
            "provider launches started but not yet registered",
            fn=lambda: self._count_phase(LAUNCHING),
        )
        m.gauge(
            "autoscaler_draining_executors",
            "managed executors retiring through the drain path",
            fn=lambda: self._count_phase(DRAINING),
        )

    def _count_phase(self, phase: str) -> int:
        with self._lock:
            return sum(1 for r in self._managed.values() if r.phase == phase)

    # ----------------------------------------------------------- the tick
    def tick(self, now: Optional[float] = None) -> None:
        """One control-loop iteration.  Exceptions are contained (the
        timer thread wraps us too): a sick provider degrades the loop to
        a no-op, never the scheduler."""
        if self._closed:
            return
        now = time.monotonic() if now is None else now
        try:
            self._reconcile(now)
        except Exception:  # noqa: BLE001 - loop robustness over precision
            log.exception("autoscaler reconcile failed")
        try:
            self._decide(now)
        except Exception:  # noqa: BLE001
            log.exception("autoscaler decision failed")
        try:
            self._actuate(now)
        except Exception:  # noqa: BLE001
            log.exception("autoscaler actuation failed")

    # -------------------------------------------------------- reconcile
    def _reconcile(self, now: float) -> None:
        em = self.state.executor_manager
        alive = em.get_alive_executors()
        with self._lock:
            records = list(self._managed.values())

        for rec in records:
            if rec.phase != LAUNCHING:
                continue
            if rec.error:
                self._launch_failed(rec, rec.error)
                continue
            if rec.executor_id in alive:
                with self._lock:
                    rec.phase = ALIVE
                self._consecutive_launch_failures = 0
                em.record_launch_success(rec.executor_id)
                self.state.events.emit(
                    "executor_launched",
                    executor=rec.executor_id,
                    wait_s=round(now - rec.started_mono, 3),
                    adopted=rec.adopted,
                )
                log.info(
                    "executor %s registered %.1fs after launch",
                    rec.executor_id, now - rec.started_mono,
                )
                continue
            if now - rec.started_mono > self.policy.launch_timeout_s:
                rec.cancelled = True
                if rec.handle is not None:
                    self._safe_terminate(rec.handle)
                self._launch_failed(
                    rec,
                    f"launch timed out after {self.policy.launch_timeout_s:.0f}s",
                )

        # child process liveness: a crash is capacity loss; a draining
        # child's exit concludes its retirement
        try:
            statuses = self.provider.poll()
        except Exception as e:  # noqa: BLE001 - provider may be sick
            log.warning("provider poll failed: %s", e)
            statuses = {}
        for eid, rc in statuses.items():
            if rc is None:
                continue
            with self._lock:
                rec = self._managed.get(eid)
            if rec is None or rec.phase == LAUNCHING:
                # LAUNCHING exits are handled by the timeout/registration
                # race above next tick (the registration can still be in
                # flight when a fast child dies)
                if rec is not None:
                    rec.error = rec.error or f"process exited rc={rc}"
                continue
            if rec.phase == DRAINING or em.is_dead_executor(eid):
                self._retire(rec, rc, now)
            else:
                self._crashed(rec, rc)

        # a draining child that neither exited nor was declared lost gets
        # its process reaped once well past the drain budget
        for rec in records:
            if rec.phase != DRAINING or rec.handle is None:
                continue
            overdue = rec.drain_timeout_s + DRAIN_KILL_GRACE_S
            if now - rec.drain_started_mono > overdue:
                log.warning(
                    "draining executor %s still running %.0fs past its "
                    "budget; terminating the process", rec.executor_id,
                    now - rec.drain_started_mono - rec.drain_timeout_s,
                )
                self._safe_terminate(rec.handle)

    def _launch_failed(self, rec: _Managed, error: str) -> None:
        with self._lock:
            self._managed.pop(rec.executor_id, None)
        self._consecutive_launch_failures += 1
        # the existing consecutive-launch-failure machinery sees provider
        # failures exactly like LaunchTask failures (journal + quarantine
        # accounting); expulsion is moot for a never-registered id
        em = self.state.executor_manager
        em.record_launch_failure(rec.executor_id)
        em.take_pending_expulsions()  # never-registered: nothing to expel
        threshold = max(1, em.launch_failure_threshold)
        self.state.events.emit(
            "autoscale_decision",
            action="launch_failed",
            executor=rec.executor_id,
            error=error[:300],
            consecutive_failures=self._consecutive_launch_failures,
        )
        log.warning(
            "executor launch %s failed (%d consecutive): %s",
            rec.executor_id, self._consecutive_launch_failures, error,
        )
        if self._consecutive_launch_failures >= threshold:
            backoff = em.quarantine_backoff_s
            self._backoff_until = time.monotonic() + backoff
            self.state.events.emit(
                "autoscale_decision",
                action="launch_backoff",
                backoff_s=backoff,
                consecutive_failures=self._consecutive_launch_failures,
            )
            log.warning(
                "%d consecutive launch failures; suspending launches %.0fs",
                self._consecutive_launch_failures, backoff,
            )

    def _retire(self, rec: _Managed, rc: Optional[int], now: float) -> None:
        with self._lock:
            self._managed.pop(rec.executor_id, None)
        self.state.events.emit(
            "executor_retired",
            executor=rec.executor_id,
            drain_s=round(now - rec.drain_started_mono, 3)
            if rec.drain_started_mono else None,
            exit_code=rc,
        )
        log.info("executor %s retired (rc=%s)", rec.executor_id, rc)

    def _crashed(self, rec: _Managed, rc: Optional[int]) -> None:
        with self._lock:
            self._managed.pop(rec.executor_id, None)
        self.state.events.emit(
            "autoscale_decision",
            action="capacity_lost",
            executor=rec.executor_id,
            exit_code=rc,
        )
        log.warning(
            "managed executor %s exited unexpectedly (rc=%s); reporting "
            "loss and healing", rec.executor_id, rc,
        )
        # same front door as heartbeat expiry: rollback/re-point runs on
        # the event loop; the next actuation relaunches toward desired
        self.server.executor_lost(
            rec.executor_id, "executor process exited (autoscaler poll)"
        )

    def _safe_terminate(self, handle: ExecutorHandle) -> None:
        try:
            self.provider.terminate(handle)
        except Exception as e:  # noqa: BLE001
            log.warning("provider terminate(%s) failed: %s",
                        handle.executor_id, e)

    # ----------------------------------------------------------- decision
    def signals(self) -> Dict[str, float]:
        """The measured inputs, one read per tick (also the /api surface)."""
        state = self.state
        pending, running = state.task_manager.task_counts()
        em = state.executor_manager
        alive = em.get_alive_executors()
        draining = set(em.draining_executors())
        return {
            "queued_jobs": state.admission.queued_count(),
            "pending_tasks": pending,
            "running_tasks": running,
            "available_slots": em.available_slots(),
            "alive_total": len(alive),
            "alive_effective": len(alive - draining),
            "slo_burn_rate": state.slo.burn_rate(),
        }

    def _decide(self, now: float) -> None:
        p = self.policy
        sig = self.signals()
        deficit_slots = (
            max(0, sig["pending_tasks"] - sig["available_slots"])
            + sig["queued_jobs"]
        )
        burning = (
            p.slo_burn_threshold > 0
            and sig["slo_burn_rate"] >= p.slo_burn_threshold
        )
        pressure = deficit_slots > 0 or burning
        effective = int(sig["alive_effective"])
        launching = self._count_phase(LAUNCHING)

        if pressure:
            self._idle_since = None
            if self._pressure_since is None:
                self._pressure_since = now
            sustained_s = now - self._pressure_since
            if (
                sustained_s >= p.scale_out_sustain_s
                and now - self._last_scale_out >= p.cooldown_s
                and effective + launching < p.max_executors
            ):
                want = effective + launching + max(
                    1, math.ceil(deficit_slots / self.slots_per_executor)
                )
                target = min(p.max_executors, max(want, p.min_executors))
                if target > self.desired:
                    self._last_scale_out = now
                    self.desired = target
                    self.state.events.emit(
                        "autoscale_decision",
                        action="scale_out",
                        desired=self.desired,
                        scheduling_delay_s=round(sustained_s, 3),
                        deficit_slots=deficit_slots,
                        queued_jobs=sig["queued_jobs"],
                        slo_burn_rate=round(sig["slo_burn_rate"], 4),
                    )
                    log.info(
                        "scale-out: desired=%d (deficit %d slots, pressure "
                        "sustained %.1fs, burn %.2f)", self.desired,
                        deficit_slots, sustained_s, sig["slo_burn_rate"],
                    )
            return

        self._pressure_since = None
        idle = (
            sig["running_tasks"] == 0
            and sig["pending_tasks"] == 0
            and sig["queued_jobs"] == 0
        )
        if not idle:
            self._idle_since = None
            return
        if self._idle_since is None:
            self._idle_since = now
        idle_s = now - self._idle_since
        if (
            idle_s >= p.scale_in_idle_s
            and now - self._last_scale_in >= p.cooldown_s
            and effective > p.min_executors
            and self.desired > p.min_executors
        ):
            victim, unreplicated = self._pick_victim()
            if victim is None:
                return
            self._last_scale_in = now
            self.desired = max(p.min_executors, self.desired - 1)
            timeout = self.server.drain_timeout_s
            with self._lock:
                rec = self._managed.get(victim)
                if rec is not None:
                    rec.phase = DRAINING
                    rec.drain_started_mono = now
                    rec.drain_timeout_s = timeout
            self.state.events.emit(
                "autoscale_decision",
                action="scale_in",
                desired=self.desired,
                victim=victim,
                idle_s=round(idle_s, 3),
                unreplicated_bytes=unreplicated,
            )
            log.info(
                "scale-in: desired=%d, draining %s (%d un-replicated "
                "bytes, idle %.1fs)", self.desired, victim, unreplicated,
                idle_s,
            )
            self.server.decommission_executor(
                victim, reason="autoscaler scale-in", timeout_s=timeout
            )

    def _pick_victim(self) -> "tuple[Optional[str], int]":
        """Cheapest managed executor to retire: fewest un-replicated
        shuffle bytes still referenced by active jobs (those are what a
        drain must upload); ties break toward the newest launch so
        long-lived executors keep their warm caches."""
        em = self.state.executor_manager
        alive = em.get_alive_executors()
        with self._lock:
            candidates = [
                r for r in self._managed.values()
                if r.phase == ALIVE and r.executor_id in alive
                and not em.is_draining(r.executor_id)
            ]
        if not candidates:
            return None, 0
        by_executor = self.state.task_manager.unreplicated_shuffle_bytes()
        rec = min(
            candidates,
            key=lambda r: (by_executor.get(r.executor_id, 0), -r.started_mono),
        )
        return rec.executor_id, by_executor.get(rec.executor_id, 0)

    # ---------------------------------------------------------- actuation
    def _actuate(self, now: float) -> None:
        if now < self._backoff_until:
            return
        em = self.state.executor_manager
        alive = em.get_alive_executors()
        draining = set(em.draining_executors())
        effective = len(alive - draining)
        launching = self._count_phase(LAUNCHING)
        want = max(self.desired, self.policy.min_executors)
        while effective + launching < want:
            self._begin_launch(now)
            launching += 1

    def _begin_launch(self, now: float) -> None:
        eid = f"scale-{uuid.uuid4().hex[:10]}"
        rec = _Managed(executor_id=eid, started_mono=now)
        with self._lock:
            self._managed[eid] = rec
        spec = ExecutorSpec(
            executor_id=eid, task_slots=self.slots_per_executor
        )

        def _run() -> None:
            try:
                handle = self.provider.launch(spec)
            except Exception as e:  # noqa: BLE001 - journaled next tick
                rec.error = str(e) or repr(e)
                return
            late = False
            with self._lock:
                rec.handle = handle
                late = rec.cancelled
            if late:
                # launch() returned after the tick timed this attempt
                # out: the capacity was already re-requested, kill the
                # straggling process rather than double-launch
                self._safe_terminate(handle)

        threading.Thread(
            target=_run, name=f"autoscale-launch-{eid}", daemon=True
        ).start()
        log.info("launching executor %s (desired=%d)", eid, self.desired)

    # ------------------------------------------------------------ surface
    def snapshot(self) -> dict:
        """The /api/cluster/health autoscaler block: the provider's view
        (managed handles by phase) next to the policy state, so health
        counts reconcile against what is actually running."""
        with self._lock:
            phases: Dict[str, List[str]] = {}
            for rec in self._managed.values():
                phases.setdefault(rec.phase, []).append(rec.executor_id)
        return {
            "enabled": True,
            "desired": self.desired,
            "alive": len(phases.get(ALIVE, [])),
            "launching": len(phases.get(LAUNCHING, [])),
            "draining": len(phases.get(DRAINING, [])),
            "managed": {k: sorted(v) for k, v in phases.items()},
            "min_executors": self.policy.min_executors,
            "max_executors": self.policy.max_executors,
            "consecutive_launch_failures": self._consecutive_launch_failures,
            "launch_backoff_remaining_s": round(
                max(0.0, self._backoff_until - time.monotonic()), 3
            ),
        }

    def managed_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._managed)

    def scale_out_in_flight(self) -> bool:
        return self._count_phase(LAUNCHING) > 0

    def close(self) -> None:
        """Scheduler shutdown: stop ticking and reap every child (a
        LocalProcessProvider would otherwise leak subprocesses)."""
        self._closed = True
        with self._lock:
            handles = [
                r.handle for r in self._managed.values() if r.handle is not None
            ]
            self._managed.clear()
        for handle in handles:
            self._safe_terminate(handle)
        closer = getattr(self.provider, "close", None)
        if callable(closer):
            try:
                closer()
            except Exception:  # noqa: BLE001
                log.exception("provider close failed")
