"""Scheduler-driven speculative execution + task deadline reaper.

Ballista's staged shuffle execution runs a whole query at the speed of
its slowest task: one wedged worker or one degraded node holds a
partition — and the job — hostage until the executor heartbeat times out
(minutes).  This module closes that tail-latency gap with the two
mitigations a production fleet expects:

* **speculation** — once enough of a stage has finished
  (``ballista.speculation.min_completed_fraction``), a task running
  longer than ``multiplier × median(completed runtimes)`` (floored at
  ``min_runtime_seconds``) gets a duplicate attempt on a *different*
  executor; the first completion wins, commits its output locations, and
  the loser is cancelled — its late status is dropped as stale and never
  consumes failure budget (``ExecutionGraph._commit_winner``).
* **deadline reaping** — a "running" task older than
  ``ballista.task.timeout_seconds`` on a live-but-wedged executor is
  cancelled and re-queued through the normal transient path with a FREE
  attempt (staleness bump without budget burn), so a hung worker process
  can no longer hold a partition forever.

The :class:`SpeculationManager` owns the registry counters and the scan
body; the scan itself is triggered as a ``SpeculationScan`` event on the
scheduler's single event-loop thread (``query_stage_scheduler.py``) by a
timer in ``SchedulerServer`` — all graph mutations stay on that thread's
locking discipline.  Per-job policy comes from the session config at
submit (``ExecutionGraph._init_speculation_policy``); the scheduler
binary's ``--speculation-enabled`` / ``--task-timeout-seconds`` flags
force the machinery on for every session.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Tuple

log = logging.getLogger(__name__)


class SpeculationManager:
    """Periodic straggler/deadline scan over the active jobs.

    Constructed by :class:`~..scheduler.state.SchedulerState`; ``scan()``
    must run on the query-stage event-loop thread (it takes the same
    per-job entry locks as every other graph mutation).
    """

    def __init__(
        self,
        state,
        force_enabled: bool = False,
        force_task_timeout_s: float = 0.0,
    ):
        self.state = state
        self.force_enabled = force_enabled
        self.force_task_timeout_s = force_task_timeout_s
        # per-job monotonic last-scan anchor honoring the session's
        # ballista.speculation.interval_seconds (the scan thread ticks at
        # the scheduler-level period; slower sessions skip ticks)
        self._last_scan: Dict[str, float] = {}
        # speculative_launched/wins/wasted live on the TaskManager (the
        # dispatch/commit paths that actually observe them); the scan
        # only owns the reap counter
        self._timeouts = state.metrics.counter(
            "task_timeouts_total",
            "running tasks reaped past ballista.task.timeout_seconds",
        )

    # ------------------------------------------------------------- scan
    def scan(self) -> Tuple[List[Tuple[str, str]], int]:
        """Visit every active job's running stages: flag stragglers for
        duplicate dispatch, reap deadline-expired tasks, fan the queued
        CancelTasks out (pooled channels, best-effort).  Returns
        ``(job events, slots_wanted)`` — the push-mode caller mints one
        reservation per wanted slot (new speculation requests + reaped
        re-queues)."""
        tm = self.state.task_manager
        now = time.monotonic()
        events: List[Tuple[str, str]] = []
        slots_wanted = 0
        cancels: List[Tuple[str, object]] = []
        for job_id in tm.active_job_ids():
            entry = tm._entry(job_id)
            with entry.lock:
                graph = tm._load(job_id, entry)
                if graph is None:
                    continue
                interval = getattr(graph, "spec_interval_s", 1.0)
                last = self._last_scan.get(job_id, float("-inf"))
                if now - last < interval:
                    continue
                self._last_scan[job_id] = now
                out = graph.scan_speculation(
                    now,
                    force_enabled=self.force_enabled,
                    force_timeout_s=self.force_task_timeout_s,
                )
                cancels.extend(graph.take_pending_cancels())
                if not (
                    out["new_requests"] or out["timeouts"] or out["events"]
                ):
                    continue
                if out["timeouts"]:
                    self._timeouts.inc(out["timeouts"])
                slots_wanted += out["new_requests"]
                for ev in out["events"]:
                    if ev == "task_requeued":
                        tm._retries.inc()
                        slots_wanted += 1
                    events.append((job_id, ev))
                if out["new_requests"]:
                    log.info(
                        "job %s: flagged %d straggler(s) for speculation",
                        job_id,
                        out["new_requests"],
                    )
                tm._persist(graph)
        # forget jobs that left the cache (completed/failed/evicted)
        active = set(tm.active_job_ids())
        for job_id in list(self._last_scan):
            if job_id not in active:
                self._last_scan.pop(job_id, None)
        if cancels:
            tm.cancel_task_attempts(cancels)
        return events, slots_wanted
