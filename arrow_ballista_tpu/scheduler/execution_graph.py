"""Per-job DAG of stages.

Counterpart of the reference's ``scheduler/src/state/execution_graph.rs``:
tracks job status, drives stage transitions as task statuses arrive, hands
out tasks (`pop_next_task`), pushes completed map-output locations into
consumer stages (`update_stage_output_links`), and supports executor-loss
rollback (`reset_stages`).  Protobuf persistence follows the reference's
rule that Running stages are stored as Resolved so a restarted scheduler
re-dispatches in-flight work (`execution_graph.rs:867-920`).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from ..errors import SchedulerError
from ..exec.operators import ExecutionPlan
from ..proto import pb
from ..serde.scheduler_types import (
    ExecutorMetadata,
    PartitionId,
    PartitionLocation,
    PartitionStats,
    ShuffleWritePartition,
)
from ..shuffle import ShuffleWriterExec, UnresolvedShuffleExec
from .execution_stage import (
    CompletedStage,
    FailedStage,
    ResolvedStage,
    RunningStage,
    StageInput,
    TaskInfo,
    UnresolvedStage,
)
from .planner import DistributedPlanner, find_unresolved_shuffles

Stage = Union[UnresolvedStage, ResolvedStage, RunningStage, CompletedStage, FailedStage]


@dataclass
class Task:
    """A runnable task handed to an executor (reference:
    execution_graph.rs:1052-1058)."""

    session_id: str
    partition: PartitionId
    plan: ShuffleWriterExec
    output_partitioning: Optional[object]  # Partitioning of the shuffle write
    attempt: int = 0  # 0-based attempt counter, shipped in TaskDefinition
    # the job's trace id ("" = untraced/unsampled); shipped in
    # TaskDefinition so executor task spans stitch under the job trace
    trace_id: str = ""


DEFAULT_TASK_MAX_ATTEMPTS = 4
DEFAULT_STAGE_MAX_ATTEMPTS = 4


# Job status values
QUEUED = "queued"
RUNNING = "running"
COMPLETED = "completed"
FAILED = "failed"


class ExecutionGraph:
    def __init__(
        self,
        scheduler_id: str,
        job_id: str,
        session_id: str,
        plan: ExecutionPlan,
        work_dir: str = "/tmp/ballista-tpu",
        config=None,
    ):
        self.scheduler_id = scheduler_id
        self.job_id = job_id
        self.session_id = session_id
        self.status: str = QUEUED
        self.error: str = ""
        self.stages: Dict[int, Stage] = {}
        self.output_locations: List[PartitionLocation] = []
        self.task_max_attempts = (
            config.task_max_attempts if config is not None
            else DEFAULT_TASK_MAX_ATTEMPTS
        )
        self.stage_max_attempts = (
            config.stage_max_attempts if config is not None
            else DEFAULT_STAGE_MAX_ATTEMPTS
        )
        self.task_retries = 0  # transient-failure re-queues over job lifetime
        self.stage_reset_counts: Dict[int, int] = {}  # executor-loss resets
        # tracing: set by the scheduler at submit when the session has
        # ballista.obs.enabled (and the job is sampled); in-memory only —
        # a trace does not survive scheduler restart
        self.trace_id = ""
        self.submitted_unix_ns = time.time_ns()
        self.submitted_mono_ns = time.monotonic_ns()

        planner = DistributedPlanner(work_dir, config)
        stage_plans = planner.plan_query_stages(job_id, plan)
        self._final_stage_id = stage_plans[-1].stage_id
        self.output_partitions = stage_plans[-1].output_partitioning().n
        self.stages = _build_stages(stage_plans)

    # ------------------------------------------------------------- intro
    @property
    def final_stage_id(self) -> int:
        return self._final_stage_id

    def stage_count(self) -> int:
        return len(self.stages)

    def is_successful(self) -> bool:
        return self.status == COMPLETED

    def is_complete(self) -> bool:
        return all(isinstance(s, CompletedStage) for s in self.stages.values())

    def available_tasks(self) -> int:
        return sum(
            s.available_tasks()
            for s in self.stages.values()
            if isinstance(s, RunningStage)
        )

    # ------------------------------------------------------------ revive
    def revive(self) -> bool:
        """Resolve every resolvable stage and start every resolved stage
        (reference: execution_graph.rs:169-193).  Returns True if anything
        changed."""
        changed = False
        for sid, stage in list(self.stages.items()):
            if isinstance(stage, UnresolvedStage) and stage.resolvable():
                self.stages[sid] = stage.to_resolved()
                changed = True
        for sid, stage in list(self.stages.items()):
            if isinstance(stage, ResolvedStage):
                self.stages[sid] = stage.to_running()
                changed = True
        if changed and self.status == QUEUED:
            self.status = RUNNING
        return changed

    # ----------------------------------------------------------- dispatch
    def pop_next_task(
        self, executor_id: str, allow_excluded: bool = False
    ) -> Optional[Task]:
        """Find a Running stage with an unclaimed partition, mark it
        running on ``executor_id`` and return it
        (reference: execution_graph.rs:418-471).

        A partition whose last transient failure happened on
        ``executor_id`` is skipped (the retry must land elsewhere) unless
        ``allow_excluded`` — the liveness escape hatch when no other
        executor exists (``task_manager.fill_reservations``)."""
        for sid in sorted(self.stages):
            stage = self.stages[sid]
            if not isinstance(stage, RunningStage):
                continue
            for p, t in enumerate(stage.task_statuses):
                if t is not None:
                    continue
                if (
                    not allow_excluded
                    and stage.task_exclusions.get(p) == executor_id
                ):
                    continue
                attempt = stage.task_attempts.get(p, 0)
                pid = PartitionId(self.job_id, sid, p)
                stage.task_statuses[p] = TaskInfo(
                    pid, "running", executor_id, attempt=attempt
                )
                return Task(
                    self.session_id,
                    pid,
                    stage.plan,
                    stage.plan.shuffle_output_partitioning,
                    attempt,
                    trace_id=self.trace_id,
                )
        return None

    def reset_task_status(
        self, partition: PartitionId, exclude_executor: str = ""
    ) -> None:
        """Return a handed-out task to the pool (launch failed / reservation
        cancelled).  ``exclude_executor`` keeps the re-dispatch off the
        executor the launch just failed against."""
        stage = self.stages.get(partition.stage_id)
        if isinstance(stage, RunningStage):
            t = stage.task_statuses[partition.partition_id]
            if t is not None and t.state == "running":
                stage.task_statuses[partition.partition_id] = None
                if exclude_executor:
                    stage.task_exclusions[partition.partition_id] = (
                        exclude_executor
                    )

    def reset_running_tasks(self, executor_id: str) -> int:
        """Re-queue every task currently running on ``executor_id`` with
        the executor excluded (quarantine: the host is sick but its past
        shuffle output is still servable, so no stage rollback).  Returns
        the number of tasks reset.

        The attempt counter is bumped: the quarantined executor was never
        told to stop, so its late status for the superseded attempt must
        fail the stale-attempt guards instead of double-completing or
        double-failing the partition."""
        n = 0
        for stage in self.stages.values():
            if not isinstance(stage, RunningStage):
                continue
            for p, t in enumerate(stage.task_statuses):
                if t is not None and t.state == "running" and t.executor_id == executor_id:
                    stage.task_statuses[p] = None
                    stage.task_exclusions[p] = executor_id
                    stage.task_attempts[p] = stage.task_attempts.get(p, 0) + 1
                    self.task_retries += 1
                    n += 1
        return n

    # ------------------------------------------------------ status updates
    def update_task_status(
        self,
        info: TaskInfo,
        executor: Optional[ExecutorMetadata] = None,
    ) -> List[str]:
        """Apply one task status; returns job-level events out of
        ("job_updated", "job_completed", "job_failed")
        (reference: execution_graph.rs:197-318)."""
        stage = self.stages.get(info.partition_id.stage_id)
        if stage is None:
            raise SchedulerError(
                f"job {self.job_id}: unknown stage {info.partition_id.stage_id}"
            )
        if not isinstance(stage, RunningStage):
            # late status for a stage already rolled back or completed
            return []

        events: List[str] = []
        if info.state == "failed":
            return self._on_task_failed(stage, info)

        p = info.partition_id.partition_id
        if info.attempt < stage.task_attempts.get(p, 0):
            # late status from a superseded attempt (the task was reset by
            # quarantine and re-dispatched): accepting it would overwrite
            # the live attempt's status — and a stale completion would
            # propagate the same partition's output twice
            return []
        stage.update_task_status(info)
        if info.state == "completed":
            if info.fetch_retries:
                stage.task_fetch_retries[p] = info.fetch_retries
            stage.update_task_metrics(info)
            if executor is not None:
                self._propagate_output(stage, info, executor)
            if stage.is_completed():
                sid = info.partition_id.stage_id
                completed = stage.to_completed()
                self.stages[sid] = completed
                from .display import print_stage_metrics

                print_stage_metrics(
                    self.job_id, sid, completed.plan, completed.stage_metrics
                )
                for link in completed.output_links:
                    consumer = self.stages.get(link)
                    if isinstance(consumer, UnresolvedStage):
                        consumer.complete_input(sid)
                if sid == self._final_stage_id:
                    self._collect_job_output(completed, executor)
                    self.status = COMPLETED
                    events.append("job_completed")
                else:
                    self.revive()
                    events.append("job_updated")
            else:
                events.append("job_updated")
        return events

    def _on_task_failed(self, stage: RunningStage, info: TaskInfo) -> List[str]:
        """Bounded retry with failure classification (the reference fails
        the whole job on the first failed task; production cannot):
        transient failures re-queue the partition — excluded from the
        executor that just failed it — until ``ballista.task.max_attempts``
        is spent, then the job fails with the accumulated error history.
        Fatal (plan/serde/SQL) errors fail fast on attempt 1."""
        from .failure import FATAL, classify_failure

        sid = info.partition_id.stage_id
        p = info.partition_id.partition_id
        current = stage.task_attempts.get(p, 0)
        if info.attempt < current:
            # late report from an attempt already superseded (e.g. the
            # task was reset by quarantine and re-ran elsewhere)
            return []
        if info.fetch_retries:
            stage.task_fetch_retries[p] = info.fetch_retries
        error = info.error or "task failed"
        history = stage.task_failures.setdefault(p, [])
        history.append(
            f"attempt {current} on {info.executor_id or '<unknown>'}: {error}"
        )
        kind = classify_failure(error)
        if kind != FATAL and current + 1 < self.task_max_attempts:
            stage.task_attempts[p] = current + 1
            if info.executor_id:
                stage.task_exclusions[p] = info.executor_id
            stage.task_statuses[p] = None
            self.task_retries += 1
            return ["task_retried"]

        detail = "; ".join(history)
        reason = (
            "fatal error"
            if kind == FATAL
            else f"exhausted {self.task_max_attempts} attempts"
        )
        self.stages[sid] = stage.to_failed(detail)
        self.status = FAILED
        self.error = (
            f"stage {sid} task {p} failed ({reason}): {detail}"
        )
        return ["job_failed"]

    def _propagate_output(
        self, stage: RunningStage, info: TaskInfo, executor: ExecutorMetadata
    ) -> None:
        """Push one completed map task's shuffle partitions into consumer
        stages' inputs (reference: execution_graph.rs:320-369)."""
        locations = [
            PartitionLocation(
                PartitionId(self.job_id, stage.stage_id, p.partition_id),
                executor,
                PartitionStats(p.num_rows, p.num_batches, p.num_bytes),
                p.path,
            )
            for p in info.partitions
        ]
        for link in stage.output_links:
            consumer = self.stages.get(link)
            if isinstance(consumer, UnresolvedStage):
                consumer.add_input_partitions(stage.stage_id, locations)

    def _collect_job_output(
        self, stage: CompletedStage, executor: Optional[ExecutorMetadata]
    ) -> None:
        self.output_locations = []
        for t in stage.task_statuses:
            if t is None:
                continue
            meta = executor
            for p in t.partitions:
                self.output_locations.append(
                    PartitionLocation(
                        PartitionId(self.job_id, stage.stage_id, p.partition_id),
                        meta if meta is not None else ExecutorMetadata("", "", 0),
                        PartitionStats(p.num_rows, p.num_batches, p.num_bytes),
                        p.path,
                    )
                )

    # ------------------------------------------------------------- failure
    def fail_job(self, error: str) -> None:
        self.status = FAILED
        self.error = error

    def reset_stages(self, executor_id: str) -> int:
        """Executor-loss rollback (reference: execution_graph.rs:499-622):

        * clear running tasks assigned to the executor;
        * strip its partition locations from unresolved stages' inputs;
        * roll Running/Resolved stages whose inputs lost data back to
          UnResolved;
        * re-run Completed stages whose map outputs were lost.

        Returns the number of affected stages."""
        affected = set()

        # 1) running stages: reset that executor's tasks
        for sid, stage in list(self.stages.items()):
            if isinstance(stage, RunningStage):
                if stage.reset_tasks(executor_id):
                    affected.add(sid)

        # 2) strip lost input locations everywhere; find consumers that lost
        #    data and must re-resolve
        rollback_consumers = set()
        for sid, stage in list(self.stages.items()):
            if isinstance(stage, UnresolvedStage):
                before = _locations_of(stage, executor_id)
                if before:
                    stage.remove_input_partitions(executor_id)
                    affected.add(sid)
            elif isinstance(stage, (ResolvedStage, RunningStage)):
                lost = any(
                    any(
                        l.executor_meta.id == executor_id
                        for locs in inp.partition_locations.values()
                        for l in locs
                    )
                    for inp in stage.inputs.values()
                )
                if lost:
                    rollback_consumers.add(sid)

        # 3) roll back consumers to unresolved
        rerun_producers = set()
        for sid in rollback_consumers:
            stage = self.stages[sid]
            if isinstance(stage, RunningStage):
                stage = stage.to_resolved()
            assert isinstance(stage, ResolvedStage)
            unresolved = stage.to_unresolved()
            unresolved.remove_input_partitions(executor_id)
            # any input stage whose data was lost must re-run
            for in_sid, inp in unresolved.inputs.items():
                if not inp.complete:
                    rerun_producers.add(in_sid)
            self.stages[sid] = unresolved
            affected.add(sid)

        # 4) completed producers with lost map output re-run their lost tasks
        for sid in sorted(rerun_producers):
            stage = self.stages.get(sid)
            if isinstance(stage, CompletedStage):
                running = stage.to_running()
                running.reset_tasks(executor_id)
                self.stages[sid] = running
                affected.add(sid)

        # 5) bound the rollback: a stage reset more than
        #    ballista.stage.max_attempts times means the cluster is
        #    flapping faster than the job can make progress — fail it
        #    with the reset ledger instead of looping forever
        for sid in affected:
            count = self.stage_reset_counts.get(sid, 0) + 1
            self.stage_reset_counts[sid] = count
            if count >= self.stage_max_attempts and self.status != FAILED:
                self.status = FAILED
                self.error = (
                    f"stage {sid} reset {count} times after executor loss "
                    f"(last: {executor_id}); exceeded "
                    f"ballista.stage.max_attempts={self.stage_max_attempts}"
                )
        if self.status == FAILED:
            return len(affected)

        if affected and self.status == COMPLETED:
            self.status = RUNNING
        self.revive()
        return len(affected)

    # -------------------------------------------------------- persistence
    def encode(self) -> bytes:
        from ..serde import BallistaCodec

        g = pb.ExecutionGraphProto()
        g.job_id = self.job_id
        g.session_id = self.session_id
        g.scheduler_id = self.scheduler_id
        g.output_partitions = self.output_partitions
        g.task_max_attempts = self.task_max_attempts
        g.stage_max_attempts = self.stage_max_attempts
        g.task_retries = self.task_retries
        for sid in sorted(self.stage_reset_counts):
            g.stage_reset_ids.append(sid)
            g.stage_reset_counts.append(self.stage_reset_counts[sid])
        if self.status == QUEUED:
            g.status.queued.SetInParent()
        elif self.status == RUNNING:
            g.status.running.SetInParent()
        elif self.status == FAILED:
            g.status.failed.error = self.error
        else:
            for loc in self.output_locations:
                g.status.completed.partition_location.add().CopyFrom(loc.to_proto())
        for sid in sorted(self.stages):
            stage = self.stages[sid]
            sp = g.stages.add()
            if isinstance(stage, RunningStage):
                stage = stage.to_resolved()  # re-dispatch on restart
            if isinstance(stage, UnresolvedStage):
                sp.unresolved.stage_id = sid
                sp.unresolved.plan = BallistaCodec.encode_physical(stage.plan)
                sp.unresolved.output_links.extend(stage.output_links)
                _encode_inputs(sp.unresolved.inputs, stage.inputs)
            elif isinstance(stage, ResolvedStage):
                sp.resolved.stage_id = sid
                sp.resolved.partitions = stage.partitions
                sp.resolved.plan = BallistaCodec.encode_physical(stage.plan)
                sp.resolved.output_links.extend(stage.output_links)
                _encode_inputs(sp.resolved.inputs, stage.inputs)
            elif isinstance(stage, CompletedStage):
                sp.completed.stage_id = sid
                sp.completed.partitions = stage.partitions
                sp.completed.plan = BallistaCodec.encode_physical(stage.plan)
                sp.completed.output_links.extend(stage.output_links)
                _encode_inputs(sp.completed.inputs, stage.inputs)
                # merged operator metrics survive completion: the REST
                # detail and /api/jobs/{id}/profile read them from the
                # persisted graph once the cache entry is evicted
                for op, vals in stage.stage_metrics.items():
                    m = sp.completed.stage_metrics.add()
                    m.operator_name = op
                    for k, v in vals.items():
                        m.values[k] = int(v)
                for t in stage.task_statuses:
                    if t is None:
                        continue
                    ts = sp.completed.task_statuses.add()
                    ts.task_id.CopyFrom(t.partition_id.to_proto())
                    ts.attempt = stage.task_attempts.get(
                        t.partition_id.partition_id, t.attempt
                    )
                    ts.fetch_retries = stage.task_fetch_retries.get(
                        t.partition_id.partition_id, t.fetch_retries
                    )
                    ts.completed.executor_id = t.executor_id
                    for p in t.partitions:
                        ts.completed.partitions.add().CopyFrom(p.to_proto())
            elif isinstance(stage, FailedStage):
                sp.failed.stage_id = sid
                sp.failed.partitions = stage.partitions
                sp.failed.plan = BallistaCodec.encode_physical(stage.plan)
                sp.failed.output_links.extend(stage.output_links)
                sp.failed.error = stage.error
        return g.SerializeToString()

    @classmethod
    def decode(cls, data: bytes, work_dir: str = "/tmp/ballista-tpu") -> "ExecutionGraph":
        from ..serde import BallistaCodec

        g = pb.ExecutionGraphProto.FromString(data)
        self = cls.__new__(cls)
        self.scheduler_id = g.scheduler_id
        self.job_id = g.job_id
        self.session_id = g.session_id
        self.trace_id = ""  # traces don't survive restart/adoption
        self.submitted_unix_ns = time.time_ns()
        self.submitted_mono_ns = time.monotonic_ns()
        self.output_partitions = g.output_partitions
        self.output_locations = []
        self.error = ""
        # restart/HA adoption must keep the session's bounds and the spent
        # budgets — a fresh budget per failover would unbound the loops
        self.task_max_attempts = g.task_max_attempts or DEFAULT_TASK_MAX_ATTEMPTS
        self.stage_max_attempts = g.stage_max_attempts or DEFAULT_STAGE_MAX_ATTEMPTS
        self.task_retries = g.task_retries
        self.stage_reset_counts = dict(
            zip(g.stage_reset_ids, g.stage_reset_counts)
        )
        which = g.status.WhichOneof("status")
        if which == "queued":
            self.status = QUEUED
        elif which == "running":
            self.status = RUNNING
        elif which == "failed":
            self.status = FAILED
            self.error = g.status.failed.error
        else:
            self.status = COMPLETED
            self.output_locations = [
                PartitionLocation.from_proto(l)
                for l in g.status.completed.partition_location
            ]
        self.stages = {}
        max_sid = 0
        for sp in g.stages:
            which = sp.WhichOneof("stage")
            if which == "unresolved":
                s = sp.unresolved
                stage: Stage = UnresolvedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    _decode_inputs(s.inputs),
                )
            elif which == "resolved":
                s = sp.resolved
                stage = ResolvedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    _decode_inputs(s.inputs),
                )
            elif which == "completed":
                s = sp.completed
                statuses: List[Optional[TaskInfo]] = [None] * s.partitions
                attempts: Dict[int, int] = {}
                fetch_retries: Dict[int, int] = {}
                for ts in s.task_statuses:
                    pid = PartitionId.from_proto(ts.task_id)
                    statuses[pid.partition_id] = TaskInfo(
                        pid,
                        "completed",
                        ts.completed.executor_id,
                        partitions=[
                            ShuffleWritePartition.from_proto(p)
                            for p in ts.completed.partitions
                        ],
                        attempt=ts.attempt,
                        fetch_retries=ts.fetch_retries,
                    )
                    if ts.attempt:
                        attempts[pid.partition_id] = ts.attempt
                    if ts.fetch_retries:
                        fetch_retries[pid.partition_id] = ts.fetch_retries
                stage = CompletedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    _decode_inputs(s.inputs),
                    statuses,
                    stage_metrics={
                        m.operator_name: dict(m.values)
                        for m in s.stage_metrics
                    },
                    task_attempts=attempts,
                    task_fetch_retries=fetch_retries,
                )
            else:
                s = sp.failed
                stage = FailedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    s.error,
                )
            self.stages[stage.stage_id] = stage
            max_sid = max(max_sid, stage.stage_id)
        self._final_stage_id = max_sid
        return self


def _encode_inputs(out, inputs: Dict[int, StageInput]) -> None:
    for sid, inp in inputs.items():
        m = out.add()
        m.stage_id = sid
        m.complete = inp.complete
        for locs in inp.partition_locations.values():
            for l in locs:
                m.partition_locations.add().CopyFrom(l.to_proto())


def _decode_inputs(msgs) -> Dict[int, StageInput]:
    out: Dict[int, StageInput] = {}
    for m in msgs:
        inp = StageInput(complete=m.complete)
        for l in m.partition_locations:
            inp.add_partition(PartitionLocation.from_proto(l))
        out[m.stage_id] = inp
    return out


def _locations_of(stage: UnresolvedStage, executor_id: str) -> int:
    return sum(
        1
        for inp in stage.inputs.values()
        for locs in inp.partition_locations.values()
        for l in locs
        if l.executor_meta.id == executor_id
    )


def _build_stages(stage_plans: List[ShuffleWriterExec]) -> Dict[int, Stage]:
    """Infer the DAG from UnresolvedShuffleExec leaves
    (reference: ExecutionStageBuilder, execution_graph.rs:941-1038)."""
    dependencies: Dict[int, List[int]] = {}  # stage -> stages it reads
    for sp in stage_plans:
        dependencies[sp.stage_id] = [
            sh.stage_id for sh in find_unresolved_shuffles(sp)
        ]

    output_links: Dict[int, List[int]] = {sp.stage_id: [] for sp in stage_plans}
    for consumer, producers in dependencies.items():
        for p in producers:
            output_links[p].append(consumer)

    stages: Dict[int, Stage] = {}
    for sp in stage_plans:
        inputs = {p: StageInput() for p in dependencies[sp.stage_id]}
        if inputs:
            stages[sp.stage_id] = UnresolvedStage(
                sp.stage_id, sp, output_links[sp.stage_id], inputs
            )
        else:
            # leaf stage: immediately resolvable
            stages[sp.stage_id] = ResolvedStage(
                sp.stage_id, sp, output_links[sp.stage_id], {}
            )
    return stages
