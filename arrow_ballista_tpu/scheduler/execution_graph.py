"""Per-job DAG of stages.

Counterpart of the reference's ``scheduler/src/state/execution_graph.rs``:
tracks job status, drives stage transitions as task statuses arrive, hands
out tasks (`pop_next_task`), pushes completed map-output locations into
consumer stages (`update_stage_output_links`), and supports executor-loss
rollback (`reset_stages`).  Protobuf persistence follows the reference's
rule that Running stages are stored as Resolved so a restarted scheduler
re-dispatches in-flight work (`execution_graph.rs:867-920`).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from ..errors import SchedulerError
from ..exec.operators import ExecutionPlan
from ..proto import pb
from ..serde.scheduler_types import (
    ExecutorMetadata,
    PartitionId,
    PartitionLocation,
    PartitionStats,
    ShuffleWritePartition,
)
from ..shuffle import ShuffleWriterExec, UnresolvedShuffleExec
from .execution_stage import (
    CompletedStage,
    FailedStage,
    ResolvedStage,
    RunningStage,
    StageInput,
    TaskInfo,
    UnresolvedStage,
)
from .planner import DistributedPlanner, find_unresolved_shuffles

Stage = Union[UnresolvedStage, ResolvedStage, RunningStage, CompletedStage, FailedStage]


@dataclass
class Task:
    """A runnable task handed to an executor (reference:
    execution_graph.rs:1052-1058)."""

    session_id: str
    partition: PartitionId
    plan: ShuffleWriterExec
    output_partitioning: Optional[object]  # Partitioning of the shuffle write
    attempt: int = 0  # 0-based attempt counter, shipped in TaskDefinition
    # the job's trace id ("" = untraced/unsampled); shipped in
    # TaskDefinition so executor task spans stitch under the job trace
    trace_id: str = ""
    # scheduler-launched duplicate of a straggling partition (same
    # attempt number as the primary; first completion wins)
    speculative: bool = False
    # ballista.task.timeout_seconds at dispatch (0 = none); informational
    # for the executor — the scheduler's scan enforces it
    timeout_seconds: float = 0.0


DEFAULT_TASK_MAX_ATTEMPTS = 4
DEFAULT_STAGE_MAX_ATTEMPTS = 4


# Job status values
QUEUED = "queued"
RUNNING = "running"
COMPLETED = "completed"
FAILED = "failed"


class ExecutionGraph:
    def __init__(
        self,
        scheduler_id: str,
        job_id: str,
        session_id: str,
        plan: ExecutionPlan,
        work_dir: str = "/tmp/ballista-tpu",
        config=None,
    ):
        self.scheduler_id = scheduler_id
        self.job_id = job_id
        self.session_id = session_id
        self.status: str = QUEUED
        self.error: str = ""
        self.stages: Dict[int, Stage] = {}
        self.output_locations: List[PartitionLocation] = []
        self.task_max_attempts = (
            config.task_max_attempts if config is not None
            else DEFAULT_TASK_MAX_ATTEMPTS
        )
        self.stage_max_attempts = (
            config.stage_max_attempts if config is not None
            else DEFAULT_STAGE_MAX_ATTEMPTS
        )
        self.task_retries = 0  # transient-failure re-queues over job lifetime
        self.stage_reset_counts: Dict[int, int] = {}  # executor-loss resets
        # ballista.shuffle.external_path: lets executor-loss handling
        # re-point lost locations at external replicas (probe-derived for
        # drain-time uploads) instead of recomputing
        self.external_shuffle_path = (
            config.shuffle_external_path if config is not None else ""
        )
        # speculative execution + deadline policy from the session config
        # (scheduler flags can force-enable; see scheduler/speculation.py).
        # In-memory only — a restarted scheduler re-derives nothing here
        # (Running stages persist as Resolved, so timing state is gone).
        self._init_speculation_policy(config)
        # locality-aware placement (ballista.shuffle.locality_*): prefer
        # putting reduce tasks on the hosts holding the most bytes of
        # their input partitions, waiting up to locality_wait_s before
        # any host may take them.  In-memory only, like speculation — a
        # recovered graph re-dispatches location-blind until its stages
        # re-resolve.
        self._init_locality_policy(config)
        # multi-tenant admission (scheduler/admission.py): the pool and
        # lane this job belongs to.  Persisted (tenant_json) so restart
        # and HA adoption re-register the job with the admission
        # controller's per-pool concurrency accounting, and so
        # fill_reservations can keep ordering dispatch by fair share.
        self._init_tenant(config)
        # streaming pipelined execution (ballista.shuffle.pipelined):
        # streamable consumer stages start on partial map output, tailing
        # the scheduler's per-producer shuffle-location feed.  In-memory
        # only — partially-resolved stages persist as Unresolved, so a
        # restarted scheduler re-resolves against real state.
        self._init_pipelining(config)
        # adaptive query execution (scheduler/adaptive.py): persisted in
        # the graph proto so restart/HA adoption replays decisions for
        # stages that resolve after the failover
        from .adaptive import AqePolicy

        self.aqe_policy = AqePolicy.from_config(config)
        # CancelTasks fan-out queue: (executor_id, PartitionId) of losing
        # duplicate attempts / reaped deadline-timeouts, drained by the
        # TaskManager after graph mutations commit
        self.pending_cancels: List[tuple] = []
        # structured journal queue: lifecycle events recorded while
        # mutating the graph ({"kind": ..., **fields}); TaskManager's
        # _persist drains them into the EventJournal with job/trace ids
        # attached (drained even when the journal is disabled, so the
        # list never grows unbounded)
        self.pending_events: List[dict] = []
        # wasted-duplicate count not yet flushed into the scheduler's
        # registry counter (TaskManager._persist drains it, so every
        # drop site — commit, failure, reset, reap — reconciles with the
        # per-stage spec_stats rollup)
        self.spec_wasted_pending = 0
        # plan-fingerprint cache (scheduler/plan_cache.py): stages served
        # straight from cached shuffle output (sid -> fingerprint) and
        # stages elided because every consumer is served/elided (revive
        # skips them — they never dispatch).  Persisted (cache_json) so
        # restart/HA adoption keeps skipping the elided subtree instead
        # of waiting forever on inputs nobody will produce.
        self.cache_served: Dict[int, str] = {}
        self.cache_elided: set = set()
        # fingerprints whose cached files turned out to be lost; drained
        # by the TaskManager (like pending_cancels) to evict the entries
        self.pending_cache_invalidations: List[str] = []
        # tracing: set by the scheduler at submit when the session has
        # ballista.obs.enabled (and the job is sampled); in-memory only —
        # a trace does not survive scheduler restart
        self.trace_id = ""
        self.submitted_unix_ns = time.time_ns()
        self.submitted_mono_ns = time.monotonic_ns()

        planner = DistributedPlanner(work_dir, config)
        stage_plans = planner.plan_query_stages(job_id, plan)
        self._final_stage_id = stage_plans[-1].stage_id
        self.output_partitions = stage_plans[-1].output_partitioning().n
        self.stages = _build_stages(stage_plans)
        # query-doctor anchors (ISSUE 13): distributed-planning duration,
        # and leaf stages are dispatchable the moment the graph exists
        self.planning_ns = time.monotonic_ns() - self.submitted_mono_ns
        now_ns = time.time_ns()
        for stage in self.stages.values():
            if isinstance(stage, ResolvedStage):
                stage.ready_unix_ns = now_ns

    def _init_speculation_policy(self, config) -> None:
        if config is not None:
            self.spec_enabled = config.speculation_enabled
            self.spec_interval_s = config.speculation_interval_seconds
            self.spec_multiplier = config.speculation_multiplier
            self.spec_min_completed_fraction = (
                config.speculation_min_completed_fraction
            )
            self.spec_min_runtime_s = config.speculation_min_runtime_seconds
            self.spec_max_copies_per_stage = (
                config.speculation_max_copies_per_stage
            )
            self.task_timeout_s = config.task_timeout_seconds
        else:
            self.spec_enabled = False
            self.spec_interval_s = 1.0
            self.spec_multiplier = 1.5
            self.spec_min_completed_fraction = 0.75
            self.spec_min_runtime_s = 1.0
            self.spec_max_copies_per_stage = 2
            self.task_timeout_s = 0.0

    def _init_locality_policy(self, config) -> None:
        if config is not None:
            self.locality_enabled = config.shuffle_locality_enabled
            self.locality_wait_s = config.shuffle_locality_wait_seconds
        else:
            self.locality_enabled = False
            self.locality_wait_s = 0.0

    def _init_tenant(self, config) -> None:
        if config is not None:
            self.admission_enabled = config.admission_enabled
            self.tenant_pool = (config.tenant_id or "").strip() or "default"
            self.tenant_priority = config.tenant_priority
        else:
            self.admission_enabled = False
            self.tenant_pool = "default"
            self.tenant_priority = "batch"

    def _init_pipelining(self, config) -> None:
        if config is not None:
            self.pipelined_enabled = config.shuffle_pipelined
            self.pipelined_min_fraction = config.shuffle_pipelined_min_fraction
        else:
            self.pipelined_enabled = False
            self.pipelined_min_fraction = 0.25
        # producer stage id -> {"locations": [PartitionLocation] (append-
        # only, committed winners only), "complete": bool, "epoch": int}.
        # The executor-side delta store mirrors it (push notifications in
        # push mode, GetShuffleLocationDelta polls in pull mode).
        self.shuffle_feeds: Dict[int, dict] = {}
        # epoch survives feed invalidation (executor-loss rollback): a
        # recreated feed starts at epoch+1 so executors' stale mirrors
        # reset instead of merging two generations of locations
        self.feed_epochs: Dict[int, int] = {}
        # queued feed updates for the push fan-out; drained by the
        # TaskManager after graph mutations commit (like pending_cancels)
        self.pending_feed_deltas: List[dict] = []

    def take_pending_feed_deltas(self) -> List[dict]:
        out, self.pending_feed_deltas = self.pending_feed_deltas, []
        return out

    def take_pending_cancels(self) -> List[tuple]:
        out, self.pending_cancels = self.pending_cancels, []
        return out

    def take_pending_cache_invalidations(self) -> List[str]:
        out, self.pending_cache_invalidations = (
            self.pending_cache_invalidations, [],
        )
        return out

    def take_pending_events(self) -> List[dict]:
        out, self.pending_events = self.pending_events, []
        return out

    def _journal(self, kind: str, **fields) -> None:
        self.pending_events.append({"kind": kind, **fields})

    def take_spec_wasted(self) -> int:
        n, self.spec_wasted_pending = self.spec_wasted_pending, 0
        return n

    # ------------------------------------------------------------- intro
    @property
    def final_stage_id(self) -> int:
        return self._final_stage_id

    def stage_count(self) -> int:
        return len(self.stages)

    def is_successful(self) -> bool:
        return self.status == COMPLETED

    def is_complete(self) -> bool:
        return all(
            isinstance(s, CompletedStage)
            for sid, s in self.stages.items()
            if sid not in self.cache_elided
        )

    def available_tasks(self) -> int:
        return sum(
            s.available_tasks()
            for s in self.stages.values()
            if isinstance(s, RunningStage)
        )

    def running_tasks(self) -> int:
        """Tasks currently dispatched (primary + speculative copies) —
        the slot-saturation input for cluster telemetry."""
        n = 0
        for s in self.stages.values():
            if isinstance(s, RunningStage):
                n += sum(
                    1
                    for t in s.task_statuses
                    if t is not None and t.state == "running"
                )
                n += sum(
                    1
                    for t in s.speculative_statuses.values()
                    if t.state == "running"
                )
        return n

    def running_tasks_by_executor(self) -> Dict[str, int]:
        """Dispatched tasks grouped by the executor running them — the
        ground truth the restart-time slot reconcile rebuilds the durable
        slot counts from."""
        per: Dict[str, int] = {}
        for s in self.stages.values():
            if not isinstance(s, RunningStage):
                continue
            for t in s.task_statuses:
                if t is not None and t.state == "running" and t.executor_id:
                    per[t.executor_id] = per.get(t.executor_id, 0) + 1
            for t in s.speculative_statuses.values():
                if t.state == "running" and t.executor_id:
                    per[t.executor_id] = per.get(t.executor_id, 0) + 1
        return per

    # ------------------------------------------------------------ revive
    def revive(self) -> bool:
        """Resolve every resolvable stage and start every resolved stage
        (reference: execution_graph.rs:169-193).  Returns True if anything
        changed.

        The moment a stage becomes resolvable every producer has
        reported exact per-partition output sizes — the one window where
        re-planning is free (nothing dispatched yet), so the AQE hook
        runs here, just before ``to_resolved()``."""
        changed = False
        for sid, stage in list(self.stages.items()):
            if sid in self.cache_elided:
                continue  # every consumer is cache-served: never dispatch
            if isinstance(stage, UnresolvedStage) and stage.resolvable():
                self._maybe_replan(stage)
                resolved = stage.to_resolved()
                # scheduling-delay anchor: resolvable (every input
                # committed) → first dispatch is the scheduler's own
                # latency, measured from here
                resolved.ready_unix_ns = time.time_ns()
                self.stages[sid] = resolved
                changed = True
        if self.pipelined_enabled and self._revive_partial():
            changed = True
        for sid, stage in list(self.stages.items()):
            if sid in self.cache_elided:
                continue
            if isinstance(stage, ResolvedStage):
                running = stage.to_running()
                if self.locality_enabled:
                    # per-task preferred hosts from the resolved readers'
                    # exact input-partition sizes; computed only under
                    # the knob so knob-off dispatch stays the untouched
                    # baseline
                    running.task_preferred_host = preferred_hosts_of(
                        running.plan, running.partitions
                    )
                self.stages[sid] = running
                changed = True
        if changed and self.status == QUEUED:
            self.status = RUNNING
        return changed

    # ------------------------------------------- pipelined execution
    def _revive_partial(self) -> bool:
        """Partial resolution (ballista.shuffle.pipelined): start a
        consumer stage once ``pipelined_min_fraction`` of each STREAMABLE
        input's map tasks have committed, resolving those inputs to
        tailing readers over the producer's shuffle-location feed.
        Pipeline-breaking inputs (sort, hash-join build) must still be
        complete; AQE-rewritten stages keep the barrier (replans are
        gated off for partially-started stages — exact-bytes stats don't
        exist yet).  Committed-task granularity: the feed only ever
        carries first-completion-wins winners, so a consumer can never
        stream from a speculative loser."""
        import math

        from .planner import classify_shuffle_inputs

        changed = False
        for sid, stage in list(self.stages.items()):
            if not isinstance(stage, UnresolvedStage) or stage.resolvable():
                continue
            if stage.aqe:
                continue  # AQE-rewritten layout: barrier (gate, not break)
            if any(
                sh.selections is not None
                for sh in find_unresolved_shuffles(stage.plan)
            ):
                continue
            streamable, _breakers = classify_shuffle_inputs(stage.plan)
            tail: set = set()
            eligible = True
            for in_sid, inp in stage.inputs.items():
                if inp.complete:
                    continue
                if in_sid not in streamable:
                    eligible = False  # a breaker input still running
                    break
                producer = self.stages.get(in_sid)
                if not isinstance(producer, RunningStage):
                    eligible = False  # not started / mid-rollback
                    break
                need = max(
                    1,
                    math.ceil(
                        self.pipelined_min_fraction * producer.partitions
                    ),
                )
                if producer.completed_tasks() < need:
                    eligible = False
                    break
                tail.add(in_sid)
            if not eligible or not tail:
                continue
            try:
                resolved = stage.to_resolved(frozenset(tail))
            except Exception:  # noqa: BLE001 - degrade to the barrier path
                import logging

                logging.getLogger(__name__).exception(
                    "job %s: partial resolution of stage %s failed; "
                    "keeping the stage barrier", self.job_id, sid,
                )
                continue
            resolved.ready_unix_ns = time.time_ns()
            self.stages[sid] = resolved
            for in_sid in sorted(tail):
                self._ensure_feed(in_sid, stage.inputs.get(in_sid))
            self._journal(
                "stage_partial_start",
                stage=sid,
                tail_inputs=sorted(tail),
                min_fraction=self.pipelined_min_fraction,
            )
            changed = True
        return changed

    def _ensure_feed(self, sid: int, inp: Optional[StageInput]) -> None:
        """Create the producer's shuffle-location feed, seeded with every
        location committed so far (the consumer's accumulated StageInput
        carries full executor metadata; repointed external-sentinel
        locations ride through unchanged)."""
        if sid in self.shuffle_feeds:
            return
        locations: List[PartitionLocation] = []
        if inp is not None:
            for q in sorted(inp.partition_locations):
                locations.extend(
                    sorted(inp.partition_locations[q], key=lambda l: l.path)
                )
        epoch = self.feed_epochs.get(sid, 0) + 1
        self.feed_epochs[sid] = epoch
        producer = self.stages.get(sid)
        self.shuffle_feeds[sid] = {
            "locations": locations,
            "complete": isinstance(producer, CompletedStage),
            "epoch": epoch,
        }
        self._queue_feed_delta(sid, 0, locations)

    def _queue_feed_delta(
        self, sid: int, from_index: int, locations: List[PartitionLocation]
    ) -> None:
        feed = self.shuffle_feeds.get(sid)
        if feed is None:
            return
        self.pending_feed_deltas.append(
            {
                "stage": sid,
                "from_index": from_index,
                "locations": list(locations),
                "complete": feed["complete"],
                "epoch": feed["epoch"],
                "valid": True,
            }
        )

    def _append_feed(self, sid: int, locations: List[PartitionLocation]) -> None:
        feed = self.shuffle_feeds.get(sid)
        if feed is None:
            return
        start = len(feed["locations"])
        feed["locations"].extend(locations)
        self._queue_feed_delta(sid, start, locations)

    def _complete_feed(self, sid: int) -> None:
        feed = self.shuffle_feeds.get(sid)
        if feed is None or feed["complete"]:
            return
        feed["complete"] = True
        self._queue_feed_delta(sid, len(feed["locations"]), [])

    def _invalidate_feed(self, sid: int) -> None:
        """Tear a feed down (producer re-run / consumer rollback): stale
        executor mirrors must abort their tails instead of merging two
        generations of locations.  The epoch counter survives, so a
        recreated feed supersedes every mirror of this one."""
        if self.shuffle_feeds.pop(sid, None) is not None:
            self.pending_feed_deltas.append(
                {
                    "stage": sid,
                    "from_index": 0,
                    "locations": [],
                    "complete": False,
                    "epoch": self.feed_epochs.get(sid, 0),
                    "valid": False,
                }
            )

    def _feed_serves_executor(self, sid: int, executor_id: str) -> bool:
        feed = self.shuffle_feeds.get(sid)
        return feed is not None and any(
            l.executor_meta.id == executor_id for l in feed["locations"]
        )

    def shuffle_feed_delta(self, sid: int, from_index: int) -> dict:
        """The ``GetShuffleLocationDelta`` payload for one producer feed
        (pull-mode executors poll this; dict-shaped so the gRPC layer and
        tests share it)."""
        feed = self.shuffle_feeds.get(sid)
        if feed is None:
            return {
                "stage": sid,
                "from_index": 0,
                "locations": [],
                "complete": False,
                "epoch": self.feed_epochs.get(sid, 0),
                "valid": False,
            }
        locs = feed["locations"]
        start = max(0, min(int(from_index), len(locs)))
        return {
            "stage": sid,
            "from_index": start,
            "locations": list(locs[start:]),
            "complete": feed["complete"],
            "epoch": feed["epoch"],
            "valid": True,
        }

    def tailing_executors(self, sid: int) -> set:
        """Executor ids currently running tasks of a consumer stage that
        tails producer ``sid`` — the push-notification fan-out targets."""
        out: set = set()
        for stage in self.stages.values():
            if (
                isinstance(stage, RunningStage)
                and sid in stage.tail_inputs
            ):
                for t in stage.task_statuses:
                    if t is not None and t.state == "running" and t.executor_id:
                        out.add(t.executor_id)
                for si in stage.speculative_statuses.values():
                    if si.executor_id:
                        out.add(si.executor_id)
        return out

    def _maybe_replan(self, stage: UnresolvedStage) -> None:
        """AQE coalesce/skew-split hook; an AQE bug must degrade to the
        static plan, never fail the job."""
        if not self.aqe_policy.enabled:
            return
        try:
            from .adaptive import replan_stage

            replan_stage(self, stage)
        except Exception:  # noqa: BLE001 - fall back to the static plan
            import logging

            logging.getLogger(__name__).exception(
                "job %s: AQE replan of stage %s failed; keeping the "
                "static plan", self.job_id, stage.stage_id,
            )

    def _maybe_broadcast(self, completed_sid: int) -> None:
        """AQE shuffle→broadcast hook, same degrade-to-static contract."""
        if not self.aqe_policy.enabled:
            return
        try:
            from .adaptive import try_broadcast

            try_broadcast(self, completed_sid)
        except Exception:  # noqa: BLE001 - fall back to the static plan
            import logging

            logging.getLogger(__name__).exception(
                "job %s: AQE broadcast conversion after stage %s failed; "
                "keeping the static plan", self.job_id, completed_sid,
            )

    # ----------------------------------------------------------- dispatch
    def pop_next_task(
        self,
        executor_id: str,
        allow_excluded: bool = False,
        executor_host: Optional[str] = None,
    ) -> Optional[Task]:
        """Find a Running stage with an unclaimed partition, mark it
        running on ``executor_id`` and return it
        (reference: execution_graph.rs:418-471).

        A partition whose last transient failure happened on
        ``executor_id`` is skipped (the retry must land elsewhere) unless
        ``allow_excluded`` — the liveness escape hatch when no other
        executor exists (``task_manager.fill_reservations``).

        With locality placement on (``ballista.shuffle.locality_*``) and
        ``executor_host`` known, the scan walks partitions in order but
        DEFERS any task preferring a different host — leaving it for a
        preferred executor — until the stage has been running for
        ``locality_wait_s``, after which any host may take it (soft
        preference: data locality is worth waiting for, never starving
        for).  Preference-less tasks are taken whenever reached — they
        are not reordered behind this host's preferred ones (in practice
        a reduce stage's partitions either all carry preferences or none
        do, so a second prioritizing scan would buy nothing).  Callers
        that do not pass a host — or pass an empty one (metadata lookup
        failed) — keep baseline behavior: an UNKNOWN host must degrade
        to location-blind dispatch, never defer every preferred task
        against it.

        Unclaimed partitions are served first; pending speculation
        requests (straggler duplicates flagged by the scan) come second
        and only ever land on an executor OTHER than the primary's."""
        from ..shuffle.transport import normalize_host

        locality = self.locality_enabled and bool(executor_host)
        host_n = normalize_host(executor_host) if locality else ""
        now = time.monotonic() if locality else 0.0
        for sid in sorted(self.stages):
            stage = self.stages[sid]
            if not isinstance(stage, RunningStage):
                continue
            for p, t in enumerate(stage.task_statuses):
                if t is not None:
                    continue
                if (
                    not allow_excluded
                    and stage.task_exclusions.get(p) == executor_id
                ):
                    continue
                pref = (
                    stage.task_preferred_host.get(p) if locality else None
                )
                if (
                    pref
                    and pref != host_n
                    and now
                    < stage.running_since_mono + self.locality_wait_s
                ):
                    # hold out for the host that already has the bytes;
                    # the flag keeps the push-mode safety tick re-minting
                    # a reservation for the turned-away slot
                    stage.locality_deferred = True
                    continue
                if locality:
                    stage.locality_deferred = False
                if pref:
                    stage.locality_stats["local" if pref == host_n else "any"] = (
                        stage.locality_stats.get(
                            "local" if pref == host_n else "any", 0
                        )
                        + 1
                    )
                attempt = stage.task_attempts.get(p, 0)
                pid = PartitionId(self.job_id, sid, p)
                stage.task_statuses[p] = TaskInfo(
                    pid, "running", executor_id, attempt=attempt
                )
                stage.task_started_mono[p] = time.monotonic()
                # critical-path anchor: re-dispatches overwrite, so the
                # breakdown reflects the attempt that ends up committing
                stage.task_dispatch_unix_ns[p] = time.time_ns()
                return Task(
                    self.session_id,
                    pid,
                    stage.plan,
                    stage.plan.shuffle_output_partitioning,
                    attempt,
                    trace_id=self.trace_id,
                    timeout_seconds=self.task_timeout_s,
                )
            task = self._pop_speculative(sid, stage, executor_id)
            if task is not None:
                return task
        return None

    def preferred_hosts(self) -> Dict[str, int]:
        """Pending-task demand per preferred host (normalized) across
        Running stages — the ordering hint for
        ``ExecutorManager.reserve_slots`` so cluster-wide reservations
        land where the shuffle bytes already are.  Empty when locality
        placement is off."""
        out: Dict[str, int] = {}
        if not self.locality_enabled:
            return out
        for stage in self.stages.values():
            if not isinstance(stage, RunningStage):
                continue
            for p, t in enumerate(stage.task_statuses):
                if t is None:
                    h = stage.task_preferred_host.get(p)
                    if h:
                        out[h] = out.get(h, 0) + 1
        return out

    def _pop_speculative(
        self, sid: int, stage: RunningStage, executor_id: str
    ) -> Optional[Task]:
        """Hand out one pending straggler duplicate to ``executor_id``.
        The duplicate shares the primary's attempt number — whichever copy
        completes first commits; the other's late status fails the
        "partition already completed" guard."""
        for p, primary_eid in sorted(stage.speculation_requests.items()):
            t = stage.task_statuses[p]
            if t is None or t.state != "running":
                # the primary failed/was reset/completed since the scan
                # flagged it: the request is stale
                stage.speculation_requests.pop(p, None)
                continue
            if executor_id == t.executor_id or executor_id == primary_eid:
                continue  # the duplicate must race on a DIFFERENT host
            if stage.task_exclusions.get(p) == executor_id:
                continue  # ...and never on a host that already failed p
            if p in stage.speculative_statuses:
                stage.speculation_requests.pop(p, None)
                continue
            attempt = stage.task_attempts.get(p, 0)
            pid = PartitionId(self.job_id, sid, p)
            stage.speculative_statuses[p] = TaskInfo(
                pid, "running", executor_id, attempt=attempt, speculative=True
            )
            stage.spec_started_mono[p] = time.monotonic()
            stage.spec_dispatch_unix_ns[p] = time.time_ns()
            stage.bump_spec_stat("launched")
            stage.speculation_requests.pop(p, None)
            self._journal(
                "speculation_launched",
                stage=sid,
                partition=p,
                executor=executor_id,
                straggler=t.executor_id,
            )
            return Task(
                self.session_id,
                pid,
                stage.plan,
                stage.plan.shuffle_output_partitioning,
                attempt,
                trace_id=self.trace_id,
                speculative=True,
                timeout_seconds=self.task_timeout_s,
            )
        return None

    def reset_task_status(
        self, partition: PartitionId, exclude_executor: str = "",
        speculative: bool = False,
    ) -> None:
        """Return a handed-out task to the pool (launch failed / reservation
        cancelled).  ``exclude_executor`` keeps the re-dispatch off the
        executor the launch just failed against.  A failed SPECULATIVE
        launch only forgets the duplicate — the primary attempt keeps the
        partition."""
        stage = self.stages.get(partition.stage_id)
        if not isinstance(stage, RunningStage):
            return
        p = partition.partition_id
        if speculative:
            if stage.drop_speculative(p) is not None:
                stage.bump_spec_stat("wasted")
                self.spec_wasted_pending += 1
            return
        t = stage.task_statuses[p]
        if t is not None and t.state == "running":
            stage.task_statuses[p] = None
            stage.task_started_mono.pop(p, None)
            if exclude_executor:
                stage.task_exclusions[p] = exclude_executor

    def reset_running_tasks(self, executor_id: str) -> int:
        """Re-queue every task currently running on ``executor_id`` with
        the executor excluded (quarantine: the host is sick but its past
        shuffle output is still servable, so no stage rollback).  Returns
        the number of tasks reset.

        The attempt counter is bumped: the quarantined executor was never
        told to stop, so its late status for the superseded attempt must
        fail the stale-attempt guards instead of double-completing or
        double-failing the partition.  A primary whose healthy duplicate
        is still racing elsewhere is not re-queued — the duplicate is
        promoted in place (same attempt, partition stays covered)."""
        n = 0
        for stage in self.stages.values():
            if not isinstance(stage, RunningStage):
                continue
            for p, si in list(stage.speculative_statuses.items()):
                if si.executor_id == executor_id:
                    stage.drop_speculative(p)
                    stage.bump_spec_stat("wasted")
                    self.spec_wasted_pending += 1
            for p, t in enumerate(stage.task_statuses):
                if t is not None and t.state == "running" and t.executor_id == executor_id:
                    spec_started = stage.spec_started_mono.get(p)
                    spec_dispatch = stage.spec_dispatch_unix_ns.get(p)
                    shadow = stage.drop_speculative(p)
                    if shadow is not None:
                        stage.task_statuses[p] = shadow
                        if spec_started is not None:
                            stage.task_started_mono[p] = spec_started
                        else:
                            stage.task_started_mono.pop(p, None)
                        if spec_dispatch is not None:
                            stage.task_dispatch_unix_ns[p] = spec_dispatch
                        # the quarantined host's copy is superseded: abort
                        # it (best-effort) — its late reports are dropped
                        # by the superseded-copy guard either way
                        self.pending_cancels.append(
                            (executor_id, t.partition_id)
                        )
                        continue
                    stage.task_statuses[p] = None
                    stage.task_started_mono.pop(p, None)
                    stage.task_exclusions[p] = executor_id
                    stage.task_attempts[p] = stage.task_attempts.get(p, 0) + 1
                    self.task_retries += 1
                    n += 1
        return n

    # ------------------------------------------------------ status updates
    def update_task_status(
        self,
        info: TaskInfo,
        executor: Optional[ExecutorMetadata] = None,
    ) -> List[str]:
        """Apply one task status; returns job-level events out of
        ("job_updated", "job_completed", "job_failed")
        (reference: execution_graph.rs:197-318)."""
        stage = self.stages.get(info.partition_id.stage_id)
        if stage is None:
            raise SchedulerError(
                f"job {self.job_id}: unknown stage {info.partition_id.stage_id}"
            )
        if not isinstance(stage, RunningStage):
            # late status for a stage already rolled back or completed
            return []

        events: List[str] = []
        p = info.partition_id.partition_id
        committed = (
            0 <= p < stage.partitions
            and stage.task_statuses[p] is not None
            and stage.task_statuses[p].state == "completed"
        )
        if committed:
            # first-completion-wins: the partition already committed, so
            # ANY later report — the cancelled loser's success as much as
            # its failure, or a duplicate delivery — is stale.  Dropping
            # it here keeps the committed output locations stable (no
            # double propagation to consumers) and burns no failure
            # budget.
            return []

        if info.state == "failed":
            return self._on_task_failed(stage, info)

        if info.attempt < stage.task_attempts.get(p, 0):
            # late status from a superseded attempt (the task was reset by
            # quarantine and re-dispatched): accepting it would overwrite
            # the live attempt's status — and a stale completion would
            # propagate the same partition's output twice
            return []
        if info.state == "running" and info.speculative:
            # progress report from a duplicate attempt: it must never
            # shadow the primary's slot
            if p in stage.speculative_statuses:
                stage.speculative_statuses[p] = info
            return []
        if info.state == "completed":
            events.extend(self._commit_winner(stage, info))
        stage.update_task_status(info)
        if info.state == "completed":
            if info.fetch_retries:
                stage.task_fetch_retries[p] = info.fetch_retries
            stage.update_task_metrics(info)
            # per-partition written-bytes distribution (skew input): wire
            # bytes from the writer metrics when present, else the sum of
            # the partition files' sizes; raw falls back to wire
            wire_m = sum(
                int(vals.get("bytes_written_wire", 0)) for _, vals in info.metrics
            )
            raw_m = sum(
                int(vals.get("bytes_written_raw", 0)) for _, vals in info.metrics
            )
            wire = wire_m or sum(pt.num_bytes for pt in info.partitions)
            stage.task_bytes[p] = {"raw": raw_m or wire, "wire": wire}
            if executor is not None:
                self._propagate_output(stage, info, executor)
            if stage.is_completed():
                sid = info.partition_id.stage_id
                completed = stage.to_completed()
                self.stages[sid] = completed
                from ..obs.export import STAGE_SKEW_OP

                skew = completed.stage_metrics.get(STAGE_SKEW_OP, {})
                self._journal(
                    "stage_completed",
                    stage=sid,
                    partitions=completed.partitions,
                    task_retries=sum(completed.task_attempts.values()),
                    runtime_skew=skew.get("runtime_ms_skew_x1000", 0) / 1000.0,
                    bytes_skew=skew.get("bytes_wire_skew_x1000", 0) / 1000.0,
                )
                from .display import print_stage_metrics

                print_stage_metrics(
                    self.job_id, sid, completed.plan, completed.stage_metrics
                )
                for link in completed.output_links:
                    consumer = self.stages.get(link)
                    if isinstance(consumer, UnresolvedStage):
                        consumer.complete_input(sid)
                    elif sid in getattr(consumer, "tail_inputs", ()):
                        # partially-started consumer: the producer is done
                        # — flip its input complete so rollback/recovery
                        # bookkeeping sees a finished input from here on
                        inp = consumer.inputs.get(sid)
                        if inp is not None:
                            inp.complete = True
                # the tailing feed (if any consumer streams this stage)
                # ends here: executors finish their tails and the stage's
                # last fragment becomes fetchable like any other
                self._complete_feed(sid)
                if sid == self._final_stage_id:
                    self._collect_job_output(completed, executor)
                    self.status = COMPLETED
                    events.append("job_completed")
                else:
                    # AQE: a freshly-measured small build side may convert
                    # a consumer's join to broadcast (stripping the
                    # not-yet-started probe shuffle) BEFORE revive can
                    # resolve anything against the static plan
                    self._maybe_broadcast(sid)
                    self.revive()
                    events.append("job_updated")
            else:
                events.append("job_updated")
        return events

    def _commit_winner(self, stage: RunningStage, info: TaskInfo) -> List[str]:
        """First-completion-wins bookkeeping for one completed report:
        identify the losing copy (if the partition was racing two), queue
        its CancelTasks, and record the winner's runtime for the stage's
        speculation median.  The caller then commits ``info`` as the
        partition's status."""
        p = info.partition_id.partition_id
        events: List[str] = []
        cur = stage.task_statuses[p]
        started = stage.task_started_mono.get(p)
        shadow_started = stage.spec_started_mono.get(p)
        shadow_dispatch = stage.spec_dispatch_unix_ns.get(p)
        shadow = stage.drop_speculative(p)
        if info.speculative:
            # the committed attempt is the DUPLICATE: its dispatch anchor
            # replaces the straggler's, so the breakdown window excludes
            # the straggler's dead time
            if shadow_dispatch is not None:
                stage.task_dispatch_unix_ns[p] = shadow_dispatch
            # the duplicate beat the straggler: the still-running primary
            # is the loser — cancel it; its late status will hit the
            # committed-partition guard
            if (
                cur is not None
                and cur.state == "running"
                and cur.executor_id != info.executor_id
            ):
                self.pending_cancels.append(
                    (cur.executor_id, info.partition_id)
                )
            stage.bump_spec_stat("wins")
            events.append("speculative_win")
            self._journal(
                "speculation_win",
                stage=info.partition_id.stage_id,
                partition=p,
                executor=info.executor_id,
                loser=cur.executor_id if cur is not None else "",
            )
            started = shadow_started if shadow_started is not None else started
        elif shadow is not None:
            # the primary won the race after all: the duplicate is wasted
            self.pending_cancels.append(
                (shadow.executor_id, info.partition_id)
            )
            stage.bump_spec_stat("wasted")
            self.spec_wasted_pending += 1
            events.append("speculative_wasted")
            self._journal(
                "speculation_wasted",
                stage=info.partition_id.stage_id,
                partition=p,
                executor=shadow.executor_id,
            )
        stage.task_started_mono.pop(p, None)
        stage.task_finish_unix_ns[p] = time.time_ns()
        if started is not None:
            runtime = max(0.0, time.monotonic() - started)
            stage.completed_runtime_s.append(runtime)
            # per-partition runtime distribution (skew input)
            stage.task_runtime_s[p] = runtime
        return events

    def _on_task_failed(self, stage: RunningStage, info: TaskInfo) -> List[str]:
        """Bounded retry with failure classification (the reference fails
        the whole job on the first failed task; production cannot):
        transient failures re-queue the partition — excluded from the
        executor that just failed it — until ``ballista.task.max_attempts``
        is spent, then the job fails with the accumulated error history.
        Fatal (plan/serde/SQL) errors fail fast on attempt 1.

        Speculation interplay: while a partition races two copies, one
        copy's failure only drops THAT copy (the other keeps the
        partition; no re-queue, no attempt burned).  A consumer failing
        with a structured ShuffleFetchFailed triggers producer-partition
        recovery instead of burning its own attempts on data that no
        longer exists."""
        from .failure import (
            FATAL,
            classify_failure,
            parse_shuffle_fetch_failure,
        )

        sid = info.partition_id.stage_id
        p = info.partition_id.partition_id
        current = stage.task_attempts.get(p, 0)
        if info.attempt < current:
            # late report from an attempt already superseded (e.g. the
            # task was reset by quarantine and re-ran elsewhere)
            return []
        if info.fetch_retries:
            stage.task_fetch_retries[p] = info.fetch_retries
        error = info.error or "task failed"

        shadow = stage.speculative_statuses.get(p)
        cur = stage.task_statuses[p]
        if info.speculative:
            # the duplicate died; the primary still owns the partition
            if shadow is not None and info.executor_id == shadow.executor_id:
                stage.drop_speculative(p)
                stage.bump_spec_stat("wasted")
                self.spec_wasted_pending += 1
                return ["speculative_wasted"]
            if not (
                cur is not None
                and cur.state == "running"
                and cur.executor_id == info.executor_id
            ):
                return []  # duplicate already dropped/superseded: stale
            # the duplicate was PROMOTED to primary (its reports still
            # carry speculative=true from the TaskDefinition): this is
            # now the partition's only live attempt — fall through to the
            # normal failure path so it re-queues instead of stranding
            # the partition in "running" forever
        elif cur is None or cur.state != "running":
            # no live attempt owns this partition: it was reset (launch
            # failure, stage rollback, lost-shuffle recovery) and will
            # re-dispatch through the normal path.  The report is from a
            # superseded copy — e.g. a recovery-cancelled consumer task's
            # late "Cancelled:" — and must neither burn budget nor
            # fail-fast a job mid-recovery.
            return []
        elif (
            cur.executor_id
            and info.executor_id
            and cur.executor_id != info.executor_id
        ):
            # same-attempt failure from an executor that no longer owns
            # the partition (e.g. a quarantine reset promoted the
            # duplicate in place and the old primary limped on): the
            # live attempt on cur.executor_id keeps the partition — do
            # not wipe it or burn budget for a superseded copy
            return []
        if (
            shadow is not None
            and cur is not None
            and cur.state == "running"
            and info.executor_id == cur.executor_id
        ):
            # the primary died but its duplicate races on: promote it in
            # place (same attempt number) instead of re-queueing
            spec_started = stage.spec_started_mono.get(p)
            spec_dispatch = stage.spec_dispatch_unix_ns.get(p)
            promoted = stage.drop_speculative(p)
            stage.task_statuses[p] = promoted
            if spec_started is not None:
                stage.task_started_mono[p] = spec_started
            else:
                stage.task_started_mono.pop(p, None)
            if spec_dispatch is not None:
                stage.task_dispatch_unix_ns[p] = spec_dispatch
            stage.task_failures.setdefault(p, []).append(
                f"attempt {current} on {info.executor_id or '<unknown>'}: "
                f"{error} (duplicate attempt promoted)"
            )
            return ["job_updated"]

        lost = parse_shuffle_fetch_failure(error)
        if lost is not None:
            recovered = self._recover_lost_shuffle(stage, *lost)
            if recovered is not None:
                return recovered

        history = stage.task_failures.setdefault(p, [])
        history.append(
            f"attempt {current} on {info.executor_id or '<unknown>'}: {error}"
        )
        kind = classify_failure(error)
        # deadline reaps bump the attempt counter for staleness but grant
        # a free attempt — they never consume the failure budget
        budget = self.task_max_attempts + stage.task_free_attempts.get(p, 0)
        if kind != FATAL and current + 1 < budget:
            stage.task_attempts[p] = current + 1
            if info.executor_id:
                stage.task_exclusions[p] = info.executor_id
            stage.task_statuses[p] = None
            stage.task_started_mono.pop(p, None)
            self.task_retries += 1
            self._journal(
                "task_retry",
                stage=sid,
                partition=p,
                attempt=current,
                executor=info.executor_id,
                error=error[:200],
            )
            return ["task_retried"]

        detail = "; ".join(history)
        reason = (
            "fatal error"
            if kind == FATAL
            else f"exhausted {self.task_max_attempts} attempts"
        )
        self.stages[sid] = stage.to_failed(detail)
        self.status = FAILED
        self.error = (
            f"stage {sid} task {p} failed ({reason}): {detail}"
        )
        return ["job_failed"]

    def _propagate_output(
        self, stage: RunningStage, info: TaskInfo, executor: ExecutorMetadata
    ) -> None:
        """Push one completed map task's shuffle partitions into consumer
        stages' inputs (reference: execution_graph.rs:320-369).  Only
        COMMITTED winners reach here (the first-completion-wins guard
        drops losers before publication), so partially-started consumers
        and the tailing feed can never stream from a losing attempt."""
        locations = [
            PartitionLocation(
                PartitionId(self.job_id, stage.stage_id, p.partition_id),
                executor,
                PartitionStats(p.num_rows, p.num_batches, p.num_bytes),
                p.path,
                replica_path=p.replica_path,
            )
            for p in info.partitions
        ]
        for link in stage.output_links:
            consumer = self.stages.get(link)
            if isinstance(consumer, UnresolvedStage):
                consumer.add_input_partitions(stage.stage_id, locations)
            elif stage.stage_id in getattr(consumer, "tail_inputs", ()):
                # partially-started consumer: keep its StageInput current
                # (rollback/recovery reads it) while the live stream rides
                # the feed below
                inp = consumer.inputs.get(stage.stage_id)
                if inp is not None:
                    for loc in locations:
                        inp.add_partition(loc)
        self._append_feed(stage.stage_id, locations)

    def _collect_job_output(
        self, stage: CompletedStage, executor: Optional[ExecutorMetadata]
    ) -> None:
        self.output_locations = []
        for t in stage.task_statuses:
            if t is None:
                continue
            meta = executor
            for p in t.partitions:
                self.output_locations.append(
                    PartitionLocation(
                        PartitionId(self.job_id, stage.stage_id, p.partition_id),
                        meta if meta is not None else ExecutorMetadata("", "", 0),
                        PartitionStats(p.num_rows, p.num_batches, p.num_bytes),
                        p.path,
                        replica_path=p.replica_path,
                    )
                )

    # ------------------------------------------ lost-shuffle recovery
    def _recover_lost_shuffle(
        self,
        consumer: RunningStage,
        prod_sid: int,
        map_partition: int,
        executor_id: str,
    ) -> Optional[List[str]]:
        """A consumer task exhausted its fetch retries against map output
        that no longer exists (``ShuffleFetchFailed``): re-run only the
        PRODUCER partitions that lived on ``executor_id`` and roll the
        consumer back to Unresolved, instead of burning the consumer's
        attempt budget on data nobody can serve.  Returns the job events,
        or None when recovery does not apply (the normal transient retry
        path then takes over).  Bounded by the same
        ``ballista.stage.max_attempts`` ledger as executor-loss resets."""
        from ..shuffle.store import EXTERNAL_EXECUTOR_ID

        producer = self.stages.get(prod_sid)
        if producer is None or prod_sid == consumer.stage_id:
            return None
        csid = consumer.stage_id
        inp = consumer.inputs.get(prod_sid)
        lost_in_consumer = inp is not None and any(
            l.executor_meta.id == executor_id
            for locs in inp.partition_locations.values()
            for l in locs
        )
        # an EXTERNAL-STORE loss (a repointed location's copy vanished):
        # record which paths the strip below will remove, so the re-run
        # covers exactly the map tasks backing them — resetting healthy
        # executors' tasks too would re-propagate (duplicate) locations
        # the consumer still holds
        sentinel_paths: set = set()
        if executor_id == EXTERNAL_EXECUTOR_ID and inp is not None:
            sentinel_paths = {
                l.path
                for locs in inp.partition_locations.values()
                for l in locs
                if l.executor_meta.id == EXTERNAL_EXECUTOR_ID
            }
        producer_has_lost_tasks = isinstance(producer, CompletedStage) and any(
            t is not None and t.executor_id == executor_id
            for t in producer.task_statuses
        )
        producer_rerunning = isinstance(producer, (RunningStage, ResolvedStage, UnresolvedStage))
        if not (lost_in_consumer or producer_has_lost_tasks or producer_rerunning):
            return None

        # bounded: repeated data loss on the same stages must fail the
        # job with the ledger, not loop forever
        for sid in (prod_sid, csid):
            count = self.stage_reset_counts.get(sid, 0) + 1
            self.stage_reset_counts[sid] = count
            if count >= self.stage_max_attempts:
                self.status = FAILED
                self.error = (
                    f"stage {sid} reset {count} times recovering lost "
                    f"shuffle output of stage {prod_sid} on {executor_id}; "
                    f"exceeded ballista.stage.max_attempts="
                    f"{self.stage_max_attempts}"
                )
                return ["job_failed"]

        # 1) abandon the consumer's other in-flight tasks (their input
        #    set is about to change) and roll it back to Unresolved,
        #    stripping ONLY the lost executor's locations for prod_sid.
        #    A half-streamed consumer's tail feeds are invalidated so
        #    executor mirrors abort instead of merging the re-run's
        #    locations into the dead generation.
        for t in consumer.task_statuses:
            if t is not None and t.state == "running":
                self.pending_cancels.append((t.executor_id, t.partition_id))
        for si in consumer.speculative_statuses.values():
            self.pending_cancels.append((si.executor_id, si.partition_id))
        for f_sid in sorted(consumer.tail_inputs):
            self._invalidate_feed(f_sid)
        self._invalidate_feed(prod_sid)
        unresolved = consumer.to_resolved().to_unresolved()
        uinp = unresolved.inputs.get(prod_sid)
        if uinp is not None:
            stripped = False
            for q, locs in uinp.partition_locations.items():
                kept = [
                    l for l in locs if l.executor_meta.id != executor_id
                ]
                if len(kept) != len(locs):
                    stripped = True
                uinp.partition_locations[q] = kept
            if stripped or producer_has_lost_tasks or producer_rerunning:
                uinp.complete = False
        self.stages[csid] = unresolved

        # 2) re-run just the producer tasks whose output lived there
        n_rerun = 0
        if prod_sid in self.cache_served:
            # the "producer" never ran — it was served from the plan
            # cache and its files vanished: forget the serve, rebirth
            # the elided subtree, recompute through normal dispatch
            n_rerun = self._revert_cache_served(prod_sid)
        elif isinstance(producer, CompletedStage):
            running = producer.to_running()
            if executor_id == EXTERNAL_EXECUTOR_ID:
                # the external store lost data: re-run the map tasks
                # backing the stripped sentinel locations (matched by
                # replica/primary path; every task when the paths are
                # unknown) — the sentinel must never leave the consumer
                # stranded on an input nobody will complete
                for i, t in enumerate(running.task_statuses):
                    if t is None:
                        continue
                    backs_sentinel = not sentinel_paths or any(
                        p.replica_path in sentinel_paths
                        or p.path in sentinel_paths
                        for p in t.partitions
                    )
                    if backs_sentinel:
                        running.task_statuses[i] = None
                        n_rerun += 1
            else:
                n_rerun = running.reset_tasks(executor_id)
            if n_rerun:
                self.stages[prod_sid] = running
        self.revive()
        self._journal(
            "shuffle_lost_recovery",
            producer_stage=prod_sid,
            consumer_stage=csid,
            executor=executor_id,
            map_tasks_rerun=n_rerun,
        )
        return ["job_updated"] + ["task_requeued"] * n_rerun

    def _revert_cache_served(self, sid: int) -> int:
        """A cache-served stage's cached partitions vanished: forget the
        serve — the stage and its elided upstream subtree revert to
        their born state and recompute through the normal dispatch path.
        The subtree is self-contained by construction (serving requires
        every interior stage's consumers to stay inside it), so rebirth
        cannot strand or double-feed any outside consumer.  Returns the
        number of stages reborn."""
        stage = self.stages.get(sid)
        if not isinstance(stage, CompletedStage):
            self.cache_served.pop(sid, None)
            return 0
        fp = self.cache_served.pop(sid, "")
        if fp:
            self.pending_cache_invalidations.append(fp)
        reborn = {sid}
        frontier = [sid]
        while frontier:
            cur = self.stages.get(frontier.pop())
            if cur is None:
                continue
            for sh in find_unresolved_shuffles(cur.plan):
                if sh.stage_id in self.cache_elided:
                    self.cache_elided.discard(sh.stage_id)
                    reborn.add(sh.stage_id)
                    frontier.append(sh.stage_id)
        for s in sorted(reborn):
            cur = self.stages[s]
            deps = [sh.stage_id for sh in find_unresolved_shuffles(cur.plan)]
            if deps:
                self.stages[s] = UnresolvedStage(
                    s,
                    cur.plan,
                    list(cur.output_links),
                    {d: StageInput() for d in deps},
                )
            else:
                born = ResolvedStage(s, cur.plan, list(cur.output_links), {})
                born.ready_unix_ns = time.time_ns()
                self.stages[s] = born
        self._journal(
            "cache_lost",
            stage=sid,
            fingerprint=fp,
            stages_reborn=sorted(reborn),
        )
        return len(reborn)

    # --------------------------------------- speculation/deadline scan
    def scan_speculation(
        self,
        now: Optional[float] = None,
        force_enabled: bool = False,
        force_timeout_s: float = 0.0,
    ) -> dict:
        """One pass of the scheduler's periodic straggler/deadline scan
        (runs on the event-loop thread via ``scheduler/speculation.py``).
        Flags stragglers for duplicate dispatch, reaps running tasks past
        ``ballista.task.timeout_seconds``, and returns
        ``{"new_requests", "timeouts", "events"}``.  Cancellations queue
        on ``pending_cancels``."""
        now = time.monotonic() if now is None else now
        out = {"new_requests": 0, "timeouts": 0, "events": []}
        if self.status != RUNNING:
            return out
        enabled = self.spec_enabled or force_enabled
        timeout_s = self.task_timeout_s or force_timeout_s
        for sid, stage in list(self.stages.items()):
            if not isinstance(stage, RunningStage):
                continue
            if timeout_s > 0:
                self._reap_deadlines(sid, stage, now, timeout_s, out)
                if self.status == FAILED:
                    return out
            if enabled:
                self._request_speculation(stage, now, out)
        return out

    def _reap_deadlines(
        self, sid: int, stage: RunningStage, now: float, timeout_s: float,
        out: dict,
    ) -> None:
        # wedged duplicates just disappear (wasted); the primary keeps
        # the partition
        for p, si in list(stage.speculative_statuses.items()):
            started = stage.spec_started_mono.get(p)
            if started is not None and now - started >= timeout_s:
                stage.drop_speculative(p)
                stage.bump_spec_stat("wasted")
                self.spec_wasted_pending += 1
                self.pending_cancels.append(
                    (si.executor_id, si.partition_id)
                )
                out["timeouts"] += 1
        for p, t in enumerate(stage.task_statuses):
            if t is None or t.state != "running":
                continue
            started = stage.task_started_mono.get(p)
            if started is None or now - started < timeout_s:
                continue
            pid = PartitionId(self.job_id, sid, p)
            self.pending_cancels.append((t.executor_id, pid))
            out["timeouts"] += 1
            spec_started = stage.spec_started_mono.get(p)
            spec_dispatch = stage.spec_dispatch_unix_ns.get(p)
            shadow = stage.drop_speculative(p)
            if shadow is not None:
                # a healthy duplicate takes over in place (same attempt)
                stage.task_statuses[p] = shadow
                if spec_started is not None:
                    stage.task_started_mono[p] = spec_started
                else:
                    stage.task_started_mono.pop(p, None)
                if spec_dispatch is not None:
                    stage.task_dispatch_unix_ns[p] = spec_dispatch
                out["events"].append("job_updated")
                continue
            cur = stage.task_attempts.get(p, 0)
            stage.task_failures.setdefault(p, []).append(
                f"attempt {cur} on {t.executor_id or '<unknown>'}: task "
                f"deadline exceeded after {now - started:.1f}s (reaped)"
            )
            # reaps are budget-free but NOT unbounded: a partition whose
            # every attempt outlives the deadline (the timeout is simply
            # below its genuine runtime) must fail the job with a clear
            # error, not loop dispatch→reap forever
            reaps = stage.task_free_attempts.get(p, 0) + 1
            if reaps >= max(2, self.task_max_attempts):
                detail = "; ".join(stage.task_failures.get(p, []))
                self.stages[sid] = stage.to_failed(detail)
                self.status = FAILED
                self.error = (
                    f"stage {sid} task {p} reaped {reaps} times at "
                    f"ballista.task.timeout_seconds={timeout_s:g} — the "
                    f"deadline is below the task's real runtime: {detail}"
                )
                out["events"].append("job_failed")
                return
            stage.task_statuses[p] = None
            stage.task_started_mono.pop(p, None)
            if t.executor_id:
                stage.task_exclusions[p] = t.executor_id
            # the bump keeps the wedged executor's late report stale; the
            # free attempt keeps the reap out of the failure budget
            stage.task_attempts[p] = cur + 1
            stage.task_free_attempts[p] = reaps
            self.task_retries += 1
            self._journal(
                "task_reaped",
                stage=sid,
                partition=p,
                executor=t.executor_id,
                elapsed_s=round(now - started, 3),
                timeout_s=timeout_s,
            )
            out["events"].append("task_requeued")

    def _request_speculation(
        self, stage: RunningStage, now: float, out: dict
    ) -> None:
        import math
        import statistics

        launched = stage.spec_stats.get("launched", 0)
        budget = (
            self.spec_max_copies_per_stage
            - launched
            - len(stage.speculation_requests)
        )
        if budget <= 0:
            return
        runtimes = stage.completed_runtime_s
        need = max(
            1,
            math.ceil(self.spec_min_completed_fraction * stage.partitions),
        )
        if not runtimes or stage.completed_tasks() < need:
            return
        threshold = max(
            self.spec_multiplier * statistics.median(runtimes),
            self.spec_min_runtime_s,
        )
        for p, t in enumerate(stage.task_statuses):
            if budget <= 0:
                break
            if t is None or t.state != "running":
                continue
            if (
                p in stage.speculative_statuses
                or p in stage.speculation_requests
            ):
                continue
            started = stage.task_started_mono.get(p)
            if started is None or now - started <= threshold:
                continue
            stage.speculation_requests[p] = t.executor_id
            out["new_requests"] += 1
            budget -= 1

    # ------------------------------------------------------------- failure
    def fail_job(self, error: str) -> None:
        self.status = FAILED
        self.error = error

    # ------------------------------------------- replica repoint helpers
    def _external_location(self, loc: PartitionLocation, path: str) -> PartitionLocation:
        from ..shuffle.store import EXTERNAL_EXECUTOR

        return PartitionLocation(
            loc.partition_id, EXTERNAL_EXECUTOR, loc.partition_stats, path
        )

    @staticmethod
    def _exists_memo():
        """Memoized ``os.path.exists``: one reset_stages pass probes the
        same replica paths from several angles (annotate, victim split,
        keep_task, repoint) and runs on the single event-loop thread —
        with the external root on a network mount each stat is a round
        trip, so pay it once per path per loss, not four times."""
        import os

        cache: Dict[str, bool] = {}

        def probe(path: str) -> bool:
            v = cache.get(path)
            if v is None:
                v = os.path.exists(path)
                cache[path] = v
            return v

        return probe

    def _derived_replica(self, path: str, probe=None) -> str:
        """External-store copy of ``path`` that actually exists, or "".
        Covers drain-time uploads, which register no replica_path — the
        mapping is deterministic, so the scheduler probes the shared
        store instead of needing a new wire protocol."""
        import os

        from ..shuffle.store import external_replica_path, is_under_root

        probe = os.path.exists if probe is None else probe
        root = getattr(self, "external_shuffle_path", "")
        if not root or not path:
            return ""
        if is_under_root(root, path):
            # external-primary store: the partition IS the surviving copy
            return path
        cand = external_replica_path(root, path)
        return cand if cand is not None and probe(cand) else ""

    def _replica_of(
        self, loc: PartitionLocation, probe=None
    ) -> Optional[PartitionLocation]:
        """A location for a surviving copy of ``loc``'s partition, or
        None when no copy is KNOWN TO EXIST.  A registered replica_path
        is probed too: replication=async stamps it optimistically, so a
        failed background upload must not repoint consumers at a dangling
        path (they would fetch-fail against the sentinel and the
        producer would never recompute)."""
        import os

        probe = os.path.exists if probe is None else probe
        if loc.replica_path and probe(loc.replica_path):
            return self._external_location(loc, loc.replica_path)
        derived = self._derived_replica(loc.path, probe)
        return self._external_location(loc, derived) if derived else None

    def _repoint_inputs(
        self, executor_id: str, skip_paths=frozenset(), probe=None
    ) -> int:
        """Re-point every stage-input location served by ``executor_id``
        at its surviving replica (external sentinel executor, so nothing
        downstream ever strips it again).  Locations WITHOUT a surviving
        copy — and locations in ``skip_paths`` (output of map tasks that
        are about to RE-RUN: repointing half a task while the whole task
        re-propagates would feed consumers the same data twice) — are
        left for the strip/rollback passes.  Returns how many locations
        were re-pointed."""
        n = 0
        for stage in self.stages.values():
            inputs = getattr(stage, "inputs", None)
            if not inputs:
                continue
            for inp in inputs.values():
                for q, locs in inp.partition_locations.items():
                    out = []
                    for l in locs:
                        if (
                            l.executor_meta.id == executor_id
                            and l.path not in skip_paths
                        ):
                            r = self._replica_of(l, probe)
                            if r is not None:
                                out.append(r)
                                n += 1
                                continue
                        out.append(l)
                    inp.partition_locations[q] = out
        return n

    def _annotate_completed_replicas(
        self, executor_id: str, probe=None
    ) -> int:
        """Stamp probe-derived replica paths onto completed task stats of
        ``executor_id`` (drain-time uploads registered none), so the
        survivor/victim split can tell replicated partitions from truly
        lost ones.  Running stages' COMPLETED tasks are annotated too —
        a partially-finished stage's done work is just as protectable.
        Returns the number of partitions annotated."""
        from dataclasses import replace as _replace

        n = 0
        for stage in self.stages.values():
            statuses = getattr(stage, "task_statuses", None)
            if statuses is None:
                continue
            for t in statuses:
                if (
                    t is None
                    or t.executor_id != executor_id
                    or t.state != "completed"
                ):
                    continue
                parts = []
                changed = False
                for p in t.partitions:
                    if not p.replica_path:
                        derived = self._derived_replica(p.path, probe)
                        if derived:
                            p = _replace(p, replica_path=derived)
                            changed = True
                            n += 1
                    parts.append(p)
                if changed:
                    t.partitions = parts
        return n

    @staticmethod
    def _fully_replicated(t: TaskInfo, probe=None) -> bool:
        """Does every output partition of this completed task have a copy
        that EXISTS on the shared store right now?  (An optimistic async
        replica_path whose upload failed does not count.)"""
        import os as _os

        probe = _os.path.exists if probe is None else probe
        return (
            t.state == "completed"
            and bool(t.partitions)
            and all(
                p.replica_path and probe(p.replica_path)
                for p in t.partitions
            )
        )

    def _victim_task_paths(self, executor_id: str, probe=None) -> set:
        """Output paths of the lost executor's completed map tasks that
        will have to RE-RUN (some partition has no surviving copy).
        Their locations must be stripped — never repointed — so the
        re-run's propagation is the single source of their data."""
        out: set = set()
        for stage in self.stages.values():
            statuses = getattr(stage, "task_statuses", None)
            if statuses is None:
                continue
            for t in statuses:
                if (
                    t is not None
                    and t.executor_id == executor_id
                    and t.state == "completed"
                    and not self._fully_replicated(t, probe)
                ):
                    out.update(p.path for p in t.partitions)
        return out

    def handoff_task(self, partition: PartitionId, executor_id: str) -> bool:
        """Graceful-decommission handoff: a DRAINING executor cancelled
        (or otherwise failed) this task — re-queue it excluded from the
        drainer WITHOUT consuming the failure budget (the attempt bump
        keeps the drainer's late reports stale; the free attempt keeps
        the budget whole).  A duplicate copy on the drainer just drops.
        Returns True when the report was absorbed as a handoff."""
        stage = self.stages.get(partition.stage_id)
        if not isinstance(stage, RunningStage):
            return False
        p = partition.partition_id
        if not (0 <= p < stage.partitions):
            return False
        si = stage.speculative_statuses.get(p)
        if si is not None and si.executor_id == executor_id:
            stage.drop_speculative(p)
            stage.bump_spec_stat("wasted")
            self.spec_wasted_pending += 1
            return True
        t = stage.task_statuses[p]
        if t is None or t.state != "running" or t.executor_id != executor_id:
            return False
        cur = stage.task_attempts.get(p, 0)
        stage.task_statuses[p] = None
        stage.task_started_mono.pop(p, None)
        stage.task_exclusions[p] = executor_id
        stage.task_attempts[p] = cur + 1
        stage.task_free_attempts[p] = stage.task_free_attempts.get(p, 0) + 1
        self._journal(
            "drain_handoff",
            stage=partition.stage_id,
            partition=p,
            executor=executor_id,
        )
        return True

    def reset_stages(self, executor_id: str) -> int:
        """Executor-loss rollback (reference: execution_graph.rs:499-622),
        replica-aware (ISSUE 6):

        * re-point locations with a surviving external-store copy at the
          replica FIRST — those partitions are not lost, consumers keep
          (or re-resolve to) working locations and nothing recomputes;
        * clear running tasks assigned to the executor;
        * strip its un-replicated partition locations from unresolved
          stages' inputs;
        * roll Running/Resolved stages whose inputs truly lost data back
          to UnResolved;
        * re-run Completed stages' map tasks only where some output
          partition has NO surviving copy.

        Returns the number of affected/re-pointed stages; only genuine
        rollbacks (not repoints) consume the stage_max_attempts ledger."""
        affected = set()

        # 0) surviving copies first: annotate drain-uploaded partitions,
        #    split the lost executor's completed tasks into survivors
        #    (every partition has an existing copy) and victims (must
        #    re-run), then re-point the SURVIVORS' input locations — the
        #    strip/rollback passes below only ever see genuine losses,
        #    and a victim's locations are never half-repointed (the
        #    re-run re-propagates the whole task; a lingering sentinel
        #    copy would duplicate its rows at the consumer)
        probe = self._exists_memo()  # one stat per replica path per loss
        repointed = self._annotate_completed_replicas(executor_id, probe)
        victim_paths = self._victim_task_paths(executor_id, probe)
        repointed += self._repoint_inputs(
            executor_id, skip_paths=victim_paths, probe=probe
        )

        # 1) running stages: reset that executor's tasks (duplicates the
        #    stage drops count toward the wasted registry counter).  A
        #    COMPLETED task whose every partition has a surviving copy
        #    is kept — its propagated locations were just repointed, so
        #    a 90%-done stage on a drained executor re-runs nothing.
        for sid, stage in list(self.stages.items()):
            if isinstance(stage, RunningStage):
                wasted_before = stage.spec_stats.get("wasted", 0)
                if stage.reset_tasks(
                    executor_id,
                    keep_task=lambda t: self._fully_replicated(t, probe),
                ):
                    affected.add(sid)
                self.spec_wasted_pending += (
                    stage.spec_stats.get("wasted", 0) - wasted_before
                )

        # 2) strip lost input locations everywhere; find consumers that lost
        #    data and must re-resolve
        rollback_consumers = set()
        rerun_producers = set()
        for sid, stage in list(self.stages.items()):
            if isinstance(stage, UnresolvedStage):
                before = _locations_of(stage, executor_id)
                if before:
                    stage.remove_input_partitions(executor_id)
                    affected.add(sid)
                    # a producer that already COMPLETED on the lost
                    # executor has no rolled-back consumer to nominate it
                    # below — without this the consumer waits forever on
                    # an input nobody re-runs (step 4 ignores producers
                    # that are merely mid-flight)
                    for in_sid, inp in stage.inputs.items():
                        if not inp.complete:
                            rerun_producers.add(in_sid)
            elif isinstance(stage, (ResolvedStage, RunningStage)):
                lost = any(
                    any(
                        l.executor_meta.id == executor_id
                        for locs in inp.partition_locations.values()
                        for l in locs
                    )
                    for inp in stage.inputs.values()
                )
                # a tailing consumer whose FEED served the lost executor
                # rolls back even when replica repoint cleaned its inputs:
                # the stream already shipped dead locations executor-side,
                # and a stream in flight cannot be patched (pipelined
                # failure semantics ride the existing reset path)
                if not lost and stage.tail_inputs:
                    lost = any(
                        self._feed_serves_executor(f_sid, executor_id)
                        for f_sid in stage.tail_inputs
                    )
                if lost:
                    rollback_consumers.add(sid)

        # 3) roll back consumers to unresolved
        for sid in rollback_consumers:
            stage = self.stages[sid]
            if isinstance(stage, RunningStage):
                if stage.tail_inputs:
                    # half-streamed consumer: abort its in-flight tasks
                    # (their tailing fetch plans reference the dead feed)
                    # and tear the feeds down — the re-resolve recreates
                    # them at the next epoch.  Barrier-path consumers keep
                    # the pre-existing semantics (late statuses are
                    # dropped by the rolled-back-stage guard).
                    for t in stage.task_statuses:
                        if t is not None and t.state == "running":
                            self.pending_cancels.append(
                                (t.executor_id, t.partition_id)
                            )
                    for si in stage.speculative_statuses.values():
                        self.pending_cancels.append(
                            (si.executor_id, si.partition_id)
                        )
                    for f_sid in sorted(stage.tail_inputs):
                        self._invalidate_feed(f_sid)
                stage = stage.to_resolved()
            assert isinstance(stage, ResolvedStage)
            if stage.tail_inputs:
                for f_sid in sorted(stage.tail_inputs):
                    self._invalidate_feed(f_sid)
            unresolved = stage.to_unresolved()
            unresolved.remove_input_partitions(executor_id)
            # any input stage whose data was lost must re-run
            for in_sid, inp in unresolved.inputs.items():
                if not inp.complete:
                    rerun_producers.add(in_sid)
            self.stages[sid] = unresolved
            affected.add(sid)

        # 4) completed producers re-run ONLY the victim map tasks (some
        #    partition without an EXISTING copy — same split as step 0);
        #    fully-replicated tasks keep their re-pointed locations
        for sid in sorted(rerun_producers):
            stage = self.stages.get(sid)
            if isinstance(stage, CompletedStage):
                victims = [
                    i
                    for i, t in enumerate(stage.task_statuses)
                    if t is not None
                    and t.executor_id == executor_id
                    and not self._fully_replicated(t, probe)
                ]
                if not victims:
                    continue
                running = stage.to_running()
                for i in victims:
                    running.task_statuses[i] = None
                self.stages[sid] = running
                self._invalidate_feed(sid)  # re-run supersedes the feed
                affected.add(sid)

        # 5) bound the rollback: a stage reset more than
        #    ballista.stage.max_attempts times means the cluster is
        #    flapping faster than the job can make progress — fail it
        #    with the reset ledger instead of looping forever
        for sid in affected:
            count = self.stage_reset_counts.get(sid, 0) + 1
            self.stage_reset_counts[sid] = count
            if count >= self.stage_max_attempts and self.status != FAILED:
                self.status = FAILED
                self.error = (
                    f"stage {sid} reset {count} times after executor loss "
                    f"(last: {executor_id}); exceeded "
                    f"ballista.stage.max_attempts={self.stage_max_attempts}"
                )
        if self.status == FAILED:
            return len(affected)

        if affected and self.status == COMPLETED:
            self.status = RUNNING
        self.revive()
        if affected or repointed:
            # replica repoint / executor-loss rollback: the post-mortem
            # distinguishes "consumers re-pointed at replicas, nothing
            # recomputed" from a genuine rollback storm
            self._journal(
                "executor_rollback",
                executor=executor_id,
                stages_affected=sorted(affected),
                locations_repointed=repointed,
            )
        # repoint-only changes (no rollback) still mutated locations and
        # must persist — report them without burning the reset ledger
        return len(affected) if affected else (1 if repointed else 0)

    # -------------------------------------------------------- persistence
    def encode(self) -> bytes:
        from ..serde import BallistaCodec

        g = pb.ExecutionGraphProto()
        g.job_id = self.job_id
        g.session_id = self.session_id
        g.scheduler_id = self.scheduler_id
        g.output_partitions = self.output_partitions
        g.task_max_attempts = self.task_max_attempts
        g.stage_max_attempts = self.stage_max_attempts
        g.task_retries = self.task_retries
        g.external_shuffle_path = self.external_shuffle_path
        # job-level timeline anchors: the original submit wall-clock and
        # planning duration must survive eviction/restart or every
        # relative timestamp in the breakdown shifts to decode time
        g.submitted_unix_us = self.submitted_unix_ns // 1000
        g.planning_us = getattr(self, "planning_ns", 0) // 1000
        if self.aqe_policy.enabled:
            g.aqe_settings_json = self.aqe_policy.to_json()
        if self.admission_enabled:
            g.tenant_json = json.dumps(
                {"pool": self.tenant_pool, "priority": self.tenant_priority}
            )
        for sid in sorted(self.stage_reset_counts):
            g.stage_reset_ids.append(sid)
            g.stage_reset_counts.append(self.stage_reset_counts[sid])
        if self.cache_served or self.cache_elided:
            g.cache_json = json.dumps(
                {
                    "served": {
                        str(s): fp for s, fp in self.cache_served.items()
                    },
                    "elided": sorted(self.cache_elided),
                }
            )
        if self.status == QUEUED:
            g.status.queued.SetInParent()
        elif self.status == RUNNING:
            g.status.running.SetInParent()
        elif self.status == FAILED:
            g.status.failed.error = self.error
        else:
            for loc in self.output_locations:
                g.status.completed.partition_location.add().CopyFrom(loc.to_proto())
        for sid in sorted(self.stages):
            stage = self.stages[sid]
            sp = g.stages.add()
            if isinstance(stage, RunningStage):
                stage = stage.to_resolved()  # re-dispatch on restart
            if isinstance(stage, ResolvedStage) and stage.tail_inputs:
                # partially-resolved (pipelined): the location feed is
                # in-memory only, so persist as Unresolved — a restarted
                # scheduler re-resolves from the producers' real state
                stage = stage.to_unresolved()
            if isinstance(stage, UnresolvedStage):
                sp.unresolved.stage_id = sid
                sp.unresolved.plan = BallistaCodec.encode_physical(stage.plan)
                sp.unresolved.output_links.extend(stage.output_links)
                _encode_inputs(sp.unresolved.inputs, stage.inputs)
                if stage.aqe:
                    sp.unresolved.aqe_summary_json = json.dumps(stage.aqe)
            elif isinstance(stage, ResolvedStage):
                sp.resolved.stage_id = sid
                sp.resolved.partitions = stage.partitions
                sp.resolved.plan = BallistaCodec.encode_physical(stage.plan)
                sp.resolved.output_links.extend(stage.output_links)
                _encode_inputs(sp.resolved.inputs, stage.inputs)
                if stage.aqe:
                    sp.resolved.aqe_summary_json = json.dumps(stage.aqe)
            elif isinstance(stage, CompletedStage):
                sp.completed.stage_id = sid
                sp.completed.partitions = stage.partitions
                sp.completed.plan = BallistaCodec.encode_physical(stage.plan)
                sp.completed.output_links.extend(stage.output_links)
                _encode_inputs(sp.completed.inputs, stage.inputs)
                # merged operator metrics survive completion: the REST
                # detail and /api/jobs/{id}/profile read them from the
                # persisted graph once the cache entry is evicted
                for op, vals in stage.stage_metrics.items():
                    m = sp.completed.stage_metrics.add()
                    m.operator_name = op
                    for k, v in vals.items():
                        m.values[k] = int(v)
                sp.completed.speculative_launched = stage.spec_stats.get(
                    "launched", 0
                )
                sp.completed.speculative_wins = stage.spec_stats.get("wins", 0)
                sp.completed.speculative_wasted = stage.spec_stats.get(
                    "wasted", 0
                )
                for t in stage.task_statuses:
                    if t is None:
                        continue
                    ts = sp.completed.task_statuses.add()
                    ts.task_id.CopyFrom(t.partition_id.to_proto())
                    ts.attempt = stage.task_attempts.get(
                        t.partition_id.partition_id, t.attempt
                    )
                    ts.fetch_retries = stage.task_fetch_retries.get(
                        t.partition_id.partition_id, t.fetch_retries
                    )
                    ts.completed.executor_id = t.executor_id
                    for p in t.partitions:
                        ts.completed.partitions.add().CopyFrom(p.to_proto())
            elif isinstance(stage, FailedStage):
                sp.failed.stage_id = sid
                sp.failed.partitions = stage.partitions
                sp.failed.plan = BallistaCodec.encode_physical(stage.plan)
                sp.failed.output_links.extend(stage.output_links)
                sp.failed.error = stage.error
        return g.SerializeToString()

    @classmethod
    def decode(cls, data: bytes, work_dir: str = "/tmp/ballista-tpu") -> "ExecutionGraph":
        from ..serde import BallistaCodec

        g = pb.ExecutionGraphProto.FromString(data)
        self = cls.__new__(cls)
        self.scheduler_id = g.scheduler_id
        self.job_id = g.job_id
        self.session_id = g.session_id
        self.trace_id = ""  # traces don't survive restart/adoption
        # the WALL submit anchor is persisted (timeline attribution must
        # not shift to decode time); the monotonic one cannot be — live
        # elapsed/SLO math restarts from adoption
        self.submitted_unix_ns = (
            g.submitted_unix_us * 1000 if g.submitted_unix_us else time.time_ns()
        )
        self.submitted_mono_ns = time.monotonic_ns()
        self.planning_ns = g.planning_us * 1000
        self.output_partitions = g.output_partitions
        self.output_locations = []
        self.error = ""
        # restart/HA adoption must keep the session's bounds and the spent
        # budgets — a fresh budget per failover would unbound the loops
        self.task_max_attempts = g.task_max_attempts or DEFAULT_TASK_MAX_ATTEMPTS
        self.stage_max_attempts = g.stage_max_attempts or DEFAULT_STAGE_MAX_ATTEMPTS
        self.task_retries = g.task_retries
        self.external_shuffle_path = g.external_shuffle_path
        self.stage_reset_counts = dict(
            zip(g.stage_reset_ids, g.stage_reset_counts)
        )
        self.cache_served = {}
        self.cache_elided = set()
        self.pending_cache_invalidations = []
        if g.cache_json:
            try:
                c = json.loads(g.cache_json)
                self.cache_served = {
                    int(k): v for k, v in (c.get("served") or {}).items()
                }
                self.cache_elided = set(c.get("elided") or [])
            except (ValueError, TypeError, AttributeError):
                pass
        # speculation/deadline policy is session-config derived and not
        # persisted: a recovered/adopted graph runs without it until its
        # stages complete (timing anchors are gone anyway); locality
        # placement likewise (preferred hosts re-derive on re-resolve)
        self._init_speculation_policy(None)
        self._init_locality_policy(None)
        # tenant identity IS persisted: pool concurrency accounting and
        # fair dispatch ordering must survive restart / HA adoption
        self._init_tenant(None)
        # pipelined execution is session-config derived and not persisted:
        # a recovered/adopted graph runs the barrier scheduler (partial
        # stages were stored as Unresolved, so nothing dangles)
        self._init_pipelining(None)
        if g.tenant_json:
            try:
                tenant = json.loads(g.tenant_json)
                self.admission_enabled = True
                self.tenant_pool = tenant.get("pool") or "default"
                self.tenant_priority = tenant.get("priority") or "batch"
            except (ValueError, TypeError, AttributeError):
                pass
        # AQE policy IS persisted: stats and already-made decisions live
        # in the stage protos, so a restarted scheduler replays the same
        # rewrites for stages that resolve after the failover
        from .adaptive import AqePolicy

        self.aqe_policy = AqePolicy.from_json(g.aqe_settings_json)
        self.pending_cancels = []
        self.pending_events = []
        self.spec_wasted_pending = 0
        which = g.status.WhichOneof("status")
        if which == "queued":
            self.status = QUEUED
        elif which == "running":
            self.status = RUNNING
        elif which == "failed":
            self.status = FAILED
            self.error = g.status.failed.error
        else:
            self.status = COMPLETED
            self.output_locations = [
                PartitionLocation.from_proto(l)
                for l in g.status.completed.partition_location
            ]
        self.stages = {}
        max_sid = 0
        for sp in g.stages:
            which = sp.WhichOneof("stage")
            if which == "unresolved":
                s = sp.unresolved
                stage: Stage = UnresolvedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    _decode_inputs(s.inputs),
                    aqe=_decode_aqe(s.aqe_summary_json),
                )
            elif which == "resolved":
                s = sp.resolved
                stage = ResolvedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    _decode_inputs(s.inputs),
                    aqe=_decode_aqe(s.aqe_summary_json),
                )
            elif which == "completed":
                s = sp.completed
                statuses: List[Optional[TaskInfo]] = [None] * s.partitions
                attempts: Dict[int, int] = {}
                fetch_retries: Dict[int, int] = {}
                for ts in s.task_statuses:
                    pid = PartitionId.from_proto(ts.task_id)
                    statuses[pid.partition_id] = TaskInfo(
                        pid,
                        "completed",
                        ts.completed.executor_id,
                        partitions=[
                            ShuffleWritePartition.from_proto(p)
                            for p in ts.completed.partitions
                        ],
                        attempt=ts.attempt,
                        fetch_retries=ts.fetch_retries,
                    )
                    if ts.attempt:
                        attempts[pid.partition_id] = ts.attempt
                    if ts.fetch_retries:
                        fetch_retries[pid.partition_id] = ts.fetch_retries
                spec_stats = {
                    k: v
                    for k, v in (
                        ("launched", s.speculative_launched),
                        ("wins", s.speculative_wins),
                        ("wasted", s.speculative_wasted),
                    )
                    if v
                }
                stage = CompletedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    _decode_inputs(s.inputs),
                    statuses,
                    stage_metrics={
                        m.operator_name: dict(m.values)
                        for m in s.stage_metrics
                    },
                    task_attempts=attempts,
                    task_fetch_retries=fetch_retries,
                    spec_stats=spec_stats,
                )
            else:
                s = sp.failed
                stage = FailedStage(
                    s.stage_id,
                    BallistaCodec.decode_physical(s.plan, work_dir),
                    list(s.output_links),
                    s.error,
                )
            self.stages[stage.stage_id] = stage
            max_sid = max(max_sid, stage.stage_id)
        self._final_stage_id = max_sid
        # a broadcast decision PENDING at failover (build side completed
        # small, consumer still unresolved) replays now: completion events
        # never re-fire for already-Completed stages on the adopting
        # scheduler, and the conversion is idempotent (a converted
        # consumer carries its aqe marker, persisted above)
        for sid in sorted(self.stages):
            if isinstance(self.stages.get(sid), CompletedStage):
                self._maybe_broadcast(sid)
        return self


def _encode_inputs(out, inputs: Dict[int, StageInput]) -> None:
    for sid, inp in inputs.items():
        m = out.add()
        m.stage_id = sid
        m.complete = inp.complete
        for locs in inp.partition_locations.values():
            for l in locs:
                m.partition_locations.add().CopyFrom(l.to_proto())


def _decode_aqe(raw: str) -> Dict[str, int]:
    if not raw:
        return {}
    try:
        return dict(json.loads(raw))
    except Exception:  # noqa: BLE001 - tolerate future/garbage payloads
        return {}


def _decode_inputs(msgs) -> Dict[int, StageInput]:
    out: Dict[int, StageInput] = {}
    for m in msgs:
        inp = StageInput(complete=m.complete)
        for l in m.partition_locations:
            inp.add_partition(PartitionLocation.from_proto(l))
        out[m.stage_id] = inp
    return out


def preferred_hosts_of(plan, n_tasks: int) -> Dict[int, str]:
    """task index -> normalized host holding the most input bytes, from
    the resolved plan's ShuffleReaderExec location lists (exact
    per-partition wire sizes recorded at shuffle-write time).  Tasks
    whose inputs carry no sized, host-addressed location (external-store
    sentinel, empty partitions) get no preference."""
    from ..shuffle.execution_plans import ShuffleReaderExec
    from ..shuffle.transport import normalize_host

    by_task: Dict[int, Dict[str, int]] = {}
    stack = [plan]
    while stack:
        node = stack.pop()
        if isinstance(node, ShuffleReaderExec):
            for p, locs in enumerate(node.partition):
                if p >= n_tasks:
                    break
                for l in locs:
                    host = normalize_host(
                        getattr(l.executor_meta, "host", "") or ""
                    )
                    if not host:
                        continue
                    nb = int(
                        getattr(l.partition_stats, "num_bytes", 0) or 0
                    )
                    if nb <= 0:
                        continue
                    hosts = by_task.setdefault(p, {})
                    hosts[host] = hosts.get(host, 0) + nb
        stack.extend(node.children())
    return {
        # deterministic argmax: bytes desc, then host name
        p: max(sorted(hosts), key=lambda h: hosts[h])
        for p, hosts in by_task.items()
    }


def _locations_of(stage: UnresolvedStage, executor_id: str) -> int:
    return sum(
        1
        for inp in stage.inputs.values()
        for locs in inp.partition_locations.values()
        for l in locs
        if l.executor_meta.id == executor_id
    )


def _build_stages(stage_plans: List[ShuffleWriterExec]) -> Dict[int, Stage]:
    """Infer the DAG from UnresolvedShuffleExec leaves
    (reference: ExecutionStageBuilder, execution_graph.rs:941-1038)."""
    dependencies: Dict[int, List[int]] = {}  # stage -> stages it reads
    for sp in stage_plans:
        dependencies[sp.stage_id] = [
            sh.stage_id for sh in find_unresolved_shuffles(sp)
        ]

    output_links: Dict[int, List[int]] = {sp.stage_id: [] for sp in stage_plans}
    for consumer, producers in dependencies.items():
        for p in producers:
            output_links[p].append(consumer)

    stages: Dict[int, Stage] = {}
    for sp in stage_plans:
        inputs = {p: StageInput() for p in dependencies[sp.stage_id]}
        if inputs:
            stages[sp.stage_id] = UnresolvedStage(
                sp.stage_id, sp, output_links[sp.stage_id], inputs
            )
        else:
            # leaf stage: immediately resolvable
            stages[sp.stage_id] = ResolvedStage(
                sp.stage_id, sp, output_links[sp.stage_id], {}
            )
    return stages
