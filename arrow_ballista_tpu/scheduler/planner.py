"""Distributed planner: split a physical plan into shuffle-bounded stages.

Counterpart of the reference's ``scheduler/src/planner.rs``:

* recursive walk of the physical plan; at ``RepartitionExec(hash)`` insert a
  ``ShuffleWriterExec`` with that hash partitioning and replace the subtree
  with an ``UnresolvedShuffleExec`` placeholder (`planner.rs:127-156`);
* at ``CoalescePartitionsExec`` insert a ``ShuffleWriterExec`` with no
  repartitioning under the coalesce (`planner.rs:97-125`);
* non-hash repartitions are dropped (`planner.rs:157-164`);
* finally the root is wrapped in a ``ShuffleWriterExec`` with no
  partitioning — its output files are the job's result (`planner.rs:61-76`).

Also ``remove_unresolved_shuffles`` (swap placeholders for readers with real
locations once producing stages complete, `planner.rs:199-247`) and
``rollback_resolved_shuffles`` (the inverse, for executor-loss recovery,
`planner.rs:252-275`).
"""

from __future__ import annotations

from typing import Dict, List

from ..errors import PlanError
from ..exec.operators import (
    CoalescePartitionsExec,
    ExecutionPlan,
    RepartitionExec,
)
from ..serde.scheduler_types import PartitionLocation
from ..shuffle import ShuffleReaderExec, ShuffleWriterExec, UnresolvedShuffleExec


class DistributedPlanner:
    def __init__(self, work_dir: str = "/tmp/ballista-tpu", config=None):
        from ..config import BallistaConfig

        self.work_dir = work_dir
        self.config = config or BallistaConfig()
        self._next_stage_id = 0

    def _maybe_gang(self, plan: ExecutionPlan, part=None) -> ExecutionPlan:
        """TPU-native stage forms (two shapes):

        * the subtree fuses into a partial aggregate → MeshGangExec: the
          cross-partition exchange is a psum over ICI and only
          [capacity]-sized states reach the shuffle;
        * the stage feeds a hash repartition (``part``) → MeshRepartition-
          Exec: rows route to their output partition with one all_to_all
          over ICI and the writer persists pre-partitioned batches —
          replacing the per-partition hash-split + disk+Flight hop the
          reference always takes (shuffle_writer.rs:142-292, :201-285).
        """
        from ..parallel.mesh_stage import (
            MeshGangExec,
            MeshRepartitionExec,
            exchange_supported,
            gang_eligible,
        )

        if not (self.config.mesh_enable and self.config.tpu_enable):
            return plan
        if plan.output_partitioning().n <= 1:
            return plan  # single partition: nothing to gang
        if gang_eligible(plan):
            return MeshGangExec(plan, self.config.mesh_devices)
        if (
            part is not None
            and part.kind == "hash"
            and part.exprs
            and exchange_supported(plan.schema)
        ):
            return MeshRepartitionExec(plan, part, self.config.mesh_devices)
        return plan

    def _new_stage_id(self) -> int:
        self._next_stage_id += 1
        return self._next_stage_id

    def plan_query_stages(
        self, job_id: str, plan: ExecutionPlan
    ) -> List[ShuffleWriterExec]:
        """Return all stages; the last entry is the job's root stage."""
        stages, root = self._plan(job_id, plan)
        stages.append(self._create_shuffle_writer(job_id, root, None))
        return stages

    def _plan(
        self, job_id: str, plan: ExecutionPlan
    ) -> tuple[List[ShuffleWriterExec], ExecutionPlan]:
        stages: List[ShuffleWriterExec] = []
        children = []
        for child in plan.children():
            child_stages, child_plan = self._plan(job_id, child)
            stages.extend(child_stages)
            children.append(child_plan)

        if isinstance(plan, CoalescePartitionsExec):
            writer = self._create_shuffle_writer(
                job_id, self._maybe_gang(children[0]), None
            )
            stages.append(writer)
            placeholder = UnresolvedShuffleExec(
                writer.stage_id,
                writer.input_schema,
                writer.output_partitioning().n,
                # no repartition: one output file per input partition
                writer.output_partitioning().n,
            )
            return stages, plan.with_new_children([placeholder])

        if isinstance(plan, RepartitionExec):
            part = plan.partitioning
            if part.kind == "hash":
                writer = self._create_shuffle_writer(
                    job_id, self._maybe_gang(children[0], part), part
                )
                stages.append(writer)
                placeholder = UnresolvedShuffleExec(
                    writer.stage_id,
                    writer.input_schema,
                    writer.output_partitioning().n,
                    part.n,
                )
                return stages, placeholder
            # round-robin / unknown repartitions add nothing across a
            # process boundary: drop the node (reference planner.rs:157-164)
            return stages, children[0]

        if children:
            return stages, plan.with_new_children(children)
        return stages, plan

    def _create_shuffle_writer(
        self, job_id: str, plan: ExecutionPlan, partitioning
    ) -> ShuffleWriterExec:
        return ShuffleWriterExec(
            job_id, self._new_stage_id(), plan, self.work_dir, partitioning
        )


def classify_shuffle_inputs(plan: ExecutionPlan) -> tuple:
    """Pipelined-execution eligibility walk (ISSUE 15): split a stage
    plan's shuffle inputs into ``(streamable, breakers)`` — sets of
    producing stage ids.

    A shuffle input is *streamable* when no pipeline-breaking operator
    sits between the shuffle read and the stage root, so the stage can
    start consuming the producer's output before every map task has
    committed: filter, project, union, limit, aggregates (partial OR
    final — they consume a stream; a final agg still cannot EMIT early,
    but it can overlap its reads with the producing stage's tail) and
    the PROBE side of a hash join all pass through.  ``SortExec`` and
    ``WindowExec`` (which sorts internally) are breakers, as is the
    BUILD (left) side of any join — a build-side read gains nothing
    from starting early and would pin a slot against the barrier
    anyway.  Leaves are matched by ``stage_id`` attribute, so the walk
    classifies both unresolved placeholders and already-resolved
    readers (the doctor runs it over completed stages too).  A stage id
    reachable both ways (self-join of one producer) classifies as a
    breaker — partial start must be safe for EVERY read of that input.
    """
    from ..exec.joins import CrossJoinExec, HashJoinExec
    from ..exec.operators import SortExec
    from ..exec.window import WindowExec

    streamable: set = set()
    breakers: set = set()

    def walk(node: ExecutionPlan, under_breaker: bool) -> None:
        if isinstance(node, (UnresolvedShuffleExec, ShuffleReaderExec)):
            (breakers if under_breaker else streamable).add(node.stage_id)
            return
        if isinstance(node, (SortExec, WindowExec)):
            under_breaker = True
        children = node.children()
        if isinstance(node, (HashJoinExec, CrossJoinExec)) and children:
            walk(children[0], True)  # build side barriers
            for c in children[1:]:
                walk(c, under_breaker)
            return
        for c in children:
            walk(c, under_breaker)

    walk(plan, False)
    # an input read through BOTH a streamable and a breaker edge must
    # barrier for the breaker read
    streamable -= breakers
    return streamable, breakers


def find_unresolved_shuffles(plan: ExecutionPlan) -> List[UnresolvedShuffleExec]:
    out: List[UnresolvedShuffleExec] = []
    if isinstance(plan, UnresolvedShuffleExec):
        out.append(plan)
    for c in plan.children():
        out.extend(find_unresolved_shuffles(c))
    return out


def remove_unresolved_shuffles(
    plan: ExecutionPlan,
    partition_locations: Dict[int, List[List[PartitionLocation]]],
    tail_stage_ids: frozenset = frozenset(),
) -> ExecutionPlan:
    """Swap every UnresolvedShuffleExec for a ShuffleReaderExec with the
    producing stage's real output locations.

    ``partition_locations[stage]`` is always keyed by SOURCE reduce
    partition; a placeholder carrying AQE ``selections`` maps those
    source lists onto its coalesced/split task layout here, so two
    leaves reading the same producer stage can do so through different
    layouts (e.g. the split side and the duplicated side of a skew-split
    join).

    ``tail_stage_ids`` (pipelined execution, ISSUE 15): producers whose
    output is still GROWING — their leaves resolve to TAILING readers
    that carry no static locations and instead stream the scheduler's
    shuffle-location feed at execution time (``shuffle/delta_store``).
    Only valid for selections-free leaves (partial resolution is gated
    off for AQE-rewritten layouts)."""
    if isinstance(plan, UnresolvedShuffleExec):
        from ..shuffle.execution_plans import apply_read_selections

        if plan.stage_id in tail_stage_ids:
            if plan.selections is not None:
                raise PlanError(
                    f"stage {plan.stage_id}: cannot tail an AQE-rewritten "
                    "shuffle read"
                )
            return ShuffleReaderExec(
                plan.stage_id,
                plan.schema,
                [[] for _ in range(plan.output_partition_count)],
                source_partition_count=plan.output_partition_count,
                tail=True,
            )
        locs = partition_locations.get(plan.stage_id)
        if locs is None:
            raise PlanError(
                f"no partition locations for stage {plan.stage_id}"
            )
        if len(locs) != plan.output_partition_count:
            raise PlanError(
                f"stage {plan.stage_id}: expected "
                f"{plan.output_partition_count} output partitions, got {len(locs)}"
            )
        if plan.selections is not None:
            locs = apply_read_selections(plan.selections, locs)
        return ShuffleReaderExec(
            plan.stage_id,
            plan.schema,
            locs,
            selections=plan.selections,
            source_partition_count=plan.output_partition_count,
        )
    children = plan.children()
    if not children:
        return plan
    return plan.with_new_children(
        [
            remove_unresolved_shuffles(c, partition_locations, tail_stage_ids)
            for c in children
        ]
    )


def rollback_resolved_shuffles(plan: ExecutionPlan) -> ExecutionPlan:
    """Inverse of remove_unresolved_shuffles (executor-loss recovery).

    An AQE-rewritten reader rolls back to a placeholder carrying the
    SAME selections, so the re-resolved consumer keeps its adaptive
    task layout instead of silently reverting to the static plan (whose
    partition indexing the reader's task count no longer matches)."""
    if isinstance(plan, ShuffleReaderExec):
        n_src = (
            plan.source_partition_count
            if plan.source_partition_count
            else len(plan.partition)
        )
        # input partition count is not recoverable from the reader alone and
        # is not needed to re-resolve; re-derived when the stage re-completes
        return UnresolvedShuffleExec(
            plan.stage_id, plan.schema, n_src, n_src,
            selections=plan.selections,
        )
    children = plan.children()
    if not children:
        return plan
    return plan.with_new_children(
        [rollback_resolved_shuffles(c) for c in children]
    )
