"""Session lifecycle on the scheduler.

Counterpart of the reference's ``scheduler/src/state/session_manager.rs`` +
``session_registry.rs``: per-session config settings persisted in the
Sessions keyspace, an in-memory registry of live ``SessionContext``s, and a
``session_builder`` injection point so embedders can customize context
construction (the reference's Python bindings use that hook to install
custom planners).
"""

from __future__ import annotations

import threading
import uuid
from typing import Callable, Dict, Optional

from ..config import BallistaConfig
from ..context import SessionContext
from ..proto import pb
from .backend import Keyspace, StateBackend

SessionBuilder = Callable[[BallistaConfig], SessionContext]


def default_session_builder(config: BallistaConfig) -> SessionContext:
    return SessionContext(config)


class SessionManager:
    def __init__(
        self,
        backend: StateBackend,
        session_builder: SessionBuilder = default_session_builder,
    ):
        self.backend = backend
        self.session_builder = session_builder
        self._registry: Dict[str, SessionContext] = {}
        self._lock = threading.Lock()

    def create_session(self, settings: Dict[str, str]) -> SessionContext:
        config = BallistaConfig(dict(settings))
        ctx = self.session_builder(config)
        ctx.session_id = uuid.uuid4().hex[:16]
        self._persist(ctx.session_id, settings)
        with self._lock:
            self._registry[ctx.session_id] = ctx
        return ctx

    def update_session(
        self, session_id: str, settings: Dict[str, str]
    ) -> SessionContext:
        config = BallistaConfig(dict(settings))
        with self._lock:
            ctx = self._registry.get(session_id)
            if ctx is not None:
                ctx.config = config
            else:
                ctx = self.session_builder(config)
                ctx.session_id = session_id
                self._registry[session_id] = ctx
        self._persist(session_id, settings)
        return ctx

    def get_session(self, session_id: str) -> Optional[SessionContext]:
        with self._lock:
            ctx = self._registry.get(session_id)
        if ctx is not None:
            return ctx
        # rebuild from persisted settings (scheduler restart)
        raw = self.backend.get(Keyspace.Sessions, session_id)
        if raw is None:
            return None
        msg = pb.SessionSettings.FromString(raw)
        settings = {kv.key: kv.value for kv in msg.configs}
        ctx = self.session_builder(BallistaConfig(settings))
        ctx.session_id = session_id
        with self._lock:
            self._registry[session_id] = ctx
        return ctx

    def _persist(self, session_id: str, settings: Dict[str, str]) -> None:
        msg = pb.SessionSettings()
        for k, v in settings.items():
            msg.configs.add(key=k, value=v)
        self.backend.put(Keyspace.Sessions, session_id, msg.SerializeToString())
