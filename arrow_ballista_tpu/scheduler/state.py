"""Scheduler state facade bundling the managers.

Counterpart of the reference's ``scheduler/src/state/mod.rs``: owns the
backend + executor/task/session managers, performs job planning on submit,
and implements ``offer_reservation`` — the fill-and-launch cycle shared by
push scheduling and the pull-mode poll handler.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import BallistaConfig, TaskSchedulingPolicy
from ..context import SessionContext
from ..errors import BallistaError
from ..exec.operators import ExecutionPlan
from ..exec.planner import PhysicalPlanner
from ..obs import trace
from ..obs.recorder import get_recorder, trace_store
from ..obs.registry import MetricsRegistry
from ..plan import logical as lp
from ..plan.optimizer import optimize
from ..serde.scheduler_types import ExecutorMetadata
from .backend import StateBackend
from .execution_graph import Task
from .execution_stage import TaskInfo
from .executor_manager import ExecutorManager, ExecutorReservation
from .session_manager import SessionBuilder, SessionManager, default_session_builder
from .task_manager import TaskLauncher, TaskManager

log = logging.getLogger(__name__)


class SchedulerState:
    def __init__(
        self,
        backend: StateBackend,
        scheduler_id: str,
        policy: TaskSchedulingPolicy = TaskSchedulingPolicy.PULL_STAGED,
        session_builder: SessionBuilder = default_session_builder,
        launcher: Optional[TaskLauncher] = None,
        work_dir: str = "/tmp/ballista-tpu",
        liveness_window_s: float = 60.0,
        quarantine_threshold: Optional[int] = None,
        quarantine_window_s: Optional[float] = None,
        quarantine_backoff_s: Optional[float] = None,
        speculation_force_enabled: bool = False,
        task_timeout_force_s: float = 0.0,
        aqe_force_enabled: bool = False,
        admission_force_enabled: bool = False,
        admission_defaults: Optional[Dict[str, str]] = None,
        admission_wal_enabled: bool = False,
        cache_force_enabled: bool = False,
        cache_policy_force_enabled: bool = False,
        cache_settings: Optional[Dict[str, str]] = None,
        event_journal_dir: str = "",
        event_journal_rotate_bytes: Optional[int] = None,
        event_journal_segments: Optional[int] = None,
    ):
        from ..obs.events import (
            DEFAULT_KEEP_SEGMENTS,
            DEFAULT_ROTATE_BYTES,
            EventJournal,
        )
        from ..obs.timeseries import ClusterTelemetry, SloTracker
        from .executor_manager import (
            DEFAULT_QUARANTINE_BACKOFF_S,
            DEFAULT_QUARANTINE_THRESHOLD,
            DEFAULT_QUARANTINE_WINDOW_S,
        )

        self.backend = backend
        self.scheduler_id = scheduler_id
        self.policy = policy
        # unified metrics: one registry per scheduler instance (a test
        # process may run several side by side) backing /api/metrics and
        # the Prometheus endpoint; managers register their counters here
        self.metrics = MetricsRegistry()
        # continuous cluster telemetry (ISSUE 7): heartbeat snapshots and
        # the scheduler's own aggregates land in bounded downsampling
        # rings behind /api/cluster/health + /api/cluster/timeseries
        self.telemetry = ClusterTelemetry(registry=self.metrics)
        # structured event journal (off unless a directory is configured;
        # emit() is then one attribute check) — managers below share it
        self.events = EventJournal(
            event_journal_dir,
            rotate_bytes=(
                DEFAULT_ROTATE_BYTES
                if event_journal_rotate_bytes is None
                else event_journal_rotate_bytes
            ),
            keep_segments=(
                DEFAULT_KEEP_SEGMENTS
                if event_journal_segments is None
                else event_journal_segments
            ),
        )
        # per-session job-latency SLO (ballista.obs.slo.job_latency_seconds)
        self.slo = SloTracker(self.metrics)
        self.executor_manager = ExecutorManager(
            backend,
            liveness_window_s,
            quarantine_threshold=(
                DEFAULT_QUARANTINE_THRESHOLD
                if quarantine_threshold is None
                else quarantine_threshold
            ),
            quarantine_window_s=(
                DEFAULT_QUARANTINE_WINDOW_S
                if quarantine_window_s is None
                else quarantine_window_s
            ),
            quarantine_backoff_s=(
                DEFAULT_QUARANTINE_BACKOFF_S
                if quarantine_backoff_s is None
                else quarantine_backoff_s
            ),
            registry=self.metrics,
            events=self.events,
        )
        # scheduler flags seed cluster-wide defaults that an EXPLICIT
        # session setting still wins over (session settings ship sparse)
        overrides: Dict[str, str] = dict(admission_defaults or {})
        overrides.update(cache_settings or {})
        if overrides:
            BallistaConfig(overrides)  # fail fast on a bad operator knob
        if aqe_force_enabled:
            overrides["ballista.aqe.enabled"] = "true"
        if admission_force_enabled:
            overrides["ballista.admission.enabled"] = "true"
        if cache_force_enabled:
            overrides["ballista.cache.enabled"] = "true"
        if cache_policy_force_enabled:
            overrides["ballista.cache.policy.enabled"] = "true"
        # multi-tenant front door (ISSUE 12): the admission queue +
        # weighted fair release.  Always constructed; it only ever acts
        # on jobs whose merged config has ballista.admission.enabled, so
        # the default-off path is byte-identical to a scheduler without
        # it.  Release/planning of queued jobs runs on the query-stage
        # event loop (query_stage_scheduler._admit_released).  Any
        # ballista.admission.* key the operator set is PINNED: cluster
        # limits then ignore whatever the submitting session says.
        from .admission import AdmissionController

        self.admission = AdmissionController(
            self.executor_manager,
            registry=self.metrics,
            events=self.events,
            pinned_settings=overrides,
        )
        # plan-fingerprint result/shuffle cache + learned per-plan policy
        # (ISSUE 18).  Always constructed — both layers are gated per-job
        # by ballista.cache.enabled / ballista.cache.policy.enabled, so a
        # default-off scheduler plans and dispatches byte-identically to
        # one without them.  Cached partitions live beside the external
        # shuffle store under the scheduler work dir.
        import os as _os

        from .plan_cache import PlanCache
        from .policy_store import PolicyStore

        self.plan_cache = PlanCache(_os.path.join(work_dir, "plan_cache"))
        self.policy_store = PolicyStore(
            _os.path.join(work_dir, "policy_store.json")
        )
        self.task_manager = TaskManager(
            backend, self.executor_manager, scheduler_id, launcher, work_dir,
            registry=self.metrics,
            events=self.events,
            slo=self.slo,
            config_overrides=overrides or None,
            admission=self.admission,
            plan_cache=self.plan_cache,
            policy_store=self.policy_store,
        )
        # durable admission queue (ISSUE 20): journal queued jobs +
        # cancel intents through the state backend so a restarted or
        # adopting scheduler replays them in submit order.  Off by
        # default — admission.wal stays None and every hook is a no-op.
        # The curator resolves lazily off the task manager because
        # __main__ finalizes the stable scheduler id after construction.
        self.admission_wal = None
        if admission_wal_enabled:
            from .queue_wal import AdmissionWal

            self.admission_wal = AdmissionWal(
                backend, lambda: self.task_manager.scheduler_id
            )
            self.admission.attach_wal(self.admission_wal)
        self.session_manager = SessionManager(backend, session_builder)
        # straggler mitigation: the periodic scan body (invoked on the
        # event-loop thread via the SpeculationScan event); the force
        # flags come from the scheduler binary and apply to every session
        from .speculation import SpeculationManager

        self.speculation = SpeculationManager(
            self,
            force_enabled=speculation_force_enabled,
            force_task_timeout_s=task_timeout_force_s,
        )
        # scrape-time gauges (computed on read, not pushed on change)
        self.metrics.gauge(
            "available_slots", "task slots free across alive executors",
            fn=self.executor_manager.available_slots,
        )
        self.metrics.gauge(
            "alive_executors", "executors inside the liveness window",
            fn=lambda: len(self.executor_manager.get_alive_executors()),
        )
        self.metrics.gauge(
            "active_jobs", "jobs currently cached as active",
            fn=lambda: len(self.task_manager.active_job_ids()),
        )
        self.metrics.gauge(
            "executors_quarantined", "executors currently in quarantine backoff",
            fn=lambda: len(self.executor_manager.quarantined_executors()),
        )
        self.metrics.gauge(
            "trace_store_spans", "spans held for /api/jobs/{id}/trace",
            fn=lambda: trace_store().span_count(),
        )
        # autoscaling/admission signals (ROADMAP item 3): queue depth and
        # slot saturation computed at scrape, recorded as history by the
        # SchedulerServer's cluster sampling loop
        # one task_counts() walk (it takes every cached job's entry lock)
        # feeds both gauges: the providers are read back-to-back in a
        # scrape, so a short memo halves the lock traffic without going
        # stale between scrapes
        counts_lock = threading.Lock()
        counts_state = {"mono": -1.0, "value": (0, 0)}

        def _task_counts_memo() -> Tuple[int, int]:
            with counts_lock:
                now = time.monotonic()
                if now - counts_state["mono"] > 0.1:
                    counts_state["value"] = self.task_manager.task_counts()
                    counts_state["mono"] = now
                return counts_state["value"]

        self.metrics.gauge(
            "pending_tasks", "dispatchable tasks waiting for a slot",
            fn=lambda: _task_counts_memo()[0],
        )
        self.metrics.gauge(
            "running_tasks", "tasks currently dispatched to executors",
            fn=lambda: _task_counts_memo()[1],
        )

    # ------------------------------------------------------------ planning
    def plan_job(
        self, session_ctx: SessionContext, plan: lp.LogicalPlan
    ) -> ExecutionPlan:
        """Logical → optimized → physical.  The TPU acceleration pass is NOT
        applied here: stage plans travel unaccelerated and each executor
        re-accelerates under its own session config."""
        optimized = optimize(plan)
        return PhysicalPlanner(session_ctx.config).create_physical_plan(optimized)

    def submit_job(
        self,
        job_id: str,
        session_ctx: SessionContext,
        plan: lp.LogicalPlan,
    ) -> str:
        """The scheduler's front door.  With admission enabled for this
        job (``ballista.admission.enabled`` — session setting or the
        ``--admission-enabled`` cluster default) the LOGICAL plan is
        offered to the admission controller FIRST: a saturated cluster
        holds the job queued pre-planning (no ExecutionGraph built, no
        memory pinned — returns ``"queued"``) or sheds it with a
        structured :class:`ClusterSaturated` raise.  The caller runs the
        release scan right after, so an uncontended job passes straight
        through.  Returns ``"submitted"`` once planned + submitted."""
        cfg = self._admission_config(session_ctx)
        if cfg.admission_enabled:
            decision = self.admission.offer(
                job_id, session_ctx.session_id, plan, cfg
            )
            for displaced, error in decision.displaced:
                # shed_policy=oldest displaced another session's queued
                # job to make room: fail it with the structured error
                self.task_manager.fail_job(displaced.job_id, error)
            if decision.error is not None:
                raise decision.error
            return "queued"
        self.submit_admitted_job(job_id, session_ctx, plan)
        return "submitted"

    def _admission_config(self, session_ctx: SessionContext) -> BallistaConfig:
        """Session settings over scheduler-flag defaults — the same
        merge TaskManager.submit_job applies at planning time."""
        settings = dict(self.task_manager.config_overrides)
        config = getattr(session_ctx, "config", None)
        if config is not None:
            settings.update(config.to_dict())
        return BallistaConfig(settings)

    def submit_admitted_job(
        self,
        job_id: str,
        session_ctx: SessionContext,
        plan: lp.LogicalPlan,
    ) -> None:
        """Plan + submit one job PAST the admission gate (direct path
        for admission-off jobs; the event loop's release handler for
        jobs coming off the queue)."""
        trace_id = self._maybe_start_trace(job_id, session_ctx)
        if trace_id:
            with trace.activate(trace_id), trace.span("job.plan", job=job_id):
                physical = self.plan_job(session_ctx, plan)
        else:
            physical = self.plan_job(session_ctx, plan)
        self.task_manager.submit_job(
            job_id, session_ctx.session_id, physical, trace_id=trace_id
        )
        # graph persisted (or terminal): the queue WAL entry is now
        # redundant — dropping it here (not at release) closes the
        # release→persist crash window
        self.admission.wal_discard(job_id)

    def _maybe_start_trace(self, job_id: str, session_ctx: SessionContext) -> str:
        """Mint the job's trace id when the session asks for observability
        (ratchets process tracing on; spans recorded in this process
        forward straight into the TraceStore — no transport needed).
        Returns "" for untraced/unsampled jobs."""
        config = getattr(session_ctx, "config", None)
        if config is None or not trace.enable_from_config(
            config, process="scheduler"
        ):
            return ""
        get_recorder().set_forward(trace_store().add)
        if not trace.sampled():
            return ""
        trace_id = trace.new_id()
        trace_store().bind(trace_id, job_id)
        return trace_id

    # ------------------------------------------------------------- updates
    def update_task_statuses(
        self, executor: ExecutorMetadata, statuses: List[TaskInfo]
    ) -> Tuple[List[Tuple[str, str]], List[ExecutorReservation]]:
        """Apply statuses; mint one reservation per finished task in push
        mode so freed slots immediately re-offer
        (reference: state/mod.rs:128-150)."""
        events = self.task_manager.update_task_statuses(executor, statuses)
        reservations = []
        if (
            self.policy == TaskSchedulingPolicy.PUSH_STAGED
            and not self.executor_manager.is_quarantined(executor.id)
            and not self.executor_manager.is_draining(executor.id)
        ):
            finished = sum(1 for s in statuses if s.state in ("completed", "failed"))
            reservations = [
                ExecutorReservation(executor.id) for _ in range(finished)
            ]
        return events, reservations

    # ------------------------------------------------------------ lifecycle
    def try_stop_executor(
        self, executor_id: str, reason: str, force: bool = True
    ) -> None:
        """Best-effort StopExecutor RPC on a detached thread (reference:
        scheduler_server/mod.rs:227-244).  Runs off-thread so the 5s RPC
        timeout against a dead host never stalls the caller — in
        particular the event-loop thread handling ExecutorLost."""
        try:
            meta = self.executor_manager.get_executor_metadata(executor_id)
        except Exception:  # noqa: BLE001 - already forgotten
            return
        if not meta.grpc_port:
            return

        def _stop() -> None:
            try:
                from ..proto import pb
                from ..proto.rpc import executor_stub

                executor_stub(meta.host, meta.grpc_port).StopExecutor(
                    pb.StopExecutorParams(
                        executor_id=executor_id, reason=reason, force=force
                    ),
                    timeout=5,
                )
            except Exception as e:  # noqa: BLE001 - executor may be gone
                log.debug("StopExecutor(%s) failed: %s", executor_id, e)

        threading.Thread(
            target=_stop, name="stop-executor", daemon=True
        ).start()

    # ------------------------------------------------------------ offering
    def offer_reservation(
        self, reservations: List[ExecutorReservation]
    ) -> Tuple[int, List[ExecutorReservation]]:
        """Fill reservations with tasks and launch them; returns
        (n_launched, leftover reservations to cancel or re-offer)
        (reference: state/mod.rs:188-248)."""
        assignments, free, pending = self.task_manager.fill_reservations(reservations)

        per_executor: Dict[str, List[Task]] = {}
        for executor_id, task in assignments:
            per_executor.setdefault(executor_id, []).append(task)

        launched = 0
        for executor_id, tasks in per_executor.items():
            try:
                meta = self.executor_manager.get_executor_metadata(executor_id)
                self.task_manager.launch_tasks(meta, tasks)
                launched += len(tasks)
            except BallistaError as e:
                log.warning("failed to launch tasks on %s: %s", executor_id, e)
                # tasks were reset by launch_tasks; slots go back too
                free.extend(ExecutorReservation(executor_id) for _ in tasks)

        if free and pending <= 0:
            self.executor_manager.cancel_reservations(free)
            free = []
        return launched, free
