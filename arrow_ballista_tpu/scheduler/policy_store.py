"""Learned per-plan policy store.

The doctor (obs/doctor.py) already diagnoses what ails a job — barrier-
dominated stages, locality misses, skew — and names the knob that fixes
each.  Today a human reads the finding and sets the knob.  This module
closes that loop: after every job it records the plan's *shape*
fingerprint (snapshot-free, so the same dashboard query matches across
data refreshes) together with the doctor's findings and the measured
latency; on the next submit of a matching plan it merges the learned knob
overrides *beneath* the session's explicit settings.

Safety rails, routing_table.json style — measured, never assumed:

* a ``shadow_fraction`` of submits (deterministic per job id) runs at
  baseline so there is always a live control population;
* an override whose applied-population median latency regresses past the
  shadow population's is auto-rolled-back and quarantined.

Inert unless ``ballista.cache.policy.enabled`` is set.
"""

from __future__ import annotations

import hashlib
import json
import os
import statistics
import threading
from typing import Any

from ..config import (
    AQE_ENABLED,
    AQE_SKEW_ENABLED,
    SHUFFLE_LOCALITY_ENABLED,
    SHUFFLE_PIPELINED,
)

__all__ = ["PolicyStore", "FINDING_OVERRIDES"]

# doctor finding code → the knob override it prescribes
FINDING_OVERRIDES: dict[str, dict[str, str]] = {
    "barrier_dominated_job": {SHUFFLE_PIPELINED: "true"},
    "locality_miss_stage": {SHUFFLE_LOCALITY_ENABLED: "true"},
    "skewed_stage": {AQE_ENABLED: "true", AQE_SKEW_ENABLED: "true"},
}

# rollback when applied median exceeds shadow median by this factor,
# with at least _MIN_SAMPLES observations on each side
_REGRESSION_FACTOR = 1.2
_MIN_SAMPLES = 3
_MAX_SAMPLES = 50  # per-population ring buffer


class PolicyStore:
    """Durable shape-fingerprint → learned-knob-overrides map."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        # fp → {"overrides": {key: val}, "baseline": [s], "applied": [s],
        #        "rolled_back": {key: reason}, "findings": [code],
        #        "jobs": int}
        self._plans: dict[str, dict] = {}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                self._plans = json.load(f)
        except (OSError, ValueError):
            self._plans = {}

    def _save_locked(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._plans, f)
        os.replace(tmp, self.path)

    # -- submit side ---------------------------------------------------------

    def overrides_for(
        self, job_id: str, shape_fp: str, shadow_fraction: float
    ) -> tuple[dict[str, str], str]:
        """Overrides to merge beneath session settings, and this job's arm.

        Returns ``({}, "baseline")`` for unknown plans, plans with nothing
        learned yet, and the shadow population (chosen deterministically
        from the job id so re-submits of one job are reproducible).
        """
        with self._lock:
            rec = self._plans.get(shape_fp)
            if not rec or not rec.get("overrides"):
                return {}, "baseline"
            if self._is_shadow(job_id, shadow_fraction):
                return {}, "shadow"
            return dict(rec["overrides"]), "applied"

    @staticmethod
    def _is_shadow(job_id: str, shadow_fraction: float) -> bool:
        if shadow_fraction <= 0:
            return False
        if shadow_fraction >= 1:
            return True
        h = int.from_bytes(
            hashlib.sha256(job_id.encode()).digest()[:4], "big"
        )
        return (h % 10_000) < shadow_fraction * 10_000

    # -- completion side -----------------------------------------------------

    def record_job(
        self,
        shape_fp: str,
        arm: str,
        latency_s: float,
        findings: list[dict | str] | None,
    ) -> list[dict]:
        """Fold one finished job into the plan's record.

        ``arm`` is what :meth:`overrides_for` returned at submit
        ("baseline" | "shadow" | "applied").  Baseline/shadow runs feed the
        control population and, via the doctor findings, may *learn* new
        overrides; applied runs feed the treatment population and may
        trigger rollback.  Returns a list of rollback events (possibly
        empty) for the caller to journal.
        """
        events: list[dict] = []
        with self._lock:
            rec = self._plans.setdefault(
                shape_fp,
                {
                    "overrides": {},
                    "baseline": [],
                    "applied": [],
                    "rolled_back": {},
                    "findings": [],
                    "jobs": 0,
                },
            )
            rec["jobs"] += 1
            pop = "applied" if arm == "applied" else "baseline"
            rec[pop].append(float(latency_s))
            del rec[pop][:-_MAX_SAMPLES]
            if arm != "applied":
                # learn: findings observed while running WITHOUT the
                # override are evidence the override is needed
                for f in findings or []:
                    # accept full finding dicts or bare code strings
                    code = f.get("code") if isinstance(f, dict) else f
                    for key, val in FINDING_OVERRIDES.get(code, {}).items():
                        if key in rec["rolled_back"]:
                            continue  # quarantined; needs operator reset
                        if rec["overrides"].get(key) != val:
                            rec["overrides"][key] = val
                            # new treatment ⇒ stale samples are meaningless
                            rec["applied"] = []
                    if code in FINDING_OVERRIDES and code not in rec["findings"]:
                        rec["findings"].append(code)
            else:
                events = self._maybe_rollback_locked(shape_fp, rec)
            self._save_locked()
        return events

    def _maybe_rollback_locked(self, shape_fp: str, rec: dict) -> list[dict]:
        base, appl = rec["baseline"], rec["applied"]
        if len(base) < _MIN_SAMPLES or len(appl) < _MIN_SAMPLES:
            return []
        base_med = statistics.median(base)
        appl_med = statistics.median(appl)
        if base_med <= 0 or appl_med <= base_med * _REGRESSION_FACTOR:
            return []
        events = []
        for key in list(rec["overrides"]):
            reason = (
                f"applied median {appl_med:.3f}s > "
                f"{_REGRESSION_FACTOR}x shadow median {base_med:.3f}s"
            )
            rec["rolled_back"][key] = reason
            events.append(
                {
                    "fingerprint": shape_fp,
                    "key": key,
                    "value": rec["overrides"].pop(key),
                    "reason": reason,
                }
            )
        rec["applied"] = []
        return events

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            plans = []
            for fp, rec in self._plans.items():
                base, appl = rec["baseline"], rec["applied"]
                plans.append(
                    {
                        "fingerprint": fp,
                        "jobs": rec["jobs"],
                        "overrides": dict(rec["overrides"]),
                        "rolled_back": dict(rec["rolled_back"]),
                        "findings": list(rec["findings"]),
                        "baseline_median_s": (
                            statistics.median(base) if base else None
                        ),
                        "applied_median_s": (
                            statistics.median(appl) if appl else None
                        ),
                        "baseline_n": len(base),
                        "applied_n": len(appl),
                    }
                )
        return {"plans": plans, "plan_count": len(plans)}
