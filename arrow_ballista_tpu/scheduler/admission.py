"""Multi-tenant admission control + weighted fair job release (ISSUE 12).

The scheduler's front door.  Everything downstream of here — the
ExecutionGraph cache, slot reservations, the event loop — was built for
one job at a time; under "millions of users" traffic N concurrent
submissions all raced FIFO into the same slot pool with no queue
discipline, no backpressure and no way to shed load.  This module adds:

* **Tenant pools** — every admission-enabled job belongs to a pool
  (``ballista.tenant.id``, default pool otherwise) with a weight
  (``ballista.tenant.weight``) and an optional per-pool concurrency cap
  (``ballista.tenant.max_running_jobs``).
* **A bounded admission queue** — jobs past the cluster's running-job
  capacity wait here *pre-planning*: no ExecutionGraph is built, no plan
  memory pinned, nothing persisted.  The per-job logical plan is the
  only thing held.
* **Deficit-weighted round-robin release** — as capacity frees, queued
  jobs release pool-by-pool: each eligible pool banks credit
  proportional to its weight and the richest pool admits next, so two
  pools with weights 2:1 see a 2:1 admission rate whenever both have
  work queued.  Idle pools bank nothing (deficits reset when a pool's
  queue drains), so a long-quiet tenant cannot burst past its share.
* **Priority lanes** — ``ballista.tenant.priority=interactive`` jobs
  release ahead of batch work across every pool, but only
  ``max_interactive_bypass`` times in a row past a waiting batch job:
  batch can be delayed, never starved.  A bounded express lane
  (``interactive_headroom``) additionally lets a few interactive jobs
  run ABOVE the cluster cap — a short interactive query must never
  wait a whole long batch job's completion for its admission slot —
  and their tasks dispatch first among running jobs.
* **Graceful shedding** — past ``ballista.admission.max_queued_jobs``
  the controller sheds the newest (``shed_policy=reject``) or oldest
  (``shed_policy=oldest``) queued job with a structured, retryable
  :class:`~arrow_ballista_tpu.errors.ClusterSaturated` error.  A job
  queued longer than ``max_queue_wait_seconds`` sheds the same way.
  The running set is never touched — overload degrades the queue, not
  the work already admitted.

Threading: every method is safe under the controller's own lock.  The
release/plan path runs on the query-stage event loop; cancellation and
status reads arrive from gRPC/REST threads.  The controller never calls
back into the task manager or graphs, so there is no lock ordering to
violate.

With ``ballista.admission.enabled=false`` (the default) nothing here is
ever invoked on the submit path and dispatch behavior is byte-identical
to a scheduler without this module.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from ..errors import ClusterSaturated
from ..obs.registry import MetricsRegistry

# hard floor for pool weights: a zero/absurd weight must not stall the
# deficit top-up loop or divide-by-zero the dispatch share
MIN_POOL_WEIGHT = 1e-3
DEFAULT_POOL = "default"
INTERACTIVE = "interactive"
BATCH = "batch"
# cancel intents are a tiny race-closing buffer (cancel arrived while
# the job was between queue release and graph creation); bound it so
# cancels of bogus job ids cannot accumulate forever
MAX_CANCEL_INTENTS = 256

QUEUE_WAIT_BUCKETS = (0.005, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0)


@dataclass
class QueuedJob:
    """One held-back submission: everything needed to plan it later."""

    job_id: str
    session_id: str
    plan: object  # the LOGICAL plan — nothing heavier exists yet
    pool: str
    priority: str
    enqueued_mono: float
    enqueued_unix: float
    max_wait_s: float  # 0 = wait forever


@dataclass
class AdmissionDecision:
    """Outcome of :meth:`AdmissionController.offer`."""

    queued: bool = False
    position: int = 0
    # jobs displaced by shed_policy=oldest: the caller fails each with
    # its paired error message (they belong to other sessions)
    displaced: List[Tuple[QueuedJob, str]] = field(default_factory=list)
    # set when THIS submission was shed (shed_policy=reject): the caller
    # raises it so the job fails with the structured backpressure error
    error: Optional[ClusterSaturated] = None


class _Pool:
    __slots__ = (
        "name",
        "weight",
        "max_running",
        "lanes",
        "running",
        "deficit",
        "admitted_total",
        "shed_total",
    )

    def __init__(self, name: str):
        self.name = name
        self.weight = 1.0
        self.max_running = 0  # 0 = unlimited
        self.lanes: Dict[str, Deque[QueuedJob]] = {
            INTERACTIVE: deque(),
            BATCH: deque(),
        }
        self.running: set = set()
        self.deficit = 0.0
        self.admitted_total = 0
        self.shed_total = 0

    def queued(self) -> int:
        return len(self.lanes[INTERACTIVE]) + len(self.lanes[BATCH])

    def jobs(self) -> List[QueuedJob]:
        """Release order within the pool: interactive lane first."""
        return list(self.lanes[INTERACTIVE]) + list(self.lanes[BATCH])


class AdmissionController:
    def __init__(
        self,
        executor_manager,
        registry: Optional[MetricsRegistry] = None,
        events=None,
        max_interactive_bypass: int = 4,
        pinned_settings: Optional[Dict[str, str]] = None,
    ):
        from ..obs.events import EventJournal

        self.executor_manager = executor_manager
        # operator-pinned CLUSTER limits (scheduler flags / overrides):
        # a ballista.admission.* key present here wins over whatever the
        # submitting session says — one tenant must not rewrite the
        # cluster-wide gates (queue bound, shed policy, concurrency cap)
        # every other tenant depends on.  Per-POOL knobs (ballista.
        # tenant.*) stay session-driven by design: a tenant can only
        # shape its own pool.
        self._pinned = {
            k: v
            for k, v in (pinned_settings or {}).items()
            if k.startswith("ballista.admission.")
        }
        self.events = events if events is not None else EventJournal()
        self.registry = registry or MetricsRegistry()
        self._lock = threading.Lock()
        # durable queue journal (ISSUE 20): attached by SchedulerState
        # when --admission-wal-enabled; None keeps every hook a no-op
        # and the submit path byte-identical to pre-WAL behavior
        self.wal = None
        self._pools: Dict[str, _Pool] = {}
        # job_id -> (pool, priority) for every admitted-and-not-yet-
        # terminal job; priority matters for capacity accounting —
        # running interactive jobs charge the headroom FIRST, so an
        # express-lane job never occupies base capacity a batch release
        # is waiting for (otherwise steady interactive traffic would
        # hold base_ok false forever and batch would starve structurally)
        self._running: Dict[str, Tuple[str, str]] = {}
        # job_id -> QueuedJob for queue membership / position queries
        self._queued: Dict[str, QueuedJob] = {}
        # cancel arrived while the job was mid-release (no queue entry,
        # no graph yet): the submit path checks-and-consumes these
        self._cancel_intents: OrderedDict = OrderedDict()
        # cluster-level limits, refreshed from the submitting job's
        # merged config at each offer (scheduler flags seed defaults,
        # explicit session settings win — same contract as AQE)
        self._max_running_jobs = 0  # 0 = one admitted job per task slot
        self._max_queued = 100
        self._shed_policy = "reject"
        self._max_bypass = max_interactive_bypass
        # bounded express lane: interactive jobs may run up to this many
        # ABOVE the cap — a short interactive query must never wait a
        # whole long batch job's completion for its admission slot
        # (job-granular admission would otherwise make it slower than
        # task-granular FIFO, the opposite of a priority lane)
        self._interactive_headroom = 2
        # consecutive interactive releases past waiting batch work —
        # ONE counter across pools, so interactive jumps every batch
        # queue but can never starve any of them
        self._interactive_bypass = 0
        self._queued_counter = self.registry.counter(
            "jobs_queued_total",
            "jobs held in the admission queue at submit",
        )
        self._admitted_counter = self.registry.counter(
            "jobs_admitted_total",
            "jobs released from the admission queue into planning",
        )
        self._shed_counter = self.registry.counter(
            "jobs_shed_total",
            "jobs shed with ClusterSaturated backpressure",
        )
        self._wait_hist = self.registry.histogram(
            "admission_queue_wait_seconds",
            "queue wait of admitted jobs (enqueue to release)",
            buckets=QUEUE_WAIT_BUCKETS,
        )
        self.registry.gauge(
            "admission_queued_jobs",
            "jobs currently waiting in the admission queue",
            fn=self.queued_count,
        )

    # ------------------------------------------------------------ capacity
    def _derived_max_running(self) -> int:
        """Default concurrency gate: one admitted job per task slot
        across alive executors (an empty cluster still admits one job so
        the first registration has something to run)."""
        try:
            em = self.executor_manager
            alive = em.get_alive_executors()
            total = sum(
                meta.specification.task_slots
                for meta in em.executors()
                if meta.id in alive
            )
            return max(1, total)
        except Exception:  # noqa: BLE001 - capacity probe must not fail submit
            return 1

    def _effective_max_running(self) -> int:
        return (
            self._max_running_jobs
            if self._max_running_jobs > 0
            else self._derived_max_running()
        )

    @staticmethod
    def _pool_capacity_ok(pool: _Pool) -> bool:
        return pool.max_running <= 0 or len(pool.running) < pool.max_running

    # -------------------------------------------------------------- pools
    def _pool_for(self, cfg) -> _Pool:
        name = (cfg.tenant_id or "").strip() or DEFAULT_POOL
        pool = self._pools.get(name)
        if pool is None:
            pool = self._pools[name] = _Pool(name)
        # pool parameters follow the latest submission (tenants ship
        # their own weight/cap; a scheduler-flag override wins the merge
        # upstream exactly like every other knob)
        pool.weight = max(MIN_POOL_WEIGHT, cfg.tenant_weight)
        pool.max_running = cfg.tenant_max_running_jobs
        return pool

    def _effective_cfg(self, cfg):
        """Operator-pinned admission keys over the session's values."""
        if not self._pinned:
            return cfg
        from ..config import BallistaConfig

        return BallistaConfig({**cfg.to_dict(), **self._pinned})

    def _refresh_limits(self, cfg) -> None:
        self._max_running_jobs = cfg.admission_max_running_jobs
        self._max_queued = cfg.admission_max_queued_jobs
        self._shed_policy = cfg.admission_shed_policy
        self._max_bypass = cfg.admission_max_interactive_bypass
        self._interactive_headroom = max(0, cfg.admission_interactive_headroom)

    def pool_weights(self) -> Dict[str, float]:
        """{pool: weight} snapshot — the dispatch-side fair-share input
        (``TaskManager.fill_reservations`` ordering)."""
        with self._lock:
            return {name: p.weight for name, p in self._pools.items()}

    # -------------------------------------------------------------- offer
    def offer(self, job_id: str, session_id: str, plan, cfg) -> AdmissionDecision:
        """Enqueue one admission-enabled submission (or shed per policy).

        Pure queue discipline: the caller runs :meth:`release`
        immediately after, so an uncontended job passes straight through
        with ~0 queue wait.  Returns the decision; on
        ``shed_policy=reject`` saturation the decision carries the
        :class:`ClusterSaturated` error for the caller to raise."""
        now_mono = time.monotonic()
        with self._lock:
            pool = self._pool_for(cfg)
            cfg = self._effective_cfg(cfg)
            self._refresh_limits(cfg)
            priority = cfg.tenant_priority
            qj = QueuedJob(
                job_id=job_id,
                session_id=session_id,
                plan=plan,
                pool=pool.name,
                priority=priority,
                enqueued_mono=now_mono,
                enqueued_unix=time.time(),
                max_wait_s=cfg.admission_max_queue_wait_seconds,
            )
            decision = AdmissionDecision()
            # every admission transits the queue (release() is the only
            # admit path), so the bound must never be able to reject an
            # idle cluster outright: 0 means unbounded, like the other
            # capacity knobs
            if 0 < self._max_queued <= len(self._queued):
                if self._shed_policy == "oldest":
                    oldest = min(
                        self._queued.values(), key=lambda q: q.enqueued_mono
                    )
                    err = self._shed_locked(
                        oldest, "displaced by a newer submission", now_mono
                    )
                    decision.displaced.append((oldest, str(err)))
                else:
                    pool.shed_total += 1
                    self._shed_counter.inc()
                    decision.error = ClusterSaturated(
                        "admission queue full",
                        pool=pool.name,
                        queued=len(self._queued),
                        policy=self._shed_policy,
                    )
                    self.events.emit(
                        "job_shed",
                        job=job_id,
                        pool=pool.name,
                        priority=priority,
                        queue_wait_s=0.0,
                        policy=self._shed_policy,
                        reason="queue full",
                    )
                    self._refresh_gauges_locked()
                    return decision
            pool.lanes[priority if priority in pool.lanes else BATCH].append(qj)
            self._queued[job_id] = qj
            if self.wal is not None:
                self.wal.append(qj, pool.weight, pool.max_running)
            self._queued_counter.inc()
            decision.queued = True
            decision.position = self._position_locked(qj)
            self.events.emit(
                "job_queued",
                job=job_id,
                pool=pool.name,
                priority=priority,
                position=decision.position,
                queued_jobs=len(self._queued),
            )
            self._refresh_gauges_locked()
            return decision

    # ------------------------------------------------------------- release
    def release(self) -> List[QueuedJob]:
        """Admit as many queued jobs as current capacity allows, by
        deficit-weighted round robin across pools.  The caller plans and
        submits each returned job (they are already counted running so a
        racing release cannot over-admit)."""
        out: List[QueuedJob] = []
        with self._lock:
            guard = 0
            while guard < 100_000:
                guard += 1
                if not self._queued:
                    break
                max_running = self._effective_max_running()
                inter_running = sum(
                    1
                    for _pool, prio in self._running.values()
                    if prio == INTERACTIVE
                )
                # running interactive jobs fill the headroom before they
                # count against base capacity: batch's share of the
                # cluster is never consumed by express-lane traffic
                base_used = len(self._running) - min(
                    inter_running, self._interactive_headroom
                )
                base_ok = base_used < max_running
                # the express lane: interactive jobs may still admit
                # when the base capacity is full, up to the headroom
                inter_ok = (
                    len(self._running)
                    < max_running + self._interactive_headroom
                )
                if not inter_ok:  # implies base_ok is false too
                    break
                interactive_only = not base_ok

                def lanes_queued(p: _Pool) -> bool:
                    if interactive_only:
                        return bool(p.lanes[INTERACTIVE])
                    return p.queued() > 0

                eligible = [
                    p
                    for p in self._pools.values()
                    if lanes_queued(p) and self._pool_capacity_ok(p)
                ]
                if not eligible:
                    break
                affordable = [p for p in eligible if p.deficit >= 1.0]
                if not affordable:
                    # top up: each pool banks credit proportional to its
                    # weight until someone can afford one admission
                    for p in eligible:
                        p.deficit += p.weight
                    continue
                qj, best = self._pick_locked(
                    affordable, interactive_only=interactive_only
                )
                if qj is None:  # defensive; lanes_queued said non-empty
                    continue
                best.deficit -= 1.0
                self._queued.pop(qj.job_id, None)
                best.running.add(qj.job_id)
                self._running[qj.job_id] = (best.name, qj.priority)
                best.admitted_total += 1
                self._admitted_counter.inc()
                wait = time.monotonic() - qj.enqueued_mono
                self._wait_hist.observe(wait)
                self.events.emit(
                    "job_admitted",
                    job=qj.job_id,
                    pool=best.name,
                    priority=qj.priority,
                    queue_wait_s=round(wait, 4),
                )
                out.append(qj)
            # standard DRR: an idle pool banks nothing — its burst
            # budget restarts when work arrives
            for p in self._pools.values():
                if not p.queued():
                    p.deficit = 0.0
            self._refresh_gauges_locked()
        return out

    def _pick_locked(self, affordable: List[_Pool], interactive_only=False):
        """One admission among the affordable pools: the interactive
        lane goes first ACROSS pools — but only ``max_interactive_
        bypass`` times in a row past waiting batch work, then the
        best batch head must go (bounded bypass: batch is delayed,
        never starved).  Within a lane, the pool with the largest
        deficit wins, oldest head job as the tie-break (deficit-
        weighted round robin).  ``interactive_only`` (headroom-funded
        admissions past the base cap) never counts as a bypass —
        batch could not have taken that slot anyway."""
        inter_pools = [p for p in affordable if p.lanes[INTERACTIVE]]
        batch_pools = (
            [] if interactive_only
            else [p for p in affordable if p.lanes[BATCH]]
        )

        def best_of(pools: List[_Pool], lane: str) -> _Pool:
            return max(
                pools,
                key=lambda p: (p.deficit, -p.lanes[lane][0].enqueued_mono),
            )

        if inter_pools and (
            not batch_pools
            or self._interactive_bypass < max(0, self._max_bypass)
        ):
            best = best_of(inter_pools, INTERACTIVE)
            if interactive_only:
                # headroom-funded slot: it was never batch's to take, so
                # it neither counts as a bypass nor forgives past ones —
                # unless no batch is waiting anywhere, which genuinely
                # ends the streak
                if not any(p.lanes[BATCH] for p in self._pools.values()):
                    self._interactive_bypass = 0
            elif batch_pools:
                self._interactive_bypass += 1
            else:
                self._interactive_bypass = 0
            return best.lanes[INTERACTIVE].popleft(), best
        if batch_pools:
            self._interactive_bypass = 0
            best = best_of(batch_pools, BATCH)
            return best.lanes[BATCH].popleft(), best
        return None, None

    # ------------------------------------------------------------ lifecycle
    def job_finished(self, job_id: str) -> bool:
        """A tracked job reached a terminal state: free its concurrency
        slot.  No-op (False) for jobs admission never saw."""
        with self._lock:
            entry = self._running.pop(job_id, None)
            if entry is None:
                return False
            pool = self._pools.get(entry[0])
            if pool is not None:
                pool.running.discard(job_id)
            self._refresh_gauges_locked()
            return True

    def adopt_running(self, job_id: str, pool_name: str, priority: str = BATCH) -> None:
        """Restart/HA adoption: re-register an already-admitted job so
        pool accounting (and the concurrency gate) survives failover."""
        with self._lock:
            pool = self._pools.get(pool_name)
            if pool is None:
                pool = self._pools[pool_name] = _Pool(pool_name)
            pool.running.add(job_id)
            self._running[job_id] = (pool_name, priority)
            self._refresh_gauges_locked()

    # ---------------------------------------------------- durability (WAL)
    def attach_wal(self, wal) -> None:
        """Arm the durable queue journal (:class:`~.queue_wal.
        AdmissionWal`).  Every queue mutation from here on writes
        through; ``None`` (the default) keeps behavior byte-identical
        to a WAL-less scheduler."""
        self.wal = wal

    def wal_discard(self, job_id: str) -> None:
        """The job reached a durable downstream state (its graph was
        persisted, or it went terminal): its WAL entry is now redundant.
        Deliberately NOT called at :meth:`release` — a crash between
        release and graph persistence must still replay the job."""
        if self.wal is not None:
            self.wal.discard(job_id)

    def restore(
        self,
        job_id: str,
        session_id: str,
        plan,
        pool_name: str,
        priority: str,
        pool_weight: float,
        pool_max_running: int,
        enqueued_unix: float,
        max_wait_s: float,
    ) -> bool:
        """WAL replay: re-enqueue one journaled job in arrival order
        (the caller iterates entries sorted by sequence).  Queue-wait
        age survives the restart — ``enqueued_mono`` is back-dated by
        the wall-clock elapsed so ``max_queue_wait_seconds`` expiry
        still fires on schedule.  DRR deficits deliberately restart at
        zero: they are burst credit, not queue position.  Returns False
        for jobs admission already tracks (idempotent replay)."""
        now_mono = time.monotonic()
        with self._lock:
            if job_id in self._queued or job_id in self._running:
                return False
            pool = self._pools.get(pool_name)
            if pool is None:
                pool = self._pools[pool_name] = _Pool(pool_name)
                # journaled pool parameters seed a pool the restarted
                # scheduler hasn't seen yet; a live pool keeps whatever
                # the latest real submission configured
                pool.weight = max(MIN_POOL_WEIGHT, pool_weight)
                pool.max_running = pool_max_running
            qj = QueuedJob(
                job_id=job_id,
                session_id=session_id,
                plan=plan,
                pool=pool.name,
                priority=priority,
                enqueued_mono=now_mono - max(0.0, time.time() - enqueued_unix),
                enqueued_unix=enqueued_unix,
                max_wait_s=max_wait_s,
            )
            pool.lanes[priority if priority in pool.lanes else BATCH].append(qj)
            self._queued[job_id] = qj
            self.events.emit(
                "job_requeued",
                job=job_id,
                pool=pool.name,
                priority=qj.priority,
                position=self._position_locked(qj),
            )
            self._refresh_gauges_locked()
            return True

    def restore_cancel_intent(self, job_id: str) -> None:
        """WAL replay: re-arm a cancel intent that raced the crash."""
        with self._lock:
            self._cancel_intents[job_id] = time.monotonic()
            while len(self._cancel_intents) > MAX_CANCEL_INTENTS:
                evicted, _ = self._cancel_intents.popitem(last=False)
                if self.wal is not None:
                    self.wal.discard_intent(evicted)

    # ----------------------------------------------------------- shedding
    def _shed_locked(
        self, qj: QueuedJob, reason: str, now_mono: float
    ) -> ClusterSaturated:
        """Remove one queued job and account the shed; returns the
        structured error the caller fails it with."""
        self._queued.pop(qj.job_id, None)
        if self.wal is not None:
            self.wal.discard(qj.job_id)
        pool = self._pools.get(qj.pool)
        wait = now_mono - qj.enqueued_mono
        if pool is not None:
            for lane in pool.lanes.values():
                try:
                    lane.remove(qj)
                except ValueError:
                    pass
            pool.shed_total += 1
        self._shed_counter.inc()
        err = ClusterSaturated(
            reason,
            pool=qj.pool,
            queued=len(self._queued),
            policy=self._shed_policy,
            queue_wait_s=wait,
        )
        self.events.emit(
            "job_shed",
            job=qj.job_id,
            pool=qj.pool,
            priority=qj.priority,
            queue_wait_s=round(wait, 4),
            policy=self._shed_policy,
            reason=reason,
        )
        return err

    def expire_overdue(self) -> List[Tuple[QueuedJob, str]]:
        """Shed every queued job past its ``max_queue_wait_seconds``
        (0 = never).  Returns [(job, error message)] for the caller to
        fail — the periodic admission pulse drives this."""
        now = time.monotonic()
        out: List[Tuple[QueuedJob, str]] = []
        with self._lock:
            overdue = [
                qj
                for qj in self._queued.values()
                if qj.max_wait_s > 0 and now - qj.enqueued_mono > qj.max_wait_s
            ]
            for qj in overdue:
                err = self._shed_locked(
                    qj,
                    f"queued longer than max_queue_wait_seconds="
                    f"{qj.max_wait_s:g}",
                    now,
                )
                out.append((qj, str(err)))
            if overdue:
                self._refresh_gauges_locked()
        return out

    # --------------------------------------------------------- cancellation
    def cancel(self, job_id: str) -> Optional[QueuedJob]:
        """Dequeue a still-queued job (cancel-before-admit).  Returns
        the entry when it was waiting, None when admission doesn't hold
        it (already released, or never admission-managed)."""
        with self._lock:
            qj = self._queued.pop(job_id, None)
            if qj is None:
                return None
            if self.wal is not None:
                self.wal.discard(job_id)
            pool = self._pools.get(qj.pool)
            if pool is not None:
                for lane in pool.lanes.values():
                    try:
                        lane.remove(qj)
                    except ValueError:
                        pass
            self._refresh_gauges_locked()
            return qj

    def mark_cancel_intent(self, job_id: str) -> None:
        """Cancel raced the admit window (not queued, no graph yet): the
        release/plan path consumes the intent and fails the job instead
        of running it.  Bounded — stale intents for bogus ids age out."""
        with self._lock:
            self._cancel_intents[job_id] = time.monotonic()
            if self.wal is not None:
                self.wal.put_intent(job_id)
            while len(self._cancel_intents) > MAX_CANCEL_INTENTS:
                evicted, _ = self._cancel_intents.popitem(last=False)
                if self.wal is not None:
                    self.wal.discard_intent(evicted)

    def take_cancel_intent(self, job_id: str) -> bool:
        with self._lock:
            taken = self._cancel_intents.pop(job_id, None) is not None
            if taken and self.wal is not None:
                self.wal.discard_intent(job_id)
            return taken

    # ------------------------------------------------------------- queries
    def queued_count(self) -> int:
        with self._lock:
            return len(self._queued)

    def _position_locked(self, qj: QueuedJob) -> int:
        pool = self._pools.get(qj.pool)
        if pool is None:
            return 0
        try:
            return pool.jobs().index(qj) + 1
        except ValueError:
            return 0

    def queued_status(self, job_id: str) -> Optional[dict]:
        """Job-status surface for a held-back job: queue position within
        its pool (1-based, interactive lane first) + wait so far."""
        with self._lock:
            qj = self._queued.get(job_id)
            if qj is None:
                return None
            return {
                "state": "queued",
                "job_id": job_id,
                "pool": qj.pool,
                "priority": qj.priority,
                "queue_position": self._position_locked(qj),
                "queued_seconds": round(
                    time.monotonic() - qj.enqueued_mono, 3
                ),
            }

    def queued_jobs_brief(self) -> List[dict]:
        """[{job_id, pool, priority}] for the /api/jobs table."""
        with self._lock:
            return [
                {"job_id": q.job_id, "pool": q.pool, "priority": q.priority}
                for q in self._queued.values()
            ]

    def snapshot(self) -> dict:
        """The /api/tenants payload: per-pool weights, lanes, queue
        depth, running share and lifetime counters."""
        with self._lock:
            total_weight = sum(
                p.weight for p in self._pools.values()
            ) or 1.0
            pools = {}
            for name, p in sorted(self._pools.items()):
                pools[name] = {
                    "weight": p.weight,
                    "share_target": round(p.weight / total_weight, 4),
                    "max_running_jobs": p.max_running,
                    "queued": p.queued(),
                    "queued_interactive": len(p.lanes[INTERACTIVE]),
                    "queued_batch": len(p.lanes[BATCH]),
                    "running": len(p.running),
                    "admitted_total": p.admitted_total,
                    "shed_total": p.shed_total,
                }
            return {
                "pools": pools,
                "queued_jobs": len(self._queued),
                "running_jobs": len(self._running),
                "max_running_jobs": self._effective_max_running(),
                "max_queued_jobs": self._max_queued,
                "shed_policy": self._shed_policy,
                "max_interactive_bypass": self._max_bypass,
                "interactive_headroom": self._interactive_headroom,
            }

    def health_summary(self) -> dict:
        """Compact admission block for /api/cluster/health."""
        with self._lock:
            return {
                "queued_jobs": len(self._queued),
                "running_jobs": len(self._running),
                "pools": {
                    name: {"queued": p.queued(), "running": len(p.running)}
                    for name, p in sorted(self._pools.items())
                    if p.queued() or p.running or p.admitted_total
                },
            }

    # -------------------------------------------------------------- gauges
    def _refresh_gauges_locked(self) -> None:
        total_weight = sum(p.weight for p in self._pools.values()) or 1.0
        for name, p in self._pools.items():
            labels = {"pool": name}
            self.registry.gauge(
                "tenant_queued_jobs",
                "jobs waiting in this pool's admission queue",
                labels=labels,
            ).set(p.queued())
            self.registry.gauge(
                "tenant_running_jobs",
                "admitted (running) jobs of this pool",
                labels=labels,
            ).set(len(p.running))
            self.registry.gauge(
                "tenant_share",
                "configured fair-share fraction of this pool",
                labels=labels,
            ).set(round(p.weight / total_weight, 4))
