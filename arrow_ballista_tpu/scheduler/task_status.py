"""TaskStatus proto ⇄ TaskInfo conversions, shared by scheduler + executor.

Counterpart of the reference's ``executor/src/lib.rs as_task_status`` (the
executor-side Result → protobuf mapping) and the scheduler-side decode in
``scheduler/src/state/task_manager.rs:132-170``.
"""

from __future__ import annotations

import json
import random
import time
from typing import List

from ..proto import pb
from ..serde.scheduler_types import PartitionId, ShuffleWritePartition
from .execution_stage import TaskInfo


def _spans_from_json(payload: bytes) -> List[dict]:
    if not payload:
        return []
    try:
        spans = json.loads(payload.decode())
        return spans if isinstance(spans, list) else []
    except Exception:  # noqa: BLE001 - malformed piggyback must not drop status
        return []


def task_info_to_proto(info: TaskInfo) -> pb.TaskStatus:
    msg = pb.TaskStatus()
    msg.task_id.CopyFrom(info.partition_id.to_proto())
    msg.attempt = info.attempt
    msg.fetch_retries = info.fetch_retries
    msg.speculative = info.speculative
    if info.spans:
        msg.spans_json = json.dumps(info.spans).encode()
    if info.state == "running":
        msg.running.executor_id = info.executor_id
    elif info.state == "failed":
        msg.failed.error = info.error or "task failed"
    elif info.state == "completed":
        msg.completed.executor_id = info.executor_id
        for p in info.partitions:
            msg.completed.partitions.append(p.to_proto())
    else:
        raise ValueError(f"unknown task state {info.state!r}")
    for op_name, values in info.metrics:
        m = msg.metrics.add()
        m.operator_name = op_name
        for k, v in values.items():
            m.values[k] = int(v)
    return msg


def task_info_from_proto(msg: pb.TaskStatus) -> TaskInfo:
    pid = PartitionId.from_proto(msg.task_id)
    which = msg.WhichOneof("status")
    metrics = [(m.operator_name, dict(m.values)) for m in msg.metrics]
    spans = _spans_from_json(msg.spans_json)
    if which == "running":
        return TaskInfo(
            pid,
            "running",
            msg.running.executor_id,
            metrics=metrics,
            attempt=msg.attempt,
            fetch_retries=msg.fetch_retries,
            spans=spans,
            speculative=msg.speculative,
        )
    if which == "failed":
        return TaskInfo(
            pid,
            "failed",
            error=msg.failed.error,
            metrics=metrics,
            attempt=msg.attempt,
            fetch_retries=msg.fetch_retries,
            spans=spans,
            speculative=msg.speculative,
        )
    if which == "completed":
        parts = [
            ShuffleWritePartition.from_proto(p) for p in msg.completed.partitions
        ]
        return TaskInfo(
            pid,
            "completed",
            msg.completed.executor_id,
            partitions=parts,
            metrics=metrics,
            attempt=msg.attempt,
            fetch_retries=msg.fetch_retries,
            spans=spans,
            speculative=msg.speculative,
        )
    raise ValueError(f"TaskStatus with no status set for {pid}")


def job_status_to_proto(status: dict) -> pb.JobStatus:
    """Scheduler-side status snapshot → wire JobStatus
    (reference: proto JobStatus oneof, ballista.proto)."""
    msg = pb.JobStatus()
    state = status.get("state")
    if state == "queued":
        msg.queued.SetInParent()
        # admission-queue coordinates (scheduler/admission.py): the
        # client poll loop distinguishes queued wait from running time
        if status.get("queue_position"):
            msg.queued.queue_position = int(status["queue_position"])
        if status.get("pool"):
            msg.queued.pool = status["pool"]
        if status.get("queued_seconds"):
            msg.queued.queued_seconds = float(status["queued_seconds"])
    elif state == "running":
        msg.running.SetInParent()
    elif state == "failed":
        msg.failed.error = status.get("error", "")
    elif state == "completed":
        for loc in status.get("locations", []):
            msg.completed.partition_location.append(loc.to_proto())
    else:
        msg.queued.SetInParent()
    return msg


def job_status_from_proto(msg: pb.JobStatus) -> dict:
    from ..serde.scheduler_types import PartitionLocation

    which = msg.WhichOneof("status")
    if which == "failed":
        return {"state": "failed", "error": msg.failed.error}
    if which == "completed":
        return {
            "state": "completed",
            "locations": [
                PartitionLocation.from_proto(p)
                for p in msg.completed.partition_location
            ],
        }
    if which == "queued":
        out = {"state": "queued"}
        if msg.queued.queue_position:
            out["queue_position"] = msg.queued.queue_position
        if msg.queued.pool:
            out["pool"] = msg.queued.pool
        if msg.queued.queued_seconds:
            out["queued_seconds"] = msg.queued.queued_seconds
        return out
    return {"state": which or "queued"}


class PollBackoff:
    """Jittered exponential poll-interval schedule, shared by the client
    ``wait_for_job`` loop and the FlightSQL front-end (the same module
    rule as :func:`poll_timeout_breakdown`): hundreds of concurrent
    waiting clients polling a fixed interval hit the scheduler in
    lockstep waves — backing each client off geometrically (x1.6 per
    poll, capped) with ±25% jitter spreads the load while keeping the
    first polls tight so short queries stay snappy.

    ``next_delay()`` returns the seconds to sleep before the next poll
    and advances the schedule; ``reset()`` snaps back to the base (used
    on a state transition — a job that just started running deserves
    tight polling again)."""

    GROWTH = 1.6
    JITTER = 0.25

    def __init__(self, base_s: float = 0.1, cap_s: float = 2.0):
        self.base_s = max(1e-3, float(base_s))
        self.cap_s = max(self.base_s, float(cap_s))
        self._current = self.base_s

    def reset(self) -> None:
        self._current = self.base_s

    def next_delay(self) -> float:
        jitter = 1.0 + self.JITTER * (2.0 * random.random() - 1.0)
        delay = self._current * jitter
        self._current = min(self._current * self.GROWTH, self.cap_s)
        return delay

    def sleep(self, deadline_mono: float) -> None:
        """Sleep the next backed-off interval, clamped to the remaining
        monotonic deadline (+10ms so the expiry check runs right after):
        a capped 2s+jitter interval must not make a timeout fire seconds
        late.  The one sleep rule for both poll loops."""
        time.sleep(
            min(
                self.next_delay(),
                max(0.0, deadline_mono - time.monotonic()) + 0.01,
            )
        )


def poll_timeout_breakdown(
    start_mono: float, running_since_mono, last_queued: dict
) -> str:
    """``(spent Xs queued in pool 'p' (last position n) and Ys
    running)`` — shared by the client poll loop and the FlightSQL
    front-end so an admission-starved job reads differently from a
    wedged one in both timeout messages."""
    now = time.monotonic()
    queued_s = (
        running_since_mono if running_since_mono is not None else now
    ) - start_mono
    running_s = (
        now - running_since_mono if running_since_mono is not None else 0.0
    )
    msg = f" (spent {queued_s:.1f}s queued"
    if last_queued.get("pool"):
        msg += f" in pool {last_queued['pool']!r}"
    if last_queued.get("queue_position"):
        msg += f" (last position {last_queued['queue_position']})"
    return msg + f" and {running_s:.1f}s running)"


def collect_plan_metrics(plan) -> List[tuple]:
    """Walk the operator tree gathering (operator_name, metric values)
    (reference: core/src/utils.rs:347-358 collect_plan_metrics)."""
    out: List[tuple] = []

    def walk(node):
        values = node.metrics.to_dict()
        if values:
            out.append((type(node).__name__, values))
        for child in node.children():
            walk(child)

    walk(plan)
    return out
