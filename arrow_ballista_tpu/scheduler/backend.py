"""Pluggable scheduler state backend.

Counterpart of the reference's ``scheduler/src/state/backend/``:
``StateBackend`` (trait, `mod.rs:63-112`) over seven keyspaces with
get / get_from_prefix / scan / scan_keys / put / put_txn / mv / lock /
watch / delete; an in-memory implementation (the testing default) and a
SQLite implementation filling the embedded-sled role ("standalone.rs") —
scheduler state survives restarts in a single file.  An etcd-style remote
backend slot is left open behind the same ABC (the python etcd3 client is
not in this image; the class raises a clear error if selected).
"""

from __future__ import annotations

import sqlite3
import threading
from abc import ABC, abstractmethod
from enum import Enum
from typing import Callable, Dict, List, Optional, Tuple


class Keyspace(str, Enum):
    Executors = "executors"
    ActiveJobs = "active_jobs"
    CompletedJobs = "completed_jobs"
    FailedJobs = "failed_jobs"
    Slots = "slots"
    Sessions = "sessions"
    Heartbeats = "heartbeats"
    # scheduler liveness lives APART from executor heartbeats: the
    # executor-manager watches Heartbeats with an empty prefix and decodes
    # every event as ExecutorHeartbeat protobuf
    Schedulers = "schedulers"
    # durable admission-queue WAL: "q:"-prefixed entries keyed by submit
    # order (zero-padded sequence), "c:"-prefixed cancel intents and
    # "t:"-prefixed submit idempotency tokens (see queue_wal.py)
    QueueWal = "queue_wal"


class WatchEvent:
    PUT = "put"
    DELETE = "delete"

    def __init__(self, kind: str, key: str, value: Optional[bytes]):
        self.kind = kind
        self.key = key
        self.value = value

    def __repr__(self) -> str:
        return f"WatchEvent({self.kind}, {self.key!r})"


Watcher = Callable[[WatchEvent], None]


class StateBackend(ABC):
    """All methods are thread-safe."""

    @abstractmethod
    def get(self, keyspace: Keyspace, key: str) -> Optional[bytes]: ...

    @abstractmethod
    def get_from_prefix(
        self, keyspace: Keyspace, prefix: str
    ) -> List[Tuple[str, bytes]]: ...

    @abstractmethod
    def scan(self, keyspace: Keyspace) -> List[Tuple[str, bytes]]: ...

    @abstractmethod
    def put(self, keyspace: Keyspace, key: str, value: bytes) -> None: ...

    @abstractmethod
    def put_txn(
        self, ops: List[Tuple[Keyspace, str, bytes]], fence=None
    ) -> None:
        """Atomically apply several puts.  ``fence`` (optional) is the
        lock object guarding the write: remote lease backends reject the
        transaction if the lease lapsed (fencing token); local backends
        ignore it — in-process mutual exclusion is already total."""

    @abstractmethod
    def mv(
        self, from_keyspace: Keyspace, to_keyspace: Keyspace, key: str
    ) -> None: ...

    @abstractmethod
    def delete(self, keyspace: Keyspace, key: str) -> None: ...

    def scan_keys(self, keyspace: Keyspace) -> List[str]:
        return [k for k, _ in self.scan(keyspace)]

    # ---- locking ----
    @abstractmethod
    def lock(self, keyspace: Keyspace, key: str) -> threading.Lock:
        """A process-wide lock scoped to (keyspace, key); the reference uses
        this for atomic slot accounting (`executor_manager.rs:121-167`)."""

    # ---- watches ----
    @abstractmethod
    def watch(self, keyspace: Keyspace, prefix: str, watcher: Watcher) -> Callable:
        """Register a callback for put/delete events under a prefix; returns
        an unsubscribe function."""


class _WatchMixin:
    def _init_watches(self) -> None:
        self._watchers: Dict[Keyspace, List[Tuple[str, Watcher]]] = {}
        self._watch_lock = threading.Lock()

    def watch(self, keyspace: Keyspace, prefix: str, watcher: Watcher) -> Callable:
        entry = (prefix, watcher)
        with self._watch_lock:
            self._watchers.setdefault(keyspace, []).append(entry)

        def unsubscribe() -> None:
            with self._watch_lock:
                try:
                    self._watchers[keyspace].remove(entry)
                except ValueError:
                    pass

        return unsubscribe

    def _notify(self, keyspace: Keyspace, event: WatchEvent) -> None:
        with self._watch_lock:
            targets = [
                w
                for prefix, w in self._watchers.get(keyspace, [])
                if event.key.startswith(prefix)
            ]
        for w in targets:
            try:
                w(event)
            except Exception:  # noqa: BLE001 - watcher errors don't poison puts
                pass


class _LockMixin:
    def _init_locks(self) -> None:
        self._locks: Dict[Tuple[Keyspace, str], threading.Lock] = {}
        self._locks_guard = threading.Lock()

    def lock(self, keyspace: Keyspace, key: str) -> threading.Lock:
        with self._locks_guard:
            lk = self._locks.get((keyspace, key))
            if lk is None:
                lk = threading.Lock()
                self._locks[(keyspace, key)] = lk
            return lk


class MemoryBackend(_WatchMixin, _LockMixin, StateBackend):
    """Dict-backed backend — the in-proc default (standalone mode, tests)."""

    def __init__(self) -> None:
        self._data: Dict[Keyspace, Dict[str, bytes]] = {k: {} for k in Keyspace}
        self._guard = threading.RLock()
        self._init_watches()
        self._init_locks()

    def get(self, keyspace: Keyspace, key: str) -> Optional[bytes]:
        with self._guard:
            return self._data[keyspace].get(key)

    def get_from_prefix(self, keyspace, prefix):
        with self._guard:
            return [
                (k, v) for k, v in self._data[keyspace].items() if k.startswith(prefix)
            ]

    def scan(self, keyspace):
        with self._guard:
            return list(self._data[keyspace].items())

    def put(self, keyspace, key, value):
        with self._guard:
            self._data[keyspace][key] = value
        self._notify(keyspace, WatchEvent(WatchEvent.PUT, key, value))

    def put_txn(self, ops, fence=None):
        with self._guard:
            for ks, k, v in ops:
                self._data[ks][k] = v
        for ks, k, v in ops:
            self._notify(ks, WatchEvent(WatchEvent.PUT, k, v))

    def mv(self, from_keyspace, to_keyspace, key):
        with self._guard:
            v = self._data[from_keyspace].pop(key, None)
            if v is not None:
                self._data[to_keyspace][key] = v
        if v is not None:
            self._notify(from_keyspace, WatchEvent(WatchEvent.DELETE, key, None))
            self._notify(to_keyspace, WatchEvent(WatchEvent.PUT, key, v))

    def delete(self, keyspace, key):
        with self._guard:
            existed = self._data[keyspace].pop(key, None) is not None
        if existed:
            self._notify(keyspace, WatchEvent(WatchEvent.DELETE, key, None))


class SqliteBackend(_WatchMixin, _LockMixin, StateBackend):
    """Single-file durable backend (the sled 'standalone' counterpart)."""

    def __init__(self, path: str):
        self.path = path
        self._guard = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            " keyspace TEXT NOT NULL, key TEXT NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (keyspace, key))"
        )
        self._conn.commit()
        self._init_watches()
        self._init_locks()

    def get(self, keyspace, key):
        with self._guard:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE keyspace=? AND key=?",
                (keyspace.value, key),
            ).fetchone()
        return row[0] if row else None

    def get_from_prefix(self, keyspace, prefix):
        with self._guard:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE keyspace=? AND key GLOB ?",
                (keyspace.value, prefix + "*"),
            ).fetchall()
        return [(k, v) for k, v in rows]

    def scan(self, keyspace):
        with self._guard:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE keyspace=?", (keyspace.value,)
            ).fetchall()
        return [(k, v) for k, v in rows]

    def put(self, keyspace, key, value):
        with self._guard:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (keyspace, key, value) VALUES (?,?,?)",
                (keyspace.value, key, value),
            )
            self._conn.commit()
        self._notify(keyspace, WatchEvent(WatchEvent.PUT, key, value))

    def put_txn(self, ops, fence=None):
        with self._guard:
            for ks, k, v in ops:
                self._conn.execute(
                    "INSERT OR REPLACE INTO kv (keyspace, key, value) VALUES (?,?,?)",
                    (ks.value, k, v),
                )
            self._conn.commit()
        for ks, k, v in ops:
            self._notify(ks, WatchEvent(WatchEvent.PUT, k, v))

    def mv(self, from_keyspace, to_keyspace, key):
        with self._guard:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE keyspace=? AND key=?",
                (from_keyspace.value, key),
            ).fetchone()
            if row is None:
                return
            self._conn.execute(
                "DELETE FROM kv WHERE keyspace=? AND key=?",
                (from_keyspace.value, key),
            )
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (keyspace, key, value) VALUES (?,?,?)",
                (to_keyspace.value, key, row[0]),
            )
            self._conn.commit()
        self._notify(from_keyspace, WatchEvent(WatchEvent.DELETE, key, None))
        self._notify(to_keyspace, WatchEvent(WatchEvent.PUT, key, row[0]))

    def delete(self, keyspace, key):
        with self._guard:
            cur = self._conn.execute(
                "DELETE FROM kv WHERE keyspace=? AND key=?", (keyspace.value, key)
            )
            self._conn.commit()
            existed = cur.rowcount > 0
        if existed:
            self._notify(keyspace, WatchEvent(WatchEvent.DELETE, key, None))

    def close(self) -> None:
        with self._guard:
            self._conn.close()


def EtcdBackend(endpoints: str, namespace: str = "ballista"):
    """Remote HA backend (the reference's etcd slot, ``backend/etcd.rs``).

    This image has no etcd3 client, so the same semantics — shared remote
    store, transactional puts, lease locks with TTL expiry, prefix watches
    — are served by this repo's own KvStoreGrpc service
    (:mod:`.kvstore`): run ``python -m arrow_ballista_tpu.scheduler.kvstore``
    (optionally over sqlite for durability) and point every scheduler's
    ``--state-backend etcd --etcd-urls host:port`` at it.
    """
    from .kvstore import RemoteBackend

    # comma lists are live failover spares: the client rotates to the
    # next endpoint on UNAVAILABLE (a backup kvstore refuses to serve
    # until it promotes, so rotation settles on the current primary)
    from .kvstore import parse_endpoint

    eps = [e.strip() for e in endpoints.split(",") if e.strip()]
    host, port = parse_endpoint(eps[0] if eps else "")
    return RemoteBackend(
        host, port, namespace=namespace,
        endpoints=eps if len(eps) > 1 else None,
    )


def create_backend(kind: str, path: Optional[str] = None) -> StateBackend:
    if kind in ("memory", "standalone"):
        return MemoryBackend()
    if kind == "sqlite":
        if not path:
            raise ValueError("sqlite backend needs a path")
        return SqliteBackend(path)
    if kind == "etcd":
        return EtcdBackend(path or "")
    raise ValueError(f"unknown state backend {kind!r}")
