"""In-process scheduler for standalone mode.

Counterpart of the reference's ``scheduler/src/standalone.rs:33-60``: a
scheduler on a random localhost port over an in-memory state backend.
"""

from __future__ import annotations

import logging
import uuid
from typing import Optional, Tuple

import grpc

from ..config import TaskSchedulingPolicy
from ..proto.rpc import add_scheduler_servicer, make_server
from .backend import MemoryBackend, StateBackend
from .grpc_service import SchedulerGrpcService
from .server import SchedulerServer

log = logging.getLogger(__name__)


class StandaloneScheduler:
    def __init__(self, server: SchedulerServer, grpc_server: grpc.Server, port: int):
        self.server = server
        self.grpc_server = grpc_server
        self.port = port
        self.host = "127.0.0.1"

    def shutdown(self) -> None:
        self.grpc_server.stop(grace=1)
        self.server.stop()


def new_standalone_scheduler(
    policy: TaskSchedulingPolicy = TaskSchedulingPolicy.PULL_STAGED,
    backend: Optional[StateBackend] = None,
    liveness_window_s: float = 60.0,
    executor_timeout_s: float = 180.0,
    event_journal_dir: str = "",
    telemetry_sample_s: float = 1.0,
    autoscaler_settings: Optional[dict] = None,
    executor_provider_factory=None,
    **server_kwargs,
) -> StandaloneScheduler:
    """``executor_provider_factory`` is ``(host, port) -> ExecutorProvider``
    — a factory because the scheduler's port doesn't exist until the gRPC
    server binds, and a subprocess provider needs that address to hand to
    the executors it launches.  ``None`` with autoscaling enabled builds a
    :class:`LocalProcessProvider` against the bound port."""
    backend = backend or MemoryBackend()
    scheduler_id = f"localhost:{uuid.uuid4().hex[:6]}"
    server = SchedulerServer(
        scheduler_id,
        backend,
        policy,
        liveness_window_s=liveness_window_s,
        executor_timeout_s=executor_timeout_s,
        event_journal_dir=event_journal_dir,
        # standalone exists for tests/local runs: sample the cluster
        # aggregates tightly so short-lived clusters still get history
        telemetry_sample_s=telemetry_sample_s,
        **server_kwargs,
    ).init()
    grpc_server = make_server()
    add_scheduler_servicer(grpc_server, SchedulerGrpcService(server))
    # the KEDA scaler rides the same gRPC server, like the reference's mux
    from .external_scaler import ExternalScalerService, add_external_scaler_servicer

    add_external_scaler_servicer(grpc_server, ExternalScalerService(server))
    port = grpc_server.add_insecure_port("127.0.0.1:0")
    grpc_server.start()
    # the scheduler id doubles as the curator address executors report to
    server.scheduler_id = f"127.0.0.1:{port}"
    server.state.task_manager.scheduler_id = server.scheduler_id
    from .autoscaler import AutoscalerPolicy

    if AutoscalerPolicy.enabled_in(autoscaler_settings):
        if executor_provider_factory is None:
            from .autoscaler import LocalProcessProvider

            provider = LocalProcessProvider("127.0.0.1", port)
        else:
            provider = executor_provider_factory("127.0.0.1", port)
        server.attach_autoscaler(provider, autoscaler_settings)
    log.info("standalone scheduler up at 127.0.0.1:%d (%s)", port, policy.value)
    return StandaloneScheduler(server, grpc_server, port)
