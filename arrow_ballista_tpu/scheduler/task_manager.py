"""Job lifecycle + task dispatch.

Counterpart of the reference's ``scheduler/src/state/task_manager.rs``:
graphs are built on submit, persisted to the ActiveJobs keyspace and cached
behind per-job locks; ``fill_reservations`` walks cached jobs popping tasks
into reserved slots; completed/failed jobs move keyspaces; ``launch_task``
pushes TaskDefinitions to executors through a pluggable launcher (a no-op
launcher stands in for gRPC in tests, mirroring the reference's
``#[cfg(test)]`` no-op, `task_manager.rs:440-449`).
"""

from __future__ import annotations

import random
import string
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..errors import SchedulerError
from ..exec.operators import ExecutionPlan
from ..obs import trace
from ..obs.export import AQE_OP, CACHE_OP, LOCALITY_OP
from ..obs.recorder import trace_store
from ..obs.registry import MetricsRegistry
from ..proto import pb
from ..serde import BallistaCodec, partitioning_to_proto
from ..serde.scheduler_types import ExecutorMetadata, PartitionId
from .backend import Keyspace, StateBackend
from .execution_graph import COMPLETED, FAILED, RUNNING, ExecutionGraph, Task
from .execution_stage import TaskInfo
from .executor_manager import ExecutorManager, ExecutorReservation


class TaskLauncher:
    """Transport for pushing tasks to executors (push scheduling)."""

    def launch(
        self,
        executor: ExecutorMetadata,
        tasks: List[pb.TaskDefinition],
        scheduler_id: str,
    ) -> None:
        raise NotImplementedError


class NoopLauncher(TaskLauncher):
    """Test stand-in; records what would have been sent."""

    def __init__(self) -> None:
        self.launched: List[Tuple[str, List[pb.TaskDefinition]]] = []

    def launch(self, executor, tasks, scheduler_id):
        self.launched.append((executor.id, tasks))


class GrpcLauncher(TaskLauncher):
    """Real transport: LaunchTask RPC on the executor's grpc port, over
    the process-wide pooled channel cache shared with every other
    scheduler→executor control-plane call (``proto/rpc.executor_stub``;
    reference: task_manager.rs:416-438)."""

    def launch(self, executor, tasks, scheduler_id):
        from ..proto.rpc import executor_stub

        stub = executor_stub(executor.host, executor.grpc_port)
        stub.LaunchTask(
            pb.LaunchTaskParams(tasks=tasks, scheduler_id=scheduler_id),
            timeout=20,
        )


def _plan_tree_text(plan: ExecutionPlan, depth: int = 0, limit: int = 40) -> str:
    """Indented one-line-per-operator tree for the dashboard plan view
    (the reference UI's query-plan panel; rendered as <pre> client-side)."""
    lines = ["  " * depth + str(plan)]
    if depth < limit:
        for child in plan.children():
            lines.append(_plan_tree_text(child, depth + 1, limit))
    return "\n".join(lines)


@dataclass
class JobEntry:
    lock: threading.RLock = field(default_factory=threading.RLock)
    graph: Optional[ExecutionGraph] = None


class TaskManager:
    def __init__(
        self,
        backend: StateBackend,
        executor_manager: ExecutorManager,
        scheduler_id: str,
        launcher: Optional[TaskLauncher] = None,
        work_dir: str = "/tmp/ballista-tpu",
        registry: Optional[MetricsRegistry] = None,
        events=None,
        slo=None,
        config_overrides: Optional[Dict[str, str]] = None,
        admission=None,
        plan_cache=None,
        policy_store=None,
    ):
        from ..obs.events import EventJournal

        self.backend = backend
        # multi-tenant admission controller (scheduler/admission.py);
        # None for bare TaskManagers in tests — every admission touch
        # point below is a no-op then
        self.admission = admission
        self.executor_manager = executor_manager
        self.scheduler_id = scheduler_id
        self.launcher = launcher or GrpcLauncher()
        self.work_dir = work_dir
        # scheduler-flag session-setting overrides (e.g. --aqe-enabled
        # forces ballista.aqe.enabled for every submitted job); applied
        # on top of the session settings at submit-time planning
        self.config_overrides = dict(config_overrides or {})
        # structured event journal + SLO tracker (obs/events.py,
        # obs/timeseries.py): shared with the owning SchedulerState; a
        # bare TaskManager (tests) gets a disabled journal
        self.events = events if events is not None else EventJournal()
        self.slo = slo
        # plan-fingerprint result/shuffle cache + learned per-plan policy
        # (scheduler/plan_cache.py, scheduler/policy_store.py); None for
        # bare TaskManagers and when the owning state never enables them.
        # Both are gated per-job by the session config knobs, so a wired
        # store with ballista.cache.enabled=false is still a no-op.
        self.plan_cache = plan_cache
        self.policy_store = policy_store
        # job_id -> learned props to stamp onto TaskDefinitions for keys
        # the session didn't set (mirrors the SHUFFLE_PIPELINED stamp)
        self._policy_props: Dict[str, Dict[str, str]] = {}
        self._cache: Dict[str, JobEntry] = {}
        self._cache_lock = threading.Lock()
        # scheduler-lifetime counters live in the unified registry
        # (obs/registry.py) backing /api/metrics + Prometheus exposition
        self.registry = registry or MetricsRegistry()
        self._retries = self.registry.counter(
            "task_retries_total",
            "transient-failure task re-queues over scheduler lifetime",
        )
        self._jobs_completed = self.registry.counter(
            "jobs_completed_total", "jobs that reached COMPLETED"
        )
        self._jobs_failed = self.registry.counter(
            "jobs_failed_total", "jobs that reached FAILED"
        )
        # speculative execution (scheduler/speculation.py drives the scan;
        # dispatch/commit paths here own the counters)
        self._spec_launched = self.registry.counter(
            "speculative_launched",
            "duplicate straggler attempts dispatched",
        )
        self._spec_wins = self.registry.counter(
            "speculative_wins",
            "partitions committed by a speculative duplicate",
        )
        self._spec_wasted = self.registry.counter(
            "speculative_wasted",
            "speculative duplicates that lost the race or died",
        )
        # replicated shuffle storage (ISSUE 6): scheduler-side rollup of
        # the data-plane counters so /api/metrics shows them even when
        # executors run in other processes
        self._replicas_written = self.registry.counter(
            "shuffle_replicas_written",
            "shuffle partitions committed with an external-store replica",
        )
        self._replica_fetches = self.registry.counter(
            "replica_fetches_total",
            "shuffle reads served by a replica after primary failover",
        )
        self._drain_handoffs = self.registry.counter(
            "drain_handoffs_total",
            "tasks handed off a draining executor without burning budget",
        )

    @property
    def task_retries_total(self) -> int:
        """Back-compat read surface for the old ad-hoc counter."""
        return int(self._retries.value)

    # ------------------------------------------------------------ helpers
    def _entry(self, job_id: str) -> JobEntry:
        with self._cache_lock:
            e = self._cache.get(job_id)
            if e is None:
                e = JobEntry()
                self._cache[job_id] = e
            return e

    def _load(self, job_id: str, entry: JobEntry) -> Optional[ExecutionGraph]:
        if entry.graph is not None:
            return entry.graph
        raw = self.backend.get(Keyspace.ActiveJobs, job_id)
        if raw is None:
            return None
        entry.graph = ExecutionGraph.decode(raw, self.work_dir)
        return entry.graph

    def _persist(self, graph: ExecutionGraph) -> None:
        # single choke point every graph mutation passes through: flush
        # wasted-duplicate counts into the registry so /api/metrics stays
        # reconciled with the per-stage spec_stats rollup whichever path
        # (commit, failure, reset, reap, executor loss) dropped the copy
        wasted = graph.take_spec_wasted()
        if wasted:
            self._spec_wasted.inc(wasted)
        # ...and drain the graph's queued journal events (stage
        # completion/skew, retries, speculation outcomes, reaps,
        # lost-shuffle recovery, drain handoffs) into the event journal —
        # drained unconditionally so a disabled journal never accumulates
        self.events.emit_many(
            graph.take_pending_events(),
            job=graph.job_id,
            trace=graph.trace_id,
        )
        try:
            self.backend.put(Keyspace.ActiveJobs, graph.job_id, graph.encode())
        except Exception:
            # store unreachable (outage) or write refused: the in-memory
            # graph now holds UNPERSISTED mutations — e.g. a task popped
            # by fill_reservations that its caller will never deliver
            # once this raises.  Drop the cached copy so the next load
            # re-reads the last persisted state; otherwise the mutation
            # strands (a "running" task no executor ever received).
            with self._cache_lock:
                e = self._cache.get(graph.job_id)
            if e is not None:
                e.graph = None
            raise

    def _cache_sync(self, graph: ExecutionGraph) -> None:
        """Plan-cache upkeep after task-status updates commit (caller
        holds the job entry lock): pin newly-completed eligible stages
        under their fingerprints, and evict entries the lost-shuffle
        recovery path proved hollow.  Best-effort — a cache failure must
        never fail the status update."""
        if self.plan_cache is None:
            return
        cfg = getattr(graph, "cache_config", None)
        if cfg is not None:
            from .plan_cache import store_completed

            try:
                store_completed(graph, self.plan_cache, cfg)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "plan-cache store failed for %s", graph.job_id
                )
        take = getattr(graph, "take_pending_cache_invalidations", None)
        if take is not None:
            for fp in take():
                try:
                    self.plan_cache.invalidate(fp)
                except Exception:
                    pass

    # ------------------------------------------------------------ recovery
    def recover_active_jobs(self) -> List[str]:
        """Resume every ActiveJobs graph from the backend (scheduler
        restart).  Graphs persist Running stages as Resolved
        (execution_graph.py module rule, mirroring the reference's
        ``execution_graph.rs:867-920``), so revive() re-marks their tasks
        dispatchable and the normal offer/poll path re-executes exactly
        the in-flight work — completed stages keep their locations.
        Returns the recovered job ids."""
        out: List[str] = []
        for job_id in self.backend.scan_keys(Keyspace.ActiveJobs):
            entry = self._entry(job_id)
            with entry.lock:
                graph = self._load(job_id, entry)
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                graph.revive()
                self._persist(graph)
                self._admission_adopt(graph)
                out.append(job_id)
        return out

    def _admission_adopt(self, graph: ExecutionGraph) -> None:
        """Restart/HA adoption: re-register a recovered admission-managed
        job with the controller so pool concurrency accounting survives.
        Queued (pre-planning) jobs are recovered separately: with
        ``--admission-wal-enabled`` the queue WAL replays them in submit
        order (``SchedulerServer.replay_admission_wal``); without it
        they are lost and their clients' retries re-enter the front
        door."""
        if self.admission is not None and graph.admission_enabled:
            self.admission.adopt_running(
                graph.job_id, graph.tenant_pool, graph.tenant_priority
            )

    def take_over_jobs(self, dead_scheduler_id: str) -> List[str]:
        """HA failover: adopt every active job CURATED by a dead peer
        scheduler (reference: jobs carry a curator scheduler id,
        ``execution_graph.rs:99-101``; with a shared etcd-style backend any
        surviving scheduler can resume them).  Returns adopted job ids."""
        out: List[str] = []
        lk = self.backend.lock(
            Keyspace.ActiveJobs, f"takeover:{dead_scheduler_id}"
        )
        with lk:
            for job_id in self.backend.scan_keys(Keyspace.ActiveJobs):
                entry = self._entry(job_id)
                with entry.lock:
                    entry.graph = None  # peer may have persisted newer state
                    graph = self._load(job_id, entry)
                    if graph is None or graph.status in (COMPLETED, FAILED):
                        continue
                    if graph.scheduler_id != dead_scheduler_id:
                        continue
                    graph.scheduler_id = self.scheduler_id
                    graph.revive()
                    # the adoption write carries the grant's fencing
                    # token (remote lease) — if this sweeper's lease
                    # lapsed (TTL outlived without a refresh), the store
                    # rejects the write and a live sweeper wins; local
                    # backends ignore the fence
                    try:
                        self.backend.put_txn(
                            [(Keyspace.ActiveJobs, job_id, graph.encode())],
                            fence=lk,
                        )
                    except Exception:
                        entry.graph = None  # store refused: reload
                        raise
                    self._admission_adopt(graph)
                    out.append(job_id)
        return out

    # -------------------------------------------------------------- submit
    def _policy_consult(
        self, job_id: str, plan: ExecutionPlan, config
    ) -> Tuple[str, str, Dict[str, str]]:
        """Shape-fingerprint the raw submitted plan (no source-snapshot
        identity — knob choices don't depend on the data) and ask the
        policy store which arm this run lands on.  Any failure degrades
        to baseline: the policy layer must never fail a submit."""
        from .plan_cache import plan_fingerprint

        try:
            fp = plan_fingerprint(plan, with_snapshot=False)
        except Exception:
            return "", "baseline", {}
        try:
            overrides, arm = self.policy_store.overrides_for(
                job_id, fp, config.cache_policy_shadow_fraction
            )
        except Exception:
            return fp, "baseline", {}
        return fp, arm, dict(overrides)

    def submit_job(
        self,
        job_id: str,
        session_id: str,
        plan: ExecutionPlan,
        trace_id: str = "",
    ) -> ExecutionGraph:
        from ..config import BallistaConfig

        # the session's config steers distributed planning (mesh gang
        # stages, shuffle data plane) exactly as it steers acceleration;
        # scheduler-flag overrides seed cluster-wide defaults that an
        # EXPLICIT session setting still wins over (session settings are
        # sparse — only user-set keys ship), so per-session A/B toggles
        # like ballista.aqe.enabled=false keep working under the flag
        session_settings = self._session_settings(session_id)
        settings = session_settings
        if self.config_overrides:
            settings = {**self.config_overrides, **settings}
        config = BallistaConfig(settings)
        # learned per-plan policy (ISSUE 18 layer 2): overrides sit ABOVE
        # cluster-flag defaults but BENEATH explicit session settings, so
        # a user's deliberate knob always wins over what the store learned
        policy_fp, policy_arm, policy_overrides = "", "baseline", {}
        if self.policy_store is not None and config.cache_policy_enabled:
            policy_fp, policy_arm, policy_overrides = self._policy_consult(
                job_id, plan, config
            )
            if policy_overrides:
                settings = {
                    **self.config_overrides,
                    **policy_overrides,
                    **session_settings,
                }
                config = BallistaConfig(settings)
        if self.admission is not None and self.admission.take_cancel_intent(
            job_id
        ):
            # cancel raced the admission release: the user gave up while
            # the job sat queued — fail it instead of building a graph
            self.admission.job_finished(job_id)
            raise SchedulerError("job cancelled by user while queued")
        graph = ExecutionGraph(
            self.scheduler_id, job_id, session_id, plan, self.work_dir, config
        )
        # set BEFORE the graph becomes poppable: a concurrent pull-mode
        # PollWork may dispatch first-stage tasks the moment the entry is
        # cached, and those TaskDefinitions must already carry the trace
        graph.trace_id = trace_id
        # policy bookkeeping rides the in-memory graph only (decoded
        # graphs degrade to baseline — getattr defaults downstream)
        graph.policy_fp = policy_fp
        graph.policy_arm = policy_arm
        graph.policy_overrides = dict(policy_overrides)
        if policy_overrides:
            self._policy_props[job_id] = dict(policy_overrides)
            self.events.emit(
                "policy_applied",
                job=job_id,
                trace=trace_id,
                fingerprint=policy_fp,
                overrides=dict(policy_overrides),
            )
        # result/shuffle cache (ISSUE 18 layer 1): serve matching stage
        # subtrees straight from the external store BEFORE revive() can
        # resolve/dispatch them; a serve failure must never fail a submit
        if self.plan_cache is not None and config.cache_enabled:
            graph.cache_config = config
            try:
                from .plan_cache import try_serve

                try_serve(graph, self.plan_cache, config)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "plan-cache serve failed for %s", job_id
                )
        graph.revive()
        self.events.emit(
            "job_submitted",
            job=job_id,
            trace=trace_id,
            session=session_id,
            stages=len(graph.stages),
            partitions=graph.output_partitions,
        )
        entry = self._entry(job_id)
        with entry.lock:
            entry.graph = graph
            try:
                self._persist(graph)
            except Exception:
                # nothing durable exists for this job: evict the cache
                # entry too, or active_job_ids() would report a phantom
                # job forever (KEDA's inflight metric never draining)
                with self._cache_lock:
                    self._cache.pop(job_id, None)
                raise
        if graph.status == COMPLETED:
            # full-plan cache hit: every stage was served, no task will
            # ever run, so no task-status update will drive completion —
            # close the job out right here (moves it to CompletedJobs,
            # records SLO/policy observations, emits the job span)
            self.complete_job(job_id)
        return graph

    def get_job_status(self, job_id: str) -> Optional[dict]:
        """Status snapshot: {state, error?, locations?}.

        Read-only: must NOT create a cache entry — a finished job's status
        is polled long after complete_job() evicted it, and a stray entry
        would make active_job_ids() (and the KEDA scaler's inflight metric)
        report the job forever."""
        if self.admission is not None:
            # a job held in the admission queue has no graph anywhere:
            # report QUEUED with its pool + position so clients can tell
            # a waiting job from a wedged one
            qs = self.admission.queued_status(job_id)
            if qs is not None:
                return qs
        return self._with_graph(job_id, self._status_of)

    @staticmethod
    def _status_of(graph: ExecutionGraph) -> dict:
        out = {"state": graph.status, "job_id": graph.job_id}
        if graph.status == FAILED:
            out["error"] = graph.error
        if graph.status == COMPLETED:
            out["locations"] = list(graph.output_locations)
        return out

    def _with_graph(self, job_id: str, fn):
        """Apply ``fn(graph)`` to the job's graph and return the result.

        For a cached (live) job, ``fn`` runs UNDER the entry lock — the
        scheduler mutates graph/stage state under that same lock from gRPC
        threads, so unlocked reads from the REST thread would race dict
        resizes mid-iteration.  Decoded (persisted) graphs are private
        copies and need no lock.  Read-only like get_job_status: never
        creates a cache entry."""
        with self._cache_lock:
            entry = self._cache.get(job_id)
        if entry is not None:
            with entry.lock:
                graph = self._load(job_id, entry)
                if graph is not None:
                    return fn(graph)
        for ks in (Keyspace.CompletedJobs, Keyspace.FailedJobs, Keyspace.ActiveJobs):
            raw = self.backend.get(ks, job_id)
            if raw is not None:
                return fn(ExecutionGraph.decode(raw, self.work_dir))
        return None

    def get_job_detail(self, job_id: str) -> Optional[dict]:
        """Per-stage drill-down for the scheduler UI (the reference UI's
        QueriesList row expansion, ``ballista/ui/scheduler/src/components/
        QueriesList.tsx``): stage state machine position, task progress
        and merged operator metrics per stage."""
        if self.admission is not None:
            qs = self.admission.queued_status(job_id)
            if qs is not None:
                return qs
        return self._with_graph(job_id, self._detail_of)

    def _detail_of(self, graph: ExecutionGraph) -> dict:
        from ..obs.critical_path import stage_timing_of

        detail = self._status_of(graph)
        detail["task_retries"] = graph.task_retries
        detail["stage_resets"] = dict(graph.stage_reset_counts)
        # job-level timeline anchors for critical-path attribution
        # (persisted with the graph, so a decoded copy keeps the
        # ORIGINAL submit anchor)
        detail["submitted_us"] = graph.submitted_unix_ns // 1000
        detail["planning_us"] = getattr(graph, "planning_ns", 0) // 1000
        # per-job attempt histogram: {attempts_consumed: n_tasks}; tasks
        # that never failed land in bucket 0
        histogram: Dict[int, int] = {}
        stages = []
        for sid in sorted(graph.stages):
            stage = graph.stages[sid]
            state = type(stage).__name__.replace("Stage", "")
            row = {
                "stage_id": sid,
                "state": state,
                "partitions": stage.partitions,
            }
            count = getattr(stage, "completed_tasks", None)
            if count is not None:
                row["completed_tasks"] = count()
            attempts = getattr(stage, "task_attempts", None)
            if attempts is not None:
                for p in range(stage.partitions):
                    a = attempts.get(p, 0)
                    histogram[a] = histogram.get(a, 0) + 1
                if attempts:
                    row["task_attempts"] = dict(attempts)
                row["task_retries"] = sum(attempts.values())
            fetch_retries = getattr(stage, "task_fetch_retries", None)
            if fetch_retries:
                row["fetch_retries"] = sum(fetch_retries.values())
            spec_stats = getattr(stage, "spec_stats", None)
            if spec_stats:
                row["speculation"] = dict(spec_stats)
            aqe = getattr(stage, "aqe", None) or (
                getattr(stage, "stage_metrics", None) or {}
            ).get(AQE_OP)
            if aqe:
                # adaptive re-plan outcome (tasks before/after, rewrite
                # counts) — also persisted inside stage_metrics[__aqe__]
                row["aqe"] = dict(aqe)
            served = (getattr(stage, "stage_metrics", None) or {}).get(
                CACHE_OP
            )
            if served:
                # plan-cache serve outcome: the stage (and its elided
                # upstream subtree) never dispatched a task
                row["cache"] = dict(served)
            placement = getattr(stage, "locality_stats", None) or (
                getattr(stage, "stage_metrics", None) or {}
            ).get(LOCALITY_OP)
            if placement:
                # locality placement outcome: tasks dispatched on their
                # preferred (most-input-bytes) host vs anywhere else
                row["locality_placement"] = dict(placement)
            pipeline = self._pipeline_of(stage)
            if pipeline:
                # pipelined-execution classification (streamable vs
                # pipeline-breaker inputs) + whether the stage actually
                # started on partial input — the doctor's evidence for
                # whether barrier_wait upside is reachable
                row["pipeline"] = pipeline
            failures = getattr(stage, "task_failures", None)
            if failures:
                row["failures"] = {p: list(h) for p, h in failures.items()}
            metrics = getattr(stage, "stage_metrics", None)
            if metrics:
                row["metrics"] = {
                    op: dict(vals) for op, vals in metrics.items()
                }
            err = getattr(stage, "error", "")
            if err:
                row["error"] = err
            # critical-path timeline anchors (live attrs on
            # Resolved/Running stages, persisted synthetic metrics on
            # Completed ones) — obs/critical_path.py's input
            timing = stage_timing_of(stage)
            if timing:
                row["timing"] = timing
            # DAG edges + operator tree for the dashboard's SVG plan view
            # (the reference UI renders the stage graph; QueriesList.tsx)
            row["output_links"] = list(getattr(stage, "output_links", []))
            row["plan"] = _plan_tree_text(stage.plan)
            stages.append(row)
        detail["stages"] = stages
        detail["attempt_histogram"] = histogram
        # decoded (persisted) graphs lose the live counter but keep the
        # per-task attempts; derive so completed jobs still report retries
        attempts_total = sum(a * n for a, n in histogram.items())
        detail["task_retries"] = max(detail["task_retries"], attempts_total)
        return detail

    @staticmethod
    def _pipeline_of(stage) -> dict:
        """Per-stage pipelined-execution block for the job detail:
        streamable/breaker input classification (planner walk — works on
        unresolved placeholders and resolved readers alike) plus the
        partial-start marker (live flag on Running stages, persisted
        ``__pipelined__`` metric on Completed ones)."""
        from ..obs.export import PIPELINED_OP
        from .planner import classify_shuffle_inputs

        out: dict = {}
        if getattr(stage, "inputs", None):
            try:
                streamable, breakers = classify_shuffle_inputs(stage.plan)
            except Exception:  # noqa: BLE001 - classification is advisory
                streamable, breakers = set(), set()
            if streamable or breakers:
                out["streamable_inputs"] = sorted(streamable)
                out["breaker_inputs"] = sorted(breakers)
        partial = getattr(stage, "started_on_partial", False) or bool(
            (getattr(stage, "stage_metrics", None) or {}).get(PIPELINED_OP)
        )
        if partial:
            out["partial_start"] = True
        return out

    def get_job_dot(self, job_id: str) -> Optional[str]:
        """GraphViz text of the job's stage DAG (reference: the UI's plan
        view via ``core/src/utils.rs produce_diagram``)."""
        from ..utils.diagram import produce_diagram

        return self._with_graph(job_id, produce_diagram)

    def get_job_progress(self, job_id: str) -> Optional[dict]:
        """Live progress snapshot (``GET /api/jobs/{id}/progress`` and
        the gRPC ``include_progress`` poll): per-stage
        done/running/pending task counts and written bytes, plus a job
        ETA extrapolated from the observed median task runtime and the
        current dispatch width.  Cheap by design — the client poll loop
        may request it every interval."""
        if self.admission is not None:
            qs = self.admission.queued_status(job_id)
            if qs is not None:
                # still in the admission queue: no graph, no stages —
                # progress is the queue coordinates
                return {
                    **qs,
                    "stages": [],
                    "tasks_total": 0,
                    "tasks_done": 0,
                    "tasks_running": 0,
                    "eta_s": None,
                }
        return self._with_graph(job_id, self._progress_of)

    @staticmethod
    def _progress_of(graph: ExecutionGraph) -> dict:
        import statistics

        from .execution_stage import CompletedStage, RunningStage

        out = {
            "job_id": graph.job_id,
            "state": graph.status,
            "stages": [],
        }
        if graph.status == FAILED:
            out["error"] = graph.error
        total = done = running_now = 0
        runtimes: List[float] = []
        cache_elided = getattr(graph, "cache_elided", None) or set()
        cache_served = getattr(graph, "cache_served", None) or {}
        for sid in sorted(graph.stages):
            stage = graph.stages[sid]
            n = stage.partitions
            row = {
                "stage_id": sid,
                "state": type(stage).__name__.replace("Stage", ""),
                "partitions": n,
                "completed": 0,
                "running": 0,
                "pending": n,
            }
            if sid in cache_elided:
                # upstream of a cache-served stage: will never dispatch a
                # task — excluded from the task totals so a (partially)
                # served job's done/total fraction still reaches 1.0
                row["pending"] = 0
                row["cache_elided"] = True
                out["stages"].append(row)
                continue
            total += n
            if sid in cache_served:
                row["cache_served"] = True
            if isinstance(stage, (RunningStage, CompletedStage)):
                completed = stage.completed_tasks()
                row["completed"] = completed
                done += completed
                if isinstance(stage, RunningStage):
                    active = sum(
                        1
                        for t in stage.task_statuses
                        if t is not None and t.state == "running"
                    )
                    row["running"] = active
                    running_now += active
                    if stage.started_on_partial:
                        # pipelined: these runtimes include stall-on-
                        # producer, so they must not inflate the
                        # observed-median ETA; the flag also tells
                        # clients the "running" tasks are streaming a
                        # producer that is NOT done yet
                        row["partial_input"] = True
                    else:
                        runtimes.extend(stage.completed_runtime_s)
                    bytes_wire = sum(
                        b.get("wire", 0) for b in stage.task_bytes.values()
                    )
                else:
                    from ..obs.export import PIPELINED_OP, TASK_RUNTIME_OP

                    if not stage.stage_metrics.get(PIPELINED_OP):
                        ms = stage.stage_metrics.get(TASK_RUNTIME_OP, {})
                        runtimes.extend(v / 1e3 for v in ms.values())
                    bytes_wire = sum(
                        stage.output_partition_bytes().values()
                    )
                row["pending"] = max(0, n - row["completed"] - row["running"])
                if bytes_wire:
                    row["bytes_wire"] = bytes_wire
            out["stages"].append(row)
        out["tasks_total"] = total
        out["tasks_done"] = done
        out["tasks_running"] = running_now
        if graph.status in (COMPLETED, FAILED):
            # a decoded (evicted) graph re-stamps its monotonic anchor,
            # so terminal elapsed comes from the persisted wall anchors:
            # submit (graph proto) → the last task commit anywhere (a
            # FAILED job has no final-stage completion, but its finished
            # stages persist __stage_timing__ too)
            from ..obs.export import STAGE_TIMING_OP

            submitted = graph.submitted_unix_ns // 1000
            end = 0
            for stage in graph.stages.values():
                metrics = getattr(stage, "stage_metrics", None) or {}
                end = max(
                    end,
                    metrics.get(STAGE_TIMING_OP, {}).get("completed_us", 0),
                )
                fin = getattr(stage, "task_finish_unix_ns", None)
                if fin:
                    end = max(end, max(fin.values()) // 1000)
            out["elapsed_s"] = (
                round((end - submitted) / 1e6, 3) if end > submitted else None
            )
        else:
            out["elapsed_s"] = round(
                (time.monotonic_ns() - graph.submitted_mono_ns) / 1e9, 3
            )
        remaining = total - done
        if graph.status == RUNNING and remaining > 0 and runtimes:
            # optimistic-but-useful ETA: remaining waves at the observed
            # median task runtime over the current dispatch width
            import math

            width = max(1, running_now)
            out["eta_s"] = round(
                statistics.median(runtimes) * math.ceil(remaining / width), 3
            )
        else:
            out["eta_s"] = None if graph.status == RUNNING else 0.0
        return out

    # ------------------------------------------------------------- updates
    def update_task_statuses(
        self,
        executor: ExecutorMetadata,
        statuses: List[TaskInfo],
    ) -> List[Tuple[str, str]]:
        """Group statuses per job, apply to graphs; returns
        [(job_id, event)] with event in
        job_updated/job_completed/job_failed/task_retried
        (reference: task_manager.rs:132-170).

        Failed statuses feed the executor quarantine window; an executor
        quarantined by this batch gets its in-flight tasks reset so they
        re-dispatch elsewhere immediately instead of timing out."""
        per_job: Dict[str, List[TaskInfo]] = {}
        for s in statuses:
            # FailedTask carries no executor id on the wire; the reporting
            # executor ran it — stamp it for exclusion/quarantine tracking
            if s.state == "failed" and not s.executor_id:
                s.executor_id = executor.id
            per_job.setdefault(s.partition_id.job_id, []).append(s)

        events: List[Tuple[str, str]] = []
        newly_quarantined: List[str] = []
        cancels: List[Tuple[str, PartitionId]] = []
        feed_pushes: List[tuple] = []
        draining = self.executor_manager.is_draining(executor.id)
        for job_id, infos in per_job.items():
            entry = self._entry(job_id)
            with entry.lock:
                graph = self._load(job_id, entry)
                if graph is None:
                    continue
                for info in infos:
                    if info.spans:
                        # piggybacked executor spans → per-job trace store
                        # (dedup by span id there; stale-attempt statuses
                        # still surrender their spans before being dropped)
                        trace_store().add(info.spans)
                        info.spans = []
                    if draining and info.state == "failed" and (
                        self._is_drain_handoff(info.error)
                    ):
                        # graceful decommission: a draining executor's
                        # cancellations/transient failures are HANDOFFS —
                        # re-queue elsewhere without burning the failure
                        # budget or feeding quarantine.  Structured
                        # lost-shuffle failures and genuine fatal errors
                        # still take the normal classification path (a
                        # handoff would re-burn a full fetch cycle on
                        # vanished data, or delay a poison-pill verdict).
                        if graph.handoff_task(info.partition_id, executor.id):
                            self._drain_handoffs.inc()
                            events.append((job_id, "task_requeued"))
                        continue
                    evs = graph.update_task_status(info, executor)
                    if info.state == "completed" and evs:
                        # committed (not a stale duplicate): roll the
                        # data-plane replica counters up scheduler-side
                        self._replicas_written.inc(
                            sum(1 for p in info.partitions if p.replica_path)
                        )
                        fetched_from_replica = sum(
                            int(vals.get("replica_fetches", 0))
                            for _, vals in info.metrics
                        )
                        if fetched_from_replica:
                            self._replica_fetches.inc(fetched_from_replica)
                    for ev in evs:
                        # speculation outcomes feed counters, not the
                        # job-event stream (the accompanying completion
                        # already carries job_updated/job_completed)
                        if ev == "speculative_win":
                            self._spec_wins.inc()
                            continue
                        if ev == "speculative_wasted":
                            continue  # counted via _persist's drain
                        if ev == "task_retried":
                            self._retries.inc()
                        events.append((job_id, ev))
                    if info.state == "failed" and evs:
                        from .failure import indicts_reporter

                        # only infrastructure (transient) failures that the
                        # graph actually PROCESSED indict the host: a fatal
                        # plan/serde error is the job's fault, a stale
                        # duplicate of a superseded attempt (evs == [])
                        # must not re-count one real failure into the
                        # quarantine window, and a lost-shuffle fetch
                        # failure blames the vanished producer data, not
                        # the healthy consumer host
                        if indicts_reporter(info.error) and (
                            self.executor_manager.record_task_failure(
                                info.executor_id
                            )
                        ):
                            newly_quarantined.append(info.executor_id)
                cancels.extend(graph.take_pending_cancels())
                feed_pushes.extend(self._collect_feed_pushes(graph))
                self._cache_sync(graph)
                self._persist(graph)
        if cancels:
            # after the locks drop: losing duplicate attempts / reaped
            # stragglers get a best-effort CancelTasks fan-out
            self.cancel_task_attempts(cancels)
        self._push_shuffle_deltas(feed_pushes)
        for eid in newly_quarantined:
            for job_id, n in self.reset_executor_running_tasks(eid).items():
                # one task_requeued per reset task: the event loop mints a
                # replacement reservation for each in push mode (the
                # quarantined executor's own slots are sidelined)
                self._retries.inc(n)
                events.extend([(job_id, "task_requeued")] * n)
        return events

    @staticmethod
    def _is_drain_handoff(error: str) -> bool:
        """Which failures from a DRAINING executor are absorbed as
        budget-free handoffs: its drain-timeout cancellations (fatal by
        classification, but deliberate here) and transient infra noise.
        ShuffleFetchFailed must reach ``_recover_lost_shuffle`` and other
        fatal errors must fail fast as usual."""
        from .failure import FATAL, classify_failure, parse_shuffle_fetch_failure

        err = (error or "").strip()
        if err.startswith("Cancelled"):
            return True
        if parse_shuffle_fetch_failure(err) is not None:
            return False
        return classify_failure(err) != FATAL

    # -------------------------------------------- pipelined feed plane
    def get_shuffle_location_delta(
        self, job_id: str, stage_id: int, from_index: int
    ) -> dict:
        """``GetShuffleLocationDelta`` body: one producer feed's delta
        from ``from_index``.  Feeds live only on CACHED graphs (they are
        in-memory scheduler state) — an evicted/restarted job reports
        the feed invalid, which aborts the tail; the task's late status
        is then dropped by the rolled-back-stage guards."""
        invalid = {
            "stage": stage_id,
            "from_index": 0,
            "locations": [],
            "complete": False,
            "epoch": 0,
            "valid": False,
        }
        with self._cache_lock:
            entry = self._cache.get(job_id)
        if entry is None:
            return invalid
        with entry.lock:
            graph = entry.graph
            if graph is None:
                return invalid
            return graph.shuffle_feed_delta(stage_id, from_index)

    def _collect_feed_pushes(self, graph: ExecutionGraph) -> List[tuple]:
        """Under the job entry lock: drain the graph's queued feed
        deltas and resolve push targets (executors currently running
        tailing consumer tasks).  Deltas with no live target are simply
        dropped — the executor-side poll fallback reads the same feed."""
        deltas = graph.take_pending_feed_deltas()
        out: List[tuple] = []
        for d in deltas:
            targets = graph.tailing_executors(d["stage"])
            if targets:
                out.append((graph.job_id, d, sorted(targets)))
        return out

    def _executor_fanout(
        self,
        items: List[Tuple[str, object]],
        send,
        thread_name: str,
        log_label: str,
        log_level: int = 30,  # logging.WARNING
    ) -> None:
        """Best-effort per-executor RPC fan-out shared by CancelTasks and
        UpdateShuffleLocations: group ``(executor_id, payload)`` items,
        resolve each executor's metadata once (unknown executors are
        skipped — they may be gone — and pull-mode executors, which
        expose no gRPC port, never receive pushes), then run
        ``send(stub, payloads)`` per executor on ONE detached daemon
        thread over the pooled channel cache.  Detached because every
        payload here is advisory (guards/polls cover a lost RPC) and a
        dead executor's RPC timeout must never stall the event-loop
        thread issuing it; failures log at ``log_level`` and move on."""
        per: Dict[str, List[object]] = {}
        metas: Dict[str, ExecutorMetadata] = {}
        for executor_id, payload in items:
            if not executor_id:
                continue
            if executor_id not in metas:
                try:
                    metas[executor_id] = (
                        self.executor_manager.get_executor_metadata(executor_id)
                    )
                except Exception:  # noqa: BLE001 - executor may be gone
                    continue
            if not metas[executor_id].grpc_port:
                continue
            per.setdefault(executor_id, []).append(payload)
        if not per:
            return

        def run() -> None:
            import logging

            from ..proto.rpc import executor_stub

            for executor_id, payloads in per.items():
                meta = metas[executor_id]
                try:
                    send(
                        executor_stub(meta.host, meta.grpc_port), payloads
                    )
                except Exception as e:  # noqa: BLE001 - advisory RPC
                    logging.getLogger(__name__).log(
                        log_level, "%s to %s failed: %s",
                        log_label, executor_id, e,
                    )

        threading.Thread(target=run, name=thread_name, daemon=True).start()

    def _push_shuffle_deltas(self, pushes: List[tuple]) -> None:
        """Best-effort UpdateShuffleLocations fan-out (push mode) to the
        executors running tailing consumer tasks; failures only log at
        debug — the executor-side poll fallback reads the same feed."""
        items = [
            (eid, (job_id, delta))
            for job_id, delta, targets in pushes
            for eid in targets
        ]

        def send(stub, payloads) -> None:
            params = pb.UpdateShuffleLocationsParams()
            for job_id, delta in payloads:
                m = params.deltas.add()
                m.job_id = job_id
                m.stage_id = delta["stage"]
                m.from_index = delta["from_index"]
                m.complete = delta["complete"]
                m.valid = delta["valid"]
                m.epoch = delta["epoch"]
                for loc in delta["locations"]:
                    m.locations.add().CopyFrom(loc.to_proto())
            stub.UpdateShuffleLocations(params, timeout=5)

        self._executor_fanout(
            items, send, "shuffle-delta-fanout", "UpdateShuffleLocations",
            log_level=10,  # logging.DEBUG
        )

    def cancel_task_attempts(
        self, cancels: List[Tuple[str, PartitionId]]
    ) -> None:
        """Best-effort CancelTasks fan-out for losing duplicate attempts
        and reaped stragglers: a cancel is advisory (the
        committed-partition guard drops the loser's results either way)."""

        def send(stub, pids) -> None:
            stub.CancelTasks(
                pb.CancelTasksParams(
                    partition_ids=[p.to_proto() for p in pids]
                ),
                timeout=5,
            )

        self._executor_fanout(
            cancels, send, "cancel-tasks-fanout", "CancelTasks"
        )

    def reset_executor_running_tasks(self, executor_id: str) -> Dict[str, int]:
        """Re-queue (with exclusion) every in-flight task on a quarantined
        executor across cached jobs; returns {job_id: tasks reset}.  Unlike
        ``executor_lost`` this does NOT roll back completed shuffle output
        — the host is sick, not gone, and its files are still servable."""
        with self._cache_lock:
            job_ids = list(self._cache.keys())
        affected: Dict[str, int] = {}
        for job_id in job_ids:
            entry = self._entry(job_id)
            with entry.lock:
                graph = self._load(job_id, entry)
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                n = graph.reset_running_tasks(executor_id)
                if n:
                    affected[job_id] = n
                    self._persist(graph)
        return affected

    def running_tasks_by_executor(self) -> Dict[str, int]:
        """Dispatched tasks per executor across every ActiveJobs graph in
        the backend (all curators — with a shared backend a peer's
        in-flight work counts too).  Input for the restart-time slot
        reconcile."""
        per: Dict[str, int] = {}
        for job_id in self.backend.scan_keys(Keyspace.ActiveJobs):
            entry = self._entry(job_id)
            with entry.lock:
                graph = self._load(job_id, entry)
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                for eid, n in graph.running_tasks_by_executor().items():
                    per[eid] = per.get(eid, 0) + n
        return per

    # ------------------------------------------------------------ dispatch
    def fill_reservations(
        self, reservations: List[ExecutorReservation]
    ) -> Tuple[List[Tuple[str, Task]], List[ExecutorReservation], int]:
        """Assign tasks to reserved slots.  Returns (assignments as
        (executor_id, task), unassigned reservations, pending tasks count)
        (reference: task_manager.rs:184-221)."""
        em = self.executor_manager
        quarantined = set(em.quarantined_executors())
        # quarantined AND draining executors' slots sit out this cycle
        # entirely — returned unfilled so the caller cancels them back to
        # the pool (a draining executor must never take NEW work)
        sitting_out = quarantined | set(em.draining_executors())
        free = [r for r in reservations if r.executor_id not in sitting_out]
        sidelined = [r for r in reservations if r.executor_id in sitting_out]
        assignments: List[Tuple[str, Task]] = []
        pending = 0

        # exclusion escape hatch: a task is never retried on the executor
        # that just failed it UNLESS that executor is the only live
        # candidate (otherwise a 1-executor cluster could never retry)
        alive = em.get_alive_executors() - sitting_out

        def _allow_excluded(executor_id: str) -> bool:
            return not (alive - {executor_id})

        # executor host per reservation (memoized): locality-aware
        # pop_next_task prefers tasks whose input bytes live on the
        # popping executor's host
        hosts: Dict[str, str] = {}

        def _host_of(executor_id: str) -> str:
            h = hosts.get(executor_id)
            if h is None:
                try:
                    h = em.get_executor_metadata(executor_id).host
                except Exception:  # noqa: BLE001 - host unknown: no pref
                    h = ""
                hosts[executor_id] = h
            return h

        with self._cache_lock:
            job_ids = list(self._cache.keys())
        # weighted fair dispatch (scheduler/admission.py): when any
        # cached job is admission-managed, walk jobs in fair-share order
        # instead of submit FIFO — interactive lane first, then by the
        # pool's weighted running-task share.  With no admission-managed
        # job this returns the list untouched (byte-identical A/B).
        job_ids = self._admission_order(job_ids)

        feed_pushes: List[tuple] = []
        for job_id in job_ids:
            if not free:
                break
            entry = self._entry(job_id)
            with entry.lock:
                graph = self._load(job_id, entry)
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                graph.revive()
                # partial resolution inside revive may have seeded a
                # shuffle feed: drain its deltas (and push-notify any
                # already-running tailing consumers) whether or not a
                # task pops below
                feed_pushes.extend(self._collect_feed_pushes(graph))
                changed = False
                start = len(assignments)
                free_before = list(free)
                still_free = []
                for r in free:
                    task = graph.pop_next_task(
                        r.executor_id,
                        allow_excluded=_allow_excluded(r.executor_id),
                        executor_host=_host_of(r.executor_id),
                    )
                    if task is None:
                        still_free.append(r)
                        continue
                    if task.speculative:
                        self._spec_launched.inc()
                    assignments.append((r.executor_id, task))
                    changed = True
                free = still_free
                pending += graph.available_tasks()
                if changed:
                    try:
                        self._persist(graph)
                    except Exception:
                        # this job's pops never became durable (_persist
                        # dropped its cached graph, so it reloads the
                        # last persisted state): withdraw ITS assignments
                        # and give the reservations back, but keep and
                        # deliver every assignment persisted for earlier
                        # jobs — otherwise their tasks strand as Running
                        # with no executor ever receiving them
                        import logging

                        logging.getLogger(__name__).warning(
                            "persist failed filling reservations for %s; "
                            "withdrawing its assignments", job_id,
                        )
                        del assignments[start:]
                        free = free_before
        self._push_shuffle_deltas(feed_pushes)
        return assignments, free + sidelined, pending

    def _admission_order(self, job_ids: List[str]) -> List[str]:
        """Fair-share walk order for ``fill_reservations``: interactive
        jobs before batch, then pools with the smallest weighted
        running-task share first (a freed slot goes to whoever is
        furthest under their share), submit order as the tie-break.
        Jobs without admission (or not yet cached) keep their relative
        submit order, interleaved as weight-1 batch work with zero
        share.  Returns the input list unchanged when no cached job is
        admission-managed, so the default-off path stays byte-identical."""
        if self.admission is None or len(job_ids) < 2:
            return job_ids
        rows = []
        managed = False
        for i, jid in enumerate(job_ids):
            with self._cache_lock:
                entry = self._cache.get(jid)
            if entry is None:
                rows.append((jid, i, None, 0))
                continue
            # one read of everything under the entry lock: the graph can
            # be evicted (entry.graph = None) by a concurrent failover
            # or persist failure between unlocked reads
            with entry.lock:
                graph = entry.graph
                if (
                    graph is None
                    or not getattr(graph, "admission_enabled", False)
                    or graph.status in (COMPLETED, FAILED)
                ):
                    rows.append((jid, i, None, 0))
                    continue
                managed = True
                rows.append(
                    (
                        jid,
                        i,
                        (graph.tenant_pool, graph.tenant_priority),
                        graph.running_tasks(),
                    )
                )
        if not managed:
            return job_ids
        pool_running: Dict[str, int] = {}
        for _jid, _i, info, running in rows:
            if info is not None:
                pool_running[info[0]] = pool_running.get(info[0], 0) + running
        weights = self.admission.pool_weights()

        def key(row):
            _jid, i, info, _running = row
            if info is None:
                return (1, 0.0, i)
            pool, priority = info
            share = pool_running.get(pool, 0) / max(
                weights.get(pool, 1.0), 1e-3
            )
            return (0 if priority == "interactive" else 1, share, i)

        rows.sort(key=key)
        return [row[0] for row in rows]

    def prepare_task_definition(self, task: Task) -> pb.TaskDefinition:
        td = pb.TaskDefinition()
        td.task_id.CopyFrom(task.partition.to_proto())
        td.plan = BallistaCodec.encode_physical(task.plan)
        if task.output_partitioning is not None:
            td.output_partitioning.CopyFrom(
                partitioning_to_proto(task.output_partitioning)
            )
            td.has_output_partitioning = True
        td.session_id = task.session_id
        td.curator_scheduler_id = self.scheduler_id
        td.attempt = task.attempt
        td.speculative = task.speculative
        td.timeout_seconds = task.timeout_seconds
        # trace propagation: executor task spans parent under the job's
        # root span (root span id == trace id by convention).  A traced
        # task also carries the obs prop so executors ratchet tracing on
        # even when it was forced scheduler-side (--obs-enabled) rather
        # than set on the session.
        td.trace_id = task.trace_id
        td.parent_span_id = task.trace_id
        if task.trace_id and "ballista.obs.enabled" not in td.props:
            td.props["ballista.obs.enabled"] = "true"
        # ship the session settings so the executor's TaskContext + TPU
        # acceleration pass see the client's config (reference: grpc.rs
        # poll_work/launch builds TaskDefinition.props from session props)
        for k, v in self._session_settings(task.session_id).items():
            td.props[k] = v
        # pipelined execution enabled by a SCHEDULER override (not the
        # session): stamp the knob so the executor's worker-eligibility
        # gate still recognizes tailing plans; sessions that set it ship
        # it above, and default-off tasks carry nothing extra
        from ..config import SHUFFLE_PIPELINED

        if SHUFFLE_PIPELINED not in td.props and self.config_overrides.get(
            SHUFFLE_PIPELINED
        ):
            td.props[SHUFFLE_PIPELINED] = self.config_overrides[
                SHUFFLE_PIPELINED
            ]
        # learned policy overrides (plan-cache layer 2) merged beneath
        # the session at submit: sessions don't ship them, so stamp any
        # key the session (or obs forcing above) didn't already set
        for k, v in self._policy_props.get(task.partition.job_id, {}).items():
            if k not in td.props:
                td.props[k] = v
        return td

    def _session_settings(self, session_id: str) -> Dict[str, str]:
        raw = self.backend.get(Keyspace.Sessions, session_id)
        if raw is None:
            return {}
        msg = pb.SessionSettings.FromString(raw)
        return {kv.key: kv.value for kv in msg.configs}

    def launch_tasks(
        self, executor: ExecutorMetadata, tasks: List[Task]
    ) -> None:
        from ..testing.faults import fault_point

        defs = [self.prepare_task_definition(t) for t in tasks]
        if tasks and tasks[0].trace_id:
            trace.record_raw(
                "scheduler.launch",
                tasks[0].trace_id,
                trace.new_id(),
                tasks[0].trace_id,
                time.time_ns(),
                0,
                job=tasks[0].partition.job_id,
                executor=executor.id,
                tasks=len(tasks),
                stages=sorted({t.partition.stage_id for t in tasks}),
            )
        try:
            fault_point("scheduler.launch_task", executor_id=executor.id)
            self.launcher.launch(executor, defs, self.scheduler_id)
        except Exception as e:
            # hand the tasks back — excluded from this executor so the
            # re-dispatch goes elsewhere — and feed the quarantine window;
            # repeated launch failures queue the executor for expulsion
            # (drained into ExecutorLost by the query-stage scheduler).
            # A failed SPECULATIVE launch only forgets the duplicate; the
            # primary attempt keeps the partition.
            for t in tasks:
                self.reset_task(
                    t.partition,
                    exclude_executor=executor.id,
                    speculative=t.speculative,
                )
            self.executor_manager.record_launch_failure(executor.id)
            raise SchedulerError(
                f"launching {len(tasks)} task(s) on {executor.id} failed: {e}"
            ) from e
        self.executor_manager.record_launch_success(executor.id)

    def reset_task(
        self, partition: PartitionId, exclude_executor: str = "",
        speculative: bool = False,
    ) -> None:
        entry = self._entry(partition.job_id)
        with entry.lock:
            graph = self._load(partition.job_id, entry)
            if graph is not None:
                graph.reset_task_status(
                    partition, exclude_executor, speculative=speculative
                )
                self._persist(graph)

    # --------------------------------------------------------- transitions
    def _emit_job_span(self, graph, status: str) -> None:
        """The trace's root span, timed submit → terminal state (its id IS
        the trace id; every shipped child parented under it)."""
        if graph is None or not getattr(graph, "trace_id", ""):
            return
        trace.record_raw(
            "job",
            graph.trace_id,
            graph.trace_id,
            "",
            graph.submitted_unix_ns,
            time.monotonic_ns() - graph.submitted_mono_ns,
            job=graph.job_id,
            status=status,
            task_retries=graph.task_retries,
            stages=len(graph.stages),
        )

    def _admission_finished(self, job_id: str) -> None:
        """Free the job's admission concurrency slot on any terminal
        transition (no-op for jobs admission never tracked).  The
        event-loop handler that drove the transition runs the release
        scan right after, so freed capacity admits queued jobs."""
        if self.admission is not None:
            self.admission.job_finished(job_id)

    def complete_job(self, job_id: str) -> None:
        self._admission_finished(job_id)
        entry = self._entry(job_id)
        with entry.lock:
            graph = self._load(job_id, entry)
            if graph is not None:
                self._persist(graph)
            self._emit_job_span(graph, "completed")
            self._jobs_completed.inc()
            self._observe_completion(graph)
            self.backend.mv(Keyspace.ActiveJobs, Keyspace.CompletedJobs, job_id)
            with self._cache_lock:
                self._cache.pop(job_id, None)

    def _observe_completion(self, graph: Optional[ExecutionGraph]) -> None:
        """Journal the completion and feed the session's latency SLO
        (``ballista.obs.slo.job_latency_seconds``; 0/absent = untracked).
        The journal line is the job's post-mortem anchor — it survives
        the cache eviction this very call performs."""
        if graph is None:
            return
        latency_s = (time.monotonic_ns() - graph.submitted_mono_ns) / 1e9
        breached = None
        if self.slo is not None:
            from ..config import OBS_SLO_JOB_LATENCY_S

            try:
                target = float(
                    self._session_settings(graph.session_id).get(
                        OBS_SLO_JOB_LATENCY_S, 0.0
                    )
                )
            except (TypeError, ValueError):
                target = 0.0
            if target > 0:
                breached = self.slo.observe(latency_s, target)
        fields = {
            "latency_s": round(latency_s, 4),
            "task_retries": graph.task_retries,
        }
        if breached is not None:
            fields["slo_breached"] = breached
        self.events.emit(
            "job_completed", job=graph.job_id, trace=graph.trace_id, **fields
        )
        self._policy_record(graph, latency_s)

    def _policy_record(
        self, graph: ExecutionGraph, latency_s: float
    ) -> None:
        """Feed the completed job's measured latency + doctor findings
        into the per-plan policy store and journal any rollbacks it
        triggers.  Best-effort: diagnosis runs the same report bundle
        the REST profile serves, and any failure inside it degrades to
        recording the latency with no findings."""
        if self.policy_store is None:
            return
        fp = getattr(graph, "policy_fp", "") or ""
        if not fp:
            return
        arm = getattr(graph, "policy_arm", "baseline") or "baseline"
        self._policy_props.pop(graph.job_id, None)
        findings: List[str] = []
        if arm != "applied":
            # findings steer what gets LEARNED; applied runs only need
            # the latency sample, so skip the diagnosis cost for them
            try:
                from ..obs.doctor import job_report
                from ..obs.recorder import spans_for_job

                detail = self._detail_of(graph)
                ev = (
                    self.events.for_job(graph.job_id)
                    if getattr(self.events, "enabled", False)
                    else []
                )
                report = job_report(detail, spans_for_job(graph.job_id), ev)
                findings = [
                    f.get("code")
                    for f in report.get("doctor") or []
                    if f.get("code")
                ]
            except Exception:
                findings = []
        try:
            rollbacks = self.policy_store.record_job(
                fp, arm, latency_s, findings
            )
        except Exception:
            return
        for rb in rollbacks:
            self.events.emit(
                "policy_rollback",
                job=graph.job_id,
                trace=graph.trace_id,
                **rb,
            )

    def fail_job(self, job_id: str, error: str) -> None:
        self._admission_finished(job_id)
        if self.admission is not None:
            # a job failed out of the queue/admit window reached its
            # terminal state: its queue-WAL entry must not replay it
            self.admission.wal_discard(job_id)
        self._policy_props.pop(job_id, None)
        entry = self._entry(job_id)
        with entry.lock:
            graph = self._load(job_id, entry)
            # two fatal tasks of one job each post JobRunningFailed; only
            # the FIRST fail_job (which moves the job into FailedJobs)
            # emits the root span + counter.  The graph's own status is
            # no signal — it's already FAILED before the event arrives.
            already_failed = (
                self.backend.get(Keyspace.FailedJobs, job_id) is not None
            )
            if not already_failed:
                self._emit_job_span(graph, "failed")
                self._jobs_failed.inc()
                self.events.emit(
                    "job_failed",
                    job=job_id,
                    trace=getattr(graph, "trace_id", "") or "",
                    error=(error or "")[:500],
                )
            tombstone = graph is None
            if graph is not None:
                if graph.status != FAILED:
                    graph.fail_job(error)
                try:
                    self._persist(graph)
                except Exception:
                    # the plan itself may be unserializable (that can be WHY
                    # the job failed); fall back to a status-only tombstone
                    tombstone = True
            if tombstone:
                msg = pb.ExecutionGraphProto(job_id=job_id)
                msg.status.failed.error = error
                self.backend.put(
                    Keyspace.ActiveJobs, job_id, msg.SerializeToString()
                )
            self.backend.mv(Keyspace.ActiveJobs, Keyspace.FailedJobs, job_id)
            with self._cache_lock:
                self._cache.pop(job_id, None)

    def update_job(self, job_id: str) -> None:
        entry = self._entry(job_id)
        with entry.lock:
            graph = self._load(job_id, entry)
            if graph is not None:
                self._persist(graph)

    def cancel_job(self, job_id: str) -> List[Tuple[ExecutorMetadata, List[PartitionId]]]:
        """Fail the job; return the running tasks per executor so the caller
        can issue CancelTasks RPCs (reference: task_manager.rs:225-303).

        A job still sitting in the ADMISSION queue has no graph and no
        running tasks: cancelling it dequeues it (it will never plan)
        and journals ``job_cancelled``.  A cancel racing the admit
        window (released from the queue but no graph cached yet) leaves
        a bounded cancel intent the submit path consumes — the job fails
        instead of running either way."""
        if self.admission is not None:
            qj = self.admission.cancel(job_id)
            if qj is not None:
                self.events.emit(
                    "job_cancelled",
                    job=job_id,
                    pool=qj.pool,
                    queued=True,
                    queue_wait_s=round(
                        time.monotonic() - qj.enqueued_mono, 4
                    ),
                )
                self.fail_job(job_id, "job cancelled by user")
                return []
        entry = self._entry(job_id)
        running: Dict[str, List[PartitionId]] = {}
        with entry.lock:
            graph = self._load(job_id, entry)
            if graph is None:
                if self.admission is not None and not any(
                    self.backend.get(ks, job_id) is not None
                    for ks in (
                        Keyspace.ActiveJobs,
                        Keyspace.CompletedJobs,
                        Keyspace.FailedJobs,
                    )
                ):
                    # nothing queued, nothing persisted: the job is in
                    # the release→plan window (or the id is bogus) —
                    # the intent makes the submit path fail it
                    self.admission.mark_cancel_intent(job_id)
                return []
            from .execution_stage import RunningStage

            for sid, stage in graph.stages.items():
                if isinstance(stage, RunningStage):
                    for t in stage.task_statuses:
                        if t is not None and t.state == "running":
                            running.setdefault(t.executor_id, []).append(
                                t.partition_id
                            )
                    # duplicate attempts racing stragglers abort too
                    for si in stage.speculative_statuses.values():
                        running.setdefault(si.executor_id, []).append(
                            si.partition_id
                        )
        self.events.emit("job_cancelled", job=job_id, queued=False)
        self.fail_job(job_id, "job cancelled by user")
        out = []
        for eid, pids in running.items():
            try:
                meta = self.executor_manager.get_executor_metadata(eid)
            except SchedulerError:
                continue
            out.append((meta, pids))
        return out

    def executor_lost(self, executor_id: str) -> List[str]:
        """Roll back every cached graph; returns affected job ids
        (reference: task_manager.rs:384-412)."""
        with self._cache_lock:
            job_ids = list(self._cache.keys())
        affected = []
        for job_id in job_ids:
            entry = self._entry(job_id)
            with entry.lock:
                graph = self._load(job_id, entry)
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                if graph.reset_stages(executor_id):
                    affected.append(job_id)
                    self._persist(graph)
        return affected

    # -------------------------------------------------------------- misc
    def active_job_ids(self) -> List[str]:
        with self._cache_lock:
            return list(self._cache.keys())

    def locality_pending(self) -> Tuple[int, Dict[str, int]]:
        """(deferred-pending tasks, per-host demand) across cached jobs
        with LOCALITY PLACEMENT ON — the periodic re-offer input keeping
        locality-deferred tasks live in push mode (a deferred task's
        slot was cancelled; somebody must mint new reservations once the
        wait expires).  Counts ONLY stages whose last pop actually
        turned a slot away (``stage.locality_deferred``): pending tasks
        the event-driven flow already covers must not be double-booked
        every tick.  Jobs without the knob contribute nothing, so
        knob-off scheduling is untouched."""
        from .execution_stage import RunningStage

        pending = 0
        hosts: Dict[str, int] = {}
        with self._cache_lock:
            entries = list(self._cache.values())
        for entry in entries:
            with entry.lock:
                graph = entry.graph
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                if not getattr(graph, "locality_enabled", False):
                    continue
                deferred = 0
                for stage in graph.stages.values():
                    if (
                        isinstance(stage, RunningStage)
                        and stage.locality_deferred
                    ):
                        deferred += sum(
                            1 for t in stage.task_statuses if t is None
                        )
                if not deferred:
                    continue
                pending += deferred
                for h, n in graph.preferred_hosts().items():
                    hosts[h] = hosts.get(h, 0) + n
        return pending, hosts

    def task_counts(self) -> Tuple[int, int]:
        """(pending, running) task totals across cached active jobs —
        the queue-depth and slot-saturation inputs for the cluster
        telemetry rings and the autoscaling gauges.  Reads only cached
        graphs (scrape-time: must never hit the backend)."""
        pending = running = 0
        with self._cache_lock:
            entries = list(self._cache.values())
        for entry in entries:
            with entry.lock:
                graph = entry.graph
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                pending += graph.available_tasks()
                running += graph.running_tasks()
        return pending, running

    def unreplicated_shuffle_bytes(self) -> Dict[str, int]:
        """Per-executor bytes of completed shuffle output that has NO
        external-store replica and is still referenced by an active job —
        exactly what a graceful drain must upload before the executor can
        retire.  The autoscaler's scale-in victim selection minimizes
        this (cheapest executor to move).  Cached graphs only
        (scrape-time: must never hit the backend)."""
        out: Dict[str, int] = {}
        with self._cache_lock:
            entries = list(self._cache.values())
        for entry in entries:
            with entry.lock:
                graph = entry.graph
                if graph is None or graph.status in (COMPLETED, FAILED):
                    continue
                for stage in graph.stages.values():
                    for info in getattr(stage, "task_statuses", None) or []:
                        if info is None or info.state != "completed":
                            continue
                        if not info.executor_id:
                            continue
                        pending = sum(
                            p.num_bytes
                            for p in info.partitions
                            if not p.replica_path and p.num_bytes > 0
                        )
                        if pending:
                            out[info.executor_id] = (
                                out.get(info.executor_id, 0) + pending
                            )
        return out

    def list_jobs(self) -> List[dict]:
        """Job table for the REST API: active, completed and failed jobs
        with their states (reference exposes this via /api/state +
        the scheduler UI's job dashboard)."""
        out: List[dict] = []
        seen: set = set()
        if self.admission is not None:
            for row in self.admission.queued_jobs_brief():
                out.append({**row, "state": "queued"})
                seen.add(row["job_id"])
        for job_id in self.active_job_ids():
            st = self.get_job_status(job_id)
            if st is not None:
                retries = self._with_graph(job_id, lambda g: g.task_retries)
                out.append(
                    {
                        "job_id": job_id,
                        "state": st["state"],
                        "task_retries": retries or 0,
                    }
                )
                seen.add(job_id)
        for ks, state in (
            (Keyspace.CompletedJobs, "completed"),
            (Keyspace.FailedJobs, "failed"),
        ):
            for key in self.backend.scan_keys(ks):
                if key not in seen:
                    out.append({"job_id": key, "state": state})
                    seen.add(key)
        return out

    @staticmethod
    def generate_job_id() -> str:
        """7-char alphanumeric (reference: task_manager.rs:544-551)."""
        return "".join(
            random.choices(string.ascii_lowercase + string.digits, k=7)
        )
