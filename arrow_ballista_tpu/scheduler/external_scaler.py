"""KEDA external-scaler gRPC service.

Counterpart of the reference's ``scheduler/src/scheduler_server/external_scaler.rs:29-65``:
kubernetes' KEDA operator polls this service to decide how many executor
replicas to run.  Like the reference stub, ``IsActive`` always reports
active and ``GetMetrics`` reports the ``inflight_tasks`` metric pinned high
enough to saturate the HPA (`:47-58` hardcodes 1,000,000); the metric spec
target is 10 per replica.  The one improvement over the stub: when the
scheduler has no active jobs, inflight is reported as 0 so idle clusters
can scale to the minimum.

With the built-in autoscaler enabled (``ballista.autoscaler.enabled``,
ISSUE 17), ``GetMetrics`` instead reports the policy's desired-replica
demand — ``desired × target-per-replica``, so the HPA's division lands
exactly on ``desired`` — and KEDA becomes a mirror of the same decision
the built-in loop is executing rather than a second, competing
controller.  The saturate-the-HPA stub is preserved verbatim when the
autoscaler is off (the KEDA-only deployment mode).
"""

from __future__ import annotations

import grpc

from ..proto import keda_pb

INFLIGHT_TASKS_METRIC_NAME = "inflight_tasks"
MAX_INFLIGHT = 1_000_000
TARGET_PER_REPLICA = 10

_EXTERNAL_SCALER_METHODS = {
    "IsActive": (keda_pb.ScaledObjectRef, keda_pb.IsActiveResponse),
    "GetMetricSpec": (keda_pb.ScaledObjectRef, keda_pb.GetMetricSpecResponse),
    "GetMetrics": (keda_pb.GetMetricsRequest, keda_pb.GetMetricsResponse),
}


class ExternalScalerService:
    def __init__(self, scheduler):
        self.scheduler = scheduler

    def IsActive(self, request, context) -> keda_pb.IsActiveResponse:
        return keda_pb.IsActiveResponse(result=True)

    def GetMetricSpec(self, request, context) -> keda_pb.GetMetricSpecResponse:
        return keda_pb.GetMetricSpecResponse(
            metricSpecs=[
                keda_pb.MetricSpec(
                    metricName=INFLIGHT_TASKS_METRIC_NAME,
                    targetSize=TARGET_PER_REPLICA,
                )
            ]
        )

    def GetMetrics(self, request, context) -> keda_pb.GetMetricsResponse:
        autoscaler = getattr(self.scheduler, "autoscaler", None)
        if autoscaler is not None:
            # built-in loop on: report ITS desired-replica demand so the
            # HPA (value / target) resolves to exactly `desired` — KEDA
            # mirrors the policy instead of fighting it with the
            # saturate-the-HPA stub below
            value = autoscaler.desired * TARGET_PER_REPLICA
        else:
            # jobs held in the admission queue are demand the cluster
            # could not absorb — exactly what an autoscaler must see as
            # inflight (ROADMAP item 2 pairs with the admission front
            # door here)
            active = self.scheduler.state.task_manager.active_job_ids()
            queued = self.scheduler.state.admission.queued_count()
            value = MAX_INFLIGHT if (active or queued) else 0
        return keda_pb.GetMetricsResponse(
            metricValues=[
                keda_pb.MetricValue(
                    metricName=INFLIGHT_TASKS_METRIC_NAME, metricValue=value
                )
            ]
        )


def add_external_scaler_servicer(server: grpc.Server, servicer) -> None:
    handlers = {}
    for name, (req_t, resp_t) in _EXTERNAL_SCALER_METHODS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (
            grpc.method_handlers_generic_handler(
                "externalscaler.ExternalScaler", handlers
            ),
        )
    )


class ExternalScalerStub:
    """Client stub (for tests / local ops tooling)."""

    def __init__(self, channel: grpc.Channel):
        for name, (req_t, resp_t) in _EXTERNAL_SCALER_METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/externalscaler.ExternalScaler/{name}",
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )
