"""Cluster membership + task-slot accounting.

Counterpart of the reference's ``scheduler/src/state/executor_manager.rs``:

* ``ExecutorReservation`` — a slot held for a specific upcoming task,
  invisible to other jobs, optionally job-affine (`:41-75`);
* ``reserve_slots`` / ``cancel_reservations`` — atomic under the global
  Slots lock with transactional writes (`:121-217`);
* registration / removal, persisted heartbeats with an in-memory map kept
  fresh by a backend watch (`:419-560`);
* liveness = heartbeat within ``liveness_window_s`` (60s in the reference,
  `:510-516`); expiry handled by the scheduler reaper.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Set

from ..errors import SchedulerError
from ..obs.registry import MetricsRegistry
from .kvstore import LeaseFenced
from ..proto import pb
from ..serde.scheduler_types import ExecutorMetadata
from .backend import Keyspace, StateBackend, WatchEvent

log = logging.getLogger(__name__)

DEFAULT_LIVENESS_WINDOW_S = 60.0
DEFAULT_EXECUTOR_TIMEOUT_S = 180.0
# Quarantine defaults (ballista.executor.quarantine_* knobs)
DEFAULT_QUARANTINE_THRESHOLD = 5
DEFAULT_QUARANTINE_WINDOW_S = 60.0
DEFAULT_QUARANTINE_BACKOFF_S = 30.0
# consecutive LaunchTask failures before an executor is declared lost
DEFAULT_LAUNCH_FAILURE_THRESHOLD = 3


@dataclass
class ExecutorReservation:
    executor_id: str
    job_id: Optional[str] = None

    def assign(self, job_id: str) -> "ExecutorReservation":
        return ExecutorReservation(self.executor_id, job_id)


@dataclass
class ExecutorHeartbeat:
    executor_id: str
    timestamp: float
    status: str = "active"  # active | dead

    def to_bytes(self) -> bytes:
        # stored in milliseconds: whole-second truncation would break
        # sub-second liveness windows (tests shrink the 60s default)
        msg = pb.ExecutorHeartbeat(
            executor_id=self.executor_id, timestamp=int(self.timestamp * 1000)
        )
        if self.status == "active":
            msg.status.active = ""
        else:
            msg.status.dead = ""
        return msg.SerializeToString()

    @staticmethod
    def from_bytes(b: bytes) -> "ExecutorHeartbeat":
        msg = pb.ExecutorHeartbeat.FromString(b)
        status = msg.status.WhichOneof("status") or "active"
        return ExecutorHeartbeat(msg.executor_id, msg.timestamp / 1000.0, status)


class ExecutorManager:
    def __init__(
        self,
        backend: StateBackend,
        liveness_window_s: float = DEFAULT_LIVENESS_WINDOW_S,
        quarantine_threshold: int = DEFAULT_QUARANTINE_THRESHOLD,
        quarantine_window_s: float = DEFAULT_QUARANTINE_WINDOW_S,
        quarantine_backoff_s: float = DEFAULT_QUARANTINE_BACKOFF_S,
        launch_failure_threshold: int = DEFAULT_LAUNCH_FAILURE_THRESHOLD,
        registry: Optional[MetricsRegistry] = None,
        events=None,
    ):
        from ..obs.events import EventJournal

        # structured event journal (obs/events.py): membership churn —
        # register/quarantine/drain/removal — is exactly what a
        # post-mortem needs when a job's slowdown traces to the cluster
        self.events = events if events is not None else EventJournal()
        self.backend = backend
        self.liveness_window_s = liveness_window_s
        self._heartbeats: Dict[str, ExecutorHeartbeat] = {}
        # monotonic receipt anchor per executor: ALL elapsed-time checks
        # (liveness, staleness, quarantine windows) run on time.monotonic
        # so a wall-clock jump can neither spuriously expire an executor
        # nor un-quarantine one.  The wall timestamp stays on the
        # persisted heartbeat for display / cross-process age estimates.
        self._hb_mono: Dict[str, float] = {}
        self._dead: Set[str] = set()
        self._hb_lock = threading.Lock()
        # ---- quarantine: sliding-window failure accounting per executor
        self.quarantine_threshold = quarantine_threshold
        self.quarantine_window_s = quarantine_window_s
        self.quarantine_backoff_s = quarantine_backoff_s
        self.launch_failure_threshold = launch_failure_threshold
        self._q_lock = threading.Lock()
        self._failure_times: Dict[str, deque] = {}
        self._quarantined_until: Dict[str, float] = {}
        self._launch_failures: Dict[str, int] = {}  # consecutive
        self._pending_expulsions: Set[str] = set()
        # ---- graceful decommission: executor -> monotonic drain deadline.
        # Draining executors take no NEW work (reserve_slots +
        # fill_reservations exclude them) but keep running/reporting what
        # they have; past the deadline (+grace) the reaper declares them
        # lost so a wedged drain can't hold its tasks hostage.
        self._draining: Dict[str, float] = {}
        self.registry = registry or MetricsRegistry()
        self._quarantines = self.registry.counter(
            "quarantines_total",
            "executors newly quarantined over scheduler lifetime",
        )
        self._drained = self.registry.counter(
            "executors_drained_total",
            "executors gracefully decommissioned (drain cycles concluded)",
        )
        self._task_failures_recorded = self.registry.counter(
            "executor_task_failures_total",
            "task/launch failures fed into quarantine windows",
        )
        self._unsubscribe = backend.watch(Keyspace.Heartbeats, "", self._on_hb_event)

    @property
    def quarantines_total(self) -> int:
        """Back-compat read surface for the old ad-hoc counter."""
        return int(self._quarantines.value)

    def close(self) -> None:
        self._unsubscribe()

    def _fenced_txn(self, lk, ops) -> None:
        """Apply a Slots transaction under its lease's fencing token.

        The reference's most carefully locked state is the slot accounting
        (``executor_manager.rs:121-217``).  With a remote lease the write
        carries the grant's token: if this holder's lease lapsed (stalled
        refresher past TTL) and another scheduler re-acquired, the store
        rejects the stale write (LeaseFenced) instead of letting it
        corrupt the slot counts.  Local backends ignore the fence —
        single-process mutual exclusion is already total."""
        self.backend.put_txn(ops, fence=lk)

    # ------------------------------------------------------- registration
    def register_executor(
        self,
        metadata: ExecutorMetadata,
        reserve: bool = False,
    ) -> List[ExecutorReservation]:
        """Persist metadata + heartbeat + slots; in push mode immediately
        reserve every slot for the offer cycle
        (reference: executor_manager.rs:308-417)."""
        slots = metadata.specification.task_slots
        lk = self.backend.lock(Keyspace.Slots, "global")
        with lk:
            self._fenced_txn(
                lk,
                [
                    (
                        Keyspace.Executors,
                        metadata.id,
                        metadata.to_proto().SerializeToString(),
                    ),
                    (
                        Keyspace.Slots,
                        metadata.id,
                        _slots_bytes(0 if reserve else slots),
                    ),
                ],
            )
        self.save_heartbeat(
            ExecutorHeartbeat(metadata.id, time.time(), "active")
        )
        with self._hb_lock:
            self._dead.discard(metadata.id)
        with self._q_lock:
            # a (re-)registering executor starts with a clean record
            self._failure_times.pop(metadata.id, None)
            self._quarantined_until.pop(metadata.id, None)
            self._launch_failures.pop(metadata.id, None)
            self._pending_expulsions.discard(metadata.id)
            self._draining.pop(metadata.id, None)
        self.events.emit(
            "executor_registered",
            executor=metadata.id,
            host=metadata.host,
            slots=slots,
        )
        if reserve:
            return [ExecutorReservation(metadata.id) for _ in range(slots)]
        return []

    def remove_executor(self, executor_id: str) -> None:
        """Mark dead and zero its slots."""
        lk = self.backend.lock(Keyspace.Slots, "global")
        with lk:
            self._fenced_txn(
                lk, [(Keyspace.Slots, executor_id, _slots_bytes(0))]
            )
        self.save_heartbeat(ExecutorHeartbeat(executor_id, time.time(), "dead"))
        with self._hb_lock:
            self._dead.add(executor_id)
        with self._q_lock:
            self._failure_times.pop(executor_id, None)
            self._quarantined_until.pop(executor_id, None)
            self._launch_failures.pop(executor_id, None)
            self._pending_expulsions.discard(executor_id)
            was_draining = executor_id in self._draining
            self._draining.pop(executor_id, None)
        if was_draining:
            # a drain cycle concluded (graceful stop OR deadline expiry):
            # the executor is out of the cluster with its locations
            # re-pointed by the accompanying rollback
            self._drained.inc()
        self.events.emit(
            "executor_removed", executor=executor_id, drained=was_draining
        )

    def get_executor_metadata(self, executor_id: str) -> ExecutorMetadata:
        raw = self.backend.get(Keyspace.Executors, executor_id)
        if raw is None:
            raise SchedulerError(f"unknown executor {executor_id!r}")
        return ExecutorMetadata.from_proto(pb.ExecutorMetadata.FromString(raw))

    def executors(self) -> List[ExecutorMetadata]:
        return [
            ExecutorMetadata.from_proto(pb.ExecutorMetadata.FromString(v))
            for _, v in self.backend.scan(Keyspace.Executors)
        ]

    def is_dead_executor(self, executor_id: str) -> bool:
        with self._hb_lock:
            return executor_id in self._dead

    # --------------------------------------------------------- heartbeats
    def save_heartbeat(self, hb: ExecutorHeartbeat) -> None:
        self.backend.put(Keyspace.Heartbeats, hb.executor_id, hb.to_bytes())

    def _on_hb_event(self, event: WatchEvent) -> None:
        if event.kind == WatchEvent.PUT and event.value is not None:
            hb = ExecutorHeartbeat.from_bytes(event.value)
            # anchor the monotonic receipt by the beat's wall age ONCE
            # (a replayed stale heartbeat — e.g. HA standby catching up —
            # must not look fresh); after this single wall read, liveness
            # math is purely monotonic and immune to clock jumps
            mono = time.monotonic() - max(0.0, time.time() - hb.timestamp)
            with self._hb_lock:
                self._heartbeats[hb.executor_id] = hb
                self._hb_mono[hb.executor_id] = mono
                if hb.status == "dead":
                    self._dead.add(hb.executor_id)

    def heartbeats(self) -> List["ExecutorHeartbeat"]:
        """Snapshot of the in-memory heartbeat map (observability/tests)."""
        with self._hb_lock:
            return list(self._heartbeats.values())

    def get_alive_executors(self, now: Optional[float] = None) -> Set[str]:
        """Executors whose last beat is inside the liveness window.
        ``now`` is in the time.monotonic domain (tests inject values)."""
        now = time.monotonic() if now is None else now
        cutoff = now - self.liveness_window_s
        with self._hb_lock:
            return {
                eid
                for eid, hb in self._heartbeats.items()
                if hb.status == "active"
                and self._hb_mono.get(eid, float("-inf")) >= cutoff
            }

    def get_expired_executors(
        self, timeout_s: float = DEFAULT_EXECUTOR_TIMEOUT_S
    ) -> List[ExecutorHeartbeat]:
        cutoff = time.monotonic() - timeout_s
        with self._hb_lock:
            return [
                hb
                for eid, hb in self._heartbeats.items()
                if hb.status == "active"
                and self._hb_mono.get(eid, float("-inf")) < cutoff
            ]

    def last_seen(self, executor_id: str) -> Optional[float]:
        with self._hb_lock:
            hb = self._heartbeats.get(executor_id)
        return hb.timestamp if hb else None

    # ---------------------------------------------------------- quarantine
    def record_task_failure(self, executor_id: str, now: Optional[float] = None) -> bool:
        """Count one failure into the executor's sliding window.  Returns
        True when this failure NEWLY quarantines the executor (the caller
        then resets its in-flight tasks)."""
        if self.quarantine_threshold <= 0 or not executor_id:
            return False
        # monotonic domain: a wall-clock jump must not age failures out of
        # the window (spuriously un-quarantining) or pile them in
        now = time.monotonic() if now is None else now
        self._task_failures_recorded.inc()
        with self._q_lock:
            dq = self._failure_times.setdefault(executor_id, deque())
            dq.append(now)
            cutoff = now - self.quarantine_window_s
            while dq and dq[0] < cutoff:
                dq.popleft()
            already = self._quarantined_until.get(executor_id, 0.0) > now
            if len(dq) < self.quarantine_threshold or already:
                return False
            quarantined = {
                eid
                for eid, until in self._quarantined_until.items()
                if until > now
            }
        # sidelining the ONLY live executor turns a sick cluster into a
        # dead one — keep it serving (its failures stay bounded by the
        # per-task attempt budget); checked outside _q_lock since
        # get_alive_executors takes its own lock
        others = self.get_alive_executors(now) - quarantined - {executor_id}
        if not others:
            log.warning(
                "executor %s crossed the quarantine threshold but is the "
                "only live executor; not quarantining",
                executor_id,
            )
            return False
        with self._q_lock:
            if self._quarantined_until.get(executor_id, 0.0) > now:
                return False  # raced: someone else quarantined it
            dq = self._failure_times.setdefault(executor_id, deque())
            self._quarantined_until[executor_id] = now + self.quarantine_backoff_s
            self._quarantines.inc()
            dq.clear()  # the window restarts after the backoff
        log.warning(
            "executor %s quarantined for %.0fs (%d failures in %.0fs window)",
            executor_id,
            self.quarantine_backoff_s,
            self.quarantine_threshold,
            self.quarantine_window_s,
        )
        self.events.emit(
            "executor_quarantined",
            executor=executor_id,
            backoff_s=self.quarantine_backoff_s,
            failures=self.quarantine_threshold,
        )
        return True

    def record_launch_failure(self, executor_id: str) -> bool:
        """Launch failures feed the quarantine window AND an escalation
        counter: after ``launch_failure_threshold`` CONSECUTIVE launch
        failures the executor is queued for expulsion (ExecutorLost) —
        the scheduler cannot even deliver tasks to it, so silently
        re-dispatching would black-hole the job.  Returns True when the
        expulsion threshold was just crossed."""
        self.record_task_failure(executor_id)
        with self._q_lock:
            n = self._launch_failures.get(executor_id, 0) + 1
            self._launch_failures[executor_id] = n
            if n < self.launch_failure_threshold:
                return False
            if executor_id in self._pending_expulsions:
                return False
            self._pending_expulsions.add(executor_id)
        log.warning(
            "executor %s failed %d consecutive launches; queueing expulsion",
            executor_id,
            n,
        )
        return True

    def record_launch_success(self, executor_id: str) -> None:
        with self._q_lock:
            self._launch_failures.pop(executor_id, None)

    def take_pending_expulsions(self) -> List[str]:
        """Drain executors whose repeated launch failures crossed the
        threshold; the caller posts ExecutorLost for each."""
        with self._q_lock:
            out = sorted(self._pending_expulsions)
            self._pending_expulsions.clear()
        return out

    # ------------------------------------------------------------ draining
    def mark_draining(self, executor_id: str, timeout_s: float) -> None:
        """Graceful decommission step 1: exclude the executor from every
        future reservation while it finishes/hands off its work."""
        with self._q_lock:
            self._draining[executor_id] = time.monotonic() + max(0.0, timeout_s)
        self.events.emit(
            "executor_drain_started", executor=executor_id, timeout_s=timeout_s
        )

    def is_draining(self, executor_id: str) -> bool:
        with self._q_lock:
            return executor_id in self._draining

    def draining_executors(self) -> List[str]:
        with self._q_lock:
            return sorted(self._draining)

    # the deadline only bounds TASK time; a draining executor then still
    # legitimately spends cancel grace + status flush + un-replicated
    # partition uploads + replicator flush (up to ~45s of bounded waits,
    # plus upload I/O) before ExecutorStopped — the watchdog grace must
    # cover that or a busy drain gets declared lost mid-upload and
    # triggers the recompute storm the drain exists to avoid
    DRAIN_GRACE_S = 60.0
    # upload I/O is unbounded (GBs to a slow shared store): a drain past
    # the grace whose executor STILL HEARTBEATS is deferred up to this
    # hard cap past its deadline — only a drain that is both overdue and
    # silent (or wedged beyond the cap) is declared lost
    DRAIN_HARD_CAP_S = 900.0

    def overdue_drains(
        self,
        grace_s: Optional[float] = None,
        alive: Optional[Set[str]] = None,
        hard_cap_s: Optional[float] = None,
    ) -> List[str]:
        """Draining executors past deadline + grace that never reported
        stopped: the reaper posts ExecutorLost for each so a wedged drain
        cannot strand its tasks.  ``alive`` (heartbeat-fresh executor
        ids) defers a live, still-uploading drain until ``hard_cap_s``
        past its deadline.  Entries stay in ``_draining`` until
        ``remove_executor`` concludes the cycle (and counts it)."""
        grace_s = self.DRAIN_GRACE_S if grace_s is None else grace_s
        hard_cap_s = self.DRAIN_HARD_CAP_S if hard_cap_s is None else hard_cap_s
        hard_cap_s = max(hard_cap_s, grace_s)
        alive = alive or set()
        now = time.monotonic()
        with self._q_lock:
            return sorted(
                eid
                for eid, deadline in self._draining.items()
                if now > deadline + grace_s
                and (eid not in alive or now > deadline + hard_cap_s)
            )

    def is_quarantined(self, executor_id: str, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._q_lock:
            return self._quarantined_until.get(executor_id, 0.0) > now

    def quarantined_executors(self, now: Optional[float] = None) -> List[str]:
        now = time.monotonic() if now is None else now
        with self._q_lock:
            return sorted(
                eid
                for eid, until in self._quarantined_until.items()
                if until > now
            )

    # -------------------------------------------------------------- slots
    def _host_weights(
        self, preferred_hosts: Optional[Dict[str, int]]
    ) -> Optional[Dict[str, int]]:
        """executor id -> demand weight of its (normalized) host, for the
        reserve_slots locality ordering; None when no preference."""
        if not preferred_hosts:
            return None
        from ..shuffle.transport import normalize_host

        wanted = {normalize_host(h): w for h, w in preferred_hosts.items()}
        out: Dict[str, int] = {}
        for eid, raw in self.backend.scan(Keyspace.Executors):
            try:
                meta = pb.ExecutorMetadata.FromString(raw)
                out[eid] = wanted.get(normalize_host(meta.host), 0)
            except Exception:  # noqa: BLE001 - unparsable: no preference
                out[eid] = 0
        return out

    def reserve_slots(
        self,
        n: int,
        job_id: Optional[str] = None,
        preferred_hosts: Optional[Dict[str, int]] = None,
    ) -> List[ExecutorReservation]:
        """Atomically grab up to ``n`` slots across alive executors
        (reference: executor_manager.rs:121-167).

        ``preferred_hosts`` ({host: pending-task demand}, from
        locality-aware graphs) SOFT-orders the scan: slots on hosts that
        already hold the shuffle bytes are taken first, everything else
        fills the remainder — the reservation-side half of locality
        placement (pop_next_task's wait is the task-side half)."""
        if n <= 0:
            return []
        alive = self.get_alive_executors()
        # quarantined executors take no new work until their backoff
        # ends; draining executors take no new work EVER
        for eid in self.quarantined_executors():
            alive.discard(eid)
        for eid in self.draining_executors():
            alive.discard(eid)
        weights = self._host_weights(preferred_hosts)
        # on LeaseFenced nothing was applied: re-scan and retry once
        # under a fresh grant (the counts may have changed meanwhile)
        for attempt in (0, 1):
            reservations: List[ExecutorReservation] = []
            lk = self.backend.lock(Keyspace.Slots, "global")
            try:
                with lk:
                    txn = []
                    entries = list(self.backend.scan(Keyspace.Slots))
                    if weights is not None:
                        # stable: equal-weight executors keep scan order
                        entries.sort(
                            key=lambda kv: -weights.get(kv[0], 0)
                        )
                    for eid, raw in entries:
                        if eid not in alive:
                            continue
                        avail = _slots_from(raw)
                        take = min(avail, n - len(reservations))
                        if take <= 0:
                            continue
                        txn.append(
                            (Keyspace.Slots, eid, _slots_bytes(avail - take))
                        )
                        reservations.extend(
                            ExecutorReservation(eid, job_id)
                            for _ in range(take)
                        )
                        if len(reservations) >= n:
                            break
                    if txn:
                        self._fenced_txn(lk, txn)
                return reservations
            except LeaseFenced:
                if attempt:
                    raise
        return reservations

    def cancel_reservations(self, reservations: List[ExecutorReservation]) -> None:
        """Give slots back (reference: executor_manager.rs:169-217)."""
        if not reservations:
            return
        per: Dict[str, int] = {}
        for r in reservations:
            per[r.executor_id] = per.get(r.executor_id, 0) + 1
        # a fenced rejection must NOT leak the slots forever (the take
        # was already applied by an earlier reserve): the give-back is a
        # pure re-derive-and-add under whatever lease is current, so on
        # LeaseFenced retry once with a fresh grant
        for attempt in (0, 1):
            lk = self.backend.lock(Keyspace.Slots, "global")
            try:
                with lk:
                    txn = []
                    for eid, k in per.items():
                        raw = self.backend.get(Keyspace.Slots, eid)
                        avail = _slots_from(raw) if raw is not None else 0
                        txn.append(
                            (Keyspace.Slots, eid, _slots_bytes(avail + k))
                        )
                    self._fenced_txn(lk, txn)
                return
            except LeaseFenced:
                if attempt:
                    raise

    def available_slots(self) -> int:
        alive = self.get_alive_executors()
        return sum(
            _slots_from(raw)
            for eid, raw in self.backend.scan(Keyspace.Slots)
            if eid in alive
        )

    def reconcile_slots(self, running: Dict[str, int]) -> Dict[str, int]:
        """Rebuild the durable slot counts from ground truth: for every
        registered executor, available = task_slots − tasks actually
        running on it (``running``, from the persisted graphs of EVERY
        curator).  Slot counts outlive the scheduler process, so
        reservations held by a process that died (SIGKILL before the
        tasks launched, or whose re-armed tasks went back to pending on
        recovery) leak forever otherwise — on a small fleet that is a
        permanent dispatch deadlock.  Runs under the global Slots lock;
        a live peer's reserved-but-not-yet-launched slots are the one
        window this can momentarily overcount, which costs brief
        oversubscription rather than a wedge.  Returns {executor_id:
        reclaimed} for executors whose count changed."""
        changed: Dict[str, int] = {}
        lk = self.backend.lock(Keyspace.Slots, "global")
        with lk:
            txn = []
            for meta in self.executors():
                want = max(
                    0,
                    meta.specification.task_slots
                    - running.get(meta.id, 0),
                )
                raw = self.backend.get(Keyspace.Slots, meta.id)
                have = _slots_from(raw) if raw is not None else 0
                if have != want:
                    txn.append((Keyspace.Slots, meta.id, _slots_bytes(want)))
                    changed[meta.id] = want - have
            if txn:
                self._fenced_txn(lk, txn)
        return changed


def _slots_bytes(n: int) -> bytes:
    return json.dumps({"slots": n}).encode()


def _slots_from(raw: bytes) -> int:
    return json.loads(raw.decode())["slots"]
