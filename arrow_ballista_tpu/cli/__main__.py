from . import main

main()
