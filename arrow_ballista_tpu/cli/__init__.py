"""Interactive SQL CLI.

Counterpart of the reference's ``ballista-cli`` crate
(``ballista-cli/src/main.rs:33-120``, ``command.rs:35-183``,
``exec.rs:35-170``, ``context.rs``): a readline REPL that runs either
*local* (in-proc single-node engine, like the reference's DataFusion mode)
or *remote* against a scheduler (``--host``/``--port``).  Backslash
commands mirror the reference's Command enum: ``\\q`` quit, ``\\?``/``\\h``
help, ``\\d`` list tables, ``\\d NAME`` describe, ``\\quiet [on|off]``,
``\\pset [format NAME]``, plus file execution via ``-f`` and ``-e``.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Optional

import pyarrow as pa

FORMATS = ("table", "csv", "tsv", "json", "nd-json")


class PrintOptions:
    def __init__(self, fmt: str = "table", quiet: bool = False):
        self.format = fmt
        self.quiet = quiet

    def print_table(self, tbl: pa.Table, elapsed_s: float) -> None:
        out = sys.stdout
        if self.format == "table":
            out.write(_ascii_table(tbl) + "\n")
        elif self.format in ("csv", "tsv"):
            sep = "," if self.format == "csv" else "\t"
            out.write(sep.join(tbl.schema.names) + "\n")
            for row in _iter_rows(tbl):
                out.write(sep.join("" if v is None else str(v) for v in row) + "\n")
        elif self.format == "json":
            import json

            out.write(json.dumps(tbl.to_pylist(), default=str) + "\n")
        elif self.format == "nd-json":
            import json

            for rec in tbl.to_pylist():
                out.write(json.dumps(rec, default=str) + "\n")
        if not self.quiet:
            out.write(
                f"{tbl.num_rows} row(s) in set. Query took {elapsed_s:.3f} seconds.\n"
            )
        out.flush()


def _iter_rows(tbl: pa.Table):
    cols = [c.to_pylist() for c in tbl.columns]
    for i in range(tbl.num_rows):
        yield [c[i] for c in cols]


def _ascii_table(tbl: pa.Table, max_rows: int = 1000) -> str:
    names = tbl.schema.names
    rows = [
        ["" if v is None else str(v) for v in row]
        for _, row in zip(range(max_rows), _iter_rows(tbl))
    ]
    widths = [len(n) for n in names]
    for row in rows:
        for i, v in enumerate(row):
            widths[i] = max(widths[i], len(v))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = [sep]
    lines.append(
        "|" + "|".join(f" {n:<{w}} " for n, w in zip(names, widths)) + "|"
    )
    lines.append(sep)
    for row in rows:
        lines.append(
            "|" + "|".join(f" {v:<{w}} " for v, w in zip(row, widths)) + "|"
        )
    lines.append(sep)
    if tbl.num_rows > max_rows:
        lines.append(f"... {tbl.num_rows - max_rows} more row(s)")
    return "\n".join(lines)


HELP = """\
\\q                 quit
\\? or \\h           this help
\\d                 list tables
\\d NAME            describe table NAME
\\quiet [on|off]    toggle row-count/timing footer
\\pset [format F]   set output format: table csv tsv json nd-json
Any other input is executed as SQL (terminate with ;)."""


class Repl:
    def __init__(self, ctx, opts: PrintOptions):
        self.ctx = ctx
        self.opts = opts

    # ------------------------------------------------------------ commands
    def handle_command(self, line: str) -> bool:
        """Returns False when the REPL should exit."""
        parts = line.strip().split()
        cmd, args = parts[0], parts[1:]
        if cmd in ("\\q", "\\quit"):
            return False
        if cmd in ("\\?", "\\h", "\\help"):
            print(HELP)
        elif cmd == "\\d":
            if args:
                self.run_sql(f"SHOW COLUMNS FROM {args[0]}")
            else:
                self.run_sql("SHOW TABLES")
        elif cmd == "\\quiet":
            if args:
                self.opts.quiet = args[0].lower() == "on"
            print(f"quiet mode {'on' if self.opts.quiet else 'off'}")
        elif cmd == "\\pset":
            if len(args) == 2 and args[0] == "format":
                if args[1] not in FORMATS:
                    print(f"unknown format {args[1]!r}; one of {FORMATS}")
                else:
                    self.opts.format = args[1]
            else:
                print(f"format: {self.opts.format}")
        else:
            print(f"unknown command {cmd!r}; \\? for help")
        return True

    def run_sql(self, sql: str) -> bool:
        """Returns False on error (REPL stays alive; batch mode exits 1)."""
        t0 = time.perf_counter()
        try:
            tbl = self.ctx.sql(sql).collect()
        except Exception as e:  # surface engine errors, keep the REPL alive
            print(f"Error: {e}")
            return False
        self.opts.print_table(tbl, time.perf_counter() - t0)
        return True

    # ---------------------------------------------------------------- loop
    def run(self) -> None:
        try:
            import readline  # noqa: F401 (line editing side effect)
        except ImportError:
            pass
        buf: list[str] = []
        while True:
            prompt = "ballista> " if not buf else "       -> "
            try:
                line = input(prompt)
            except EOFError:
                print()
                break
            except KeyboardInterrupt:
                buf.clear()
                print()
                continue
            if not buf and line.strip().startswith("\\"):
                if not self.handle_command(line):
                    break
                continue
            if not line.strip():
                continue
            buf.append(line)
            joined = "\n".join(buf)
            if joined.rstrip().endswith(";"):
                buf.clear()
                self.run_sql(joined.rstrip().rstrip(";"))


def split_statements(text: str) -> list:
    """Split on ';' outside of single/double-quoted literals (a plain
    ``text.split(';')`` would corrupt ``SELECT 'a;b'``)."""
    stmts: list[str] = []
    buf: list[str] = []
    quote: Optional[str] = None
    for ch in text:
        if quote is not None:
            buf.append(ch)
            if ch == quote:
                quote = None
        elif ch in ("'", '"'):
            quote = ch
            buf.append(ch)
        elif ch == ";":
            stmts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if "".join(buf).strip():
        stmts.append("".join(buf))
    return [s for s in stmts if s.strip()]


def exec_file(ctx, path: str, opts: PrintOptions) -> bool:
    """Non-interactive file execution (reference: exec.rs file mode).
    Returns False if any statement failed."""
    with open(path) as f:
        text = f.read()
    repl = Repl(ctx, opts)
    ok = True
    for stmt in split_statements(text):
        ok = repl.run_sql(stmt) and ok
    return ok


def main(argv=None) -> None:
    from ..utils import apply_jax_platform_env

    apply_jax_platform_env()
    ap = argparse.ArgumentParser(
        "ballista-tpu-cli", description="Ballista-TPU interactive SQL shell"
    )
    ap.add_argument("--host", default=None, help="scheduler host (remote mode)")
    ap.add_argument("--port", type=int, default=50050, help="scheduler port")
    ap.add_argument(
        "-p", "--data-path", default=None, help="chdir here before running"
    )
    ap.add_argument("-f", "--file", action="append", default=[],
                    help="run SQL from file(s) and exit")
    ap.add_argument("-e", "--command", action="append", default=[],
                    help="run the given SQL command(s) and exit")
    ap.add_argument("--format", default="table", choices=FORMATS)
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.data_path:
        import os

        os.chdir(args.data_path)

    if args.host:
        from ..client.context import BallistaContext

        ctx = BallistaContext.remote(args.host, args.port)
        mode = f"remote scheduler {args.host}:{args.port}"
    else:
        from ..context import SessionContext

        ctx = SessionContext()
        mode = "local mode"

    opts = PrintOptions(args.format, args.quiet)
    if args.file or args.command:
        ok = True
        for path in args.file:
            ok = exec_file(ctx, path, opts) and ok
        repl = Repl(ctx, opts)
        for sql in args.command:
            for stmt in split_statements(sql):
                ok = repl.run_sql(stmt) and ok
        if not ok:
            sys.exit(1)
        return
    print(f"Ballista-TPU CLI ({mode}). \\? for help, \\q to quit.")
    Repl(ctx, opts).run()


if __name__ == "__main__":
    main()
