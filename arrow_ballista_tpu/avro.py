"""Avro object-container-file reader (pure python → Arrow).

The reference reads Avro through DataFusion's avro support
(``BallistaContext::read_avro`` / ``register_avro``,
``client/src/context.rs:212-311``).  No Avro library ships in this
environment, so this is a small self-contained decoder for the format's
common subset:

* primitive types: null, boolean, int, long, float, double, bytes, string
* records (flattened to columns), unions of [null, T] (→ nullable column)
* logical types date (int) and timestamp-millis/micros (long)
* codecs: null and deflate (zlib raw)

Avro spec: https://avro.apache.org/docs/current/specification/ — varint
zigzag encoding, file header with JSON schema + 16-byte sync marker,
then blocks of (row count, byte size, data, sync).
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Iterator, Optional

import pyarrow as pa

from .errors import BallistaError

MAGIC = b"Obj\x01"


class AvroError(BallistaError):
    pass


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise AvroError("truncated avro data")
        out = self.data[self.pos: self.pos + n]
        self.pos += n
        return out

    def read_long(self) -> int:
        """Zigzag varint (bounds-checked: a truncated or corrupt file must
        raise AvroError, not IndexError / an unbounded shift loop)."""
        shift = 0
        accum = 0
        while True:
            if self.pos >= len(self.data):
                raise AvroError("truncated avro varint")
            if shift > 63:
                raise AvroError("avro varint exceeds 64 bits")
            b = self.data[self.pos]
            self.pos += 1
            accum |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (accum >> 1) ^ -(accum & 1)

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def at_end(self) -> bool:
        return self.pos >= len(self.data)


def _arrow_type(schema) -> pa.DataType:
    """Avro schema node → Arrow type (nullable handled by caller)."""
    if isinstance(schema, str):
        return {
            "null": pa.null(),
            "boolean": pa.bool_(),
            "int": pa.int32(),
            "long": pa.int64(),
            "float": pa.float32(),
            "double": pa.float64(),
            "bytes": pa.binary(),
            "string": pa.string(),
        }[schema]
    if isinstance(schema, dict):
        t = schema["type"]
        logical = schema.get("logicalType")
        if logical == "date":
            return pa.date32()
        if logical == "timestamp-millis":
            return pa.timestamp("ms")
        if logical == "timestamp-micros":
            return pa.timestamp("us")
        if logical == "time-millis":
            return pa.time32("ms")
        if logical == "decimal":
            return pa.decimal128(schema.get("precision", 38), schema.get("scale", 0))
        return _arrow_type(t)
    raise AvroError(f"unsupported avro schema node {schema!r}")


def _field_schema(schema) -> tuple[pa.DataType, bool, object]:
    """→ (arrow type, nullable, decode-schema) for one record field."""
    if isinstance(schema, list):  # union
        non_null = [s for s in schema if s != "null"]
        if len(non_null) != 1:
            raise AvroError(f"only [null, T] unions supported, got {schema}")
        t, _, dec = _field_schema(non_null[0])
        return t, True, schema
    return _arrow_type(schema), False, schema


def _decode_value(r: _Reader, schema) -> object:
    if isinstance(schema, list):  # union: branch index then value
        idx = r.read_long()
        branch = schema[idx]
        if branch == "null":
            return None
        return _decode_value(r, branch)
    if isinstance(schema, dict):
        logical = schema.get("logicalType")
        base = schema["type"]
        v = _decode_value(r, base)
        # date/timestamp remain ints; Arrow interprets via column type
        _ = logical
        return v
    if schema == "null":
        return None
    if schema == "boolean":
        return r.read(1) != b"\x00"
    if schema in ("int", "long"):
        return r.read_long()
    if schema == "float":
        return struct.unpack("<f", r.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", r.read(8))[0]
    if schema == "bytes":
        return r.read_bytes()
    if schema == "string":
        return r.read_bytes().decode("utf-8")
    raise AvroError(f"unsupported avro type {schema!r}")


class AvroFile:
    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self._raw = f.read()
        r = _Reader(self._raw)
        if r.read(4) != MAGIC:
            raise AvroError(f"{path}: not an avro object container file")
        meta: dict[str, bytes] = {}
        n = r.read_long()
        while n != 0:
            if n < 0:  # negative count: byte size follows
                n = -n
                r.read_long()
            for _ in range(n):
                key = r.read_bytes().decode()
                meta[key] = r.read_bytes()
            n = r.read_long()
        self.codec = meta.get("avro.codec", b"null").decode()
        if self.codec not in ("null", "deflate"):
            raise AvroError(f"unsupported avro codec {self.codec!r}")
        self.avro_schema = json.loads(meta["avro.schema"])
        if self.avro_schema.get("type") != "record":
            raise AvroError("top-level avro schema must be a record")
        self.sync = r.read(16)
        self._body_pos = r.pos

        fields = []
        self._decoders = []
        for f_schema in self.avro_schema["fields"]:
            t, nullable, dec = _field_schema(f_schema["type"])
            fields.append(pa.field(f_schema["name"], t, nullable))
            self._decoders.append(dec)
        self.schema = pa.schema(fields)

    def blocks(self) -> Iterator[tuple[int, bytes]]:
        r = _Reader(self._raw)
        r.pos = self._body_pos
        while not r.at_end():
            count = r.read_long()
            data = r.read_bytes()
            if r.read(16) != self.sync:
                raise AvroError(f"{self.path}: sync marker mismatch")
            if self.codec == "deflate":
                data = zlib.decompress(data, -15)
            yield count, data

    def read_batches(
        self, projection: Optional[list[str]] = None, batch_size: int = 8192
    ) -> Iterator[pa.RecordBatch]:
        names = self.schema.names
        proj_idx = (
            [names.index(p) for p in projection] if projection is not None else None
        )
        out_schema = (
            pa.schema([self.schema.field(i) for i in proj_idx])
            if proj_idx is not None
            else self.schema
        )
        cols: list[list] = [[] for _ in range(len(names))]
        rows = 0

        def flush():
            nonlocal cols, rows
            take = proj_idx if proj_idx is not None else range(len(names))
            arrays = [
                pa.array(cols[i], type=self.schema.field(i).type) for i in take
            ]
            batch = pa.RecordBatch.from_arrays(arrays, schema=out_schema)
            cols = [[] for _ in range(len(names))]
            rows = 0
            return batch

        for count, data in self.blocks():
            r = _Reader(data)
            for _ in range(count):
                for i, dec in enumerate(self._decoders):
                    v = _decode_value(r, dec)
                    cols[i].append(v)
                rows += 1
                if rows >= batch_size:
                    yield flush()
        if rows:
            yield flush()


def write_avro(path: str, table: pa.Table) -> None:
    """Minimal Avro writer (null codec) — test/tooling counterpart so the
    reader can be exercised without an external avro library."""
    import io

    def zigzag(n: int) -> bytes:
        u = (n << 1) ^ (n >> 63)
        out = bytearray()
        while True:
            b = u & 0x7F
            u >>= 7
            if u:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def enc_bytes(b: bytes) -> bytes:
        return zigzag(len(b)) + b

    def avro_of(t: pa.DataType):
        if pa.types.is_int32(t):
            return "int"
        if pa.types.is_int64(t):
            return "long"
        if pa.types.is_float32(t):
            return "float"
        if pa.types.is_float64(t):
            return "double"
        if pa.types.is_boolean(t):
            return "boolean"
        if pa.types.is_string(t):
            return "string"
        if pa.types.is_binary(t):
            return "bytes"
        if pa.types.is_date32(t):
            return {"type": "int", "logicalType": "date"}
        if pa.types.is_timestamp(t):
            unit = {"ms": "timestamp-millis", "us": "timestamp-micros"}[t.unit]
            return {"type": "long", "logicalType": unit}
        raise AvroError(f"cannot write arrow type {t} to avro")

    schema = {
        "type": "record",
        "name": "row",
        "fields": [
            {
                "name": f.name,
                "type": ["null", avro_of(f.type)] if f.nullable else avro_of(f.type),
            }
            for f in table.schema
        ],
    }

    def enc_value(v, f: pa.Field) -> bytes:
        t = f.type
        if f.nullable:
            if v is None:
                return zigzag(0)
            prefix = zigzag(1)
        else:
            prefix = b""
        if pa.types.is_boolean(t):
            return prefix + (b"\x01" if v else b"\x00")
        if pa.types.is_integer(t):
            return prefix + zigzag(int(v))
        if pa.types.is_float32(t):
            return prefix + struct.pack("<f", v)
        if pa.types.is_float64(t):
            return prefix + struct.pack("<d", v)
        if pa.types.is_string(t):
            return prefix + enc_bytes(v.encode())
        if pa.types.is_binary(t):
            return prefix + enc_bytes(v)
        if pa.types.is_date32(t):
            import datetime

            return prefix + zigzag((v - datetime.date(1970, 1, 1)).days)
        if pa.types.is_timestamp(t):
            import datetime

            epoch = datetime.datetime(1970, 1, 1)
            delta = v - epoch
            us = int(delta.total_seconds() * 1_000_000)
            return prefix + zigzag(us if t.unit == "us" else us // 1000)
        raise AvroError(f"cannot encode {t}")

    body = io.BytesIO()
    pylists = [c.to_pylist() for c in table.columns]
    for row in range(table.num_rows):
        for i, f in enumerate(table.schema):
            body.write(enc_value(pylists[i][row], f))
    data = body.getvalue()

    sync = b"0123456789abcdef"
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = {
            "avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"null",
        }
        f.write(zigzag(len(meta)))
        for k, v in meta.items():
            f.write(enc_bytes(k.encode()))
            f.write(enc_bytes(v))
        f.write(zigzag(0))
        f.write(sync)
        if table.num_rows:
            f.write(zigzag(table.num_rows))
            f.write(zigzag(len(data)))
            f.write(data)
            f.write(sync)
