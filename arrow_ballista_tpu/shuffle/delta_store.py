"""Executor-side mirror of the scheduler's shuffle-location feeds.

Streaming pipelined execution (ISSUE 15): a consumer stage resolved on
PARTIAL map output executes with tailing ``ShuffleReaderExec``s that
carry no static locations — each tails the scheduler's append-only
per-(job, producer-stage) feed of committed map-output locations until
the feed reports complete.  This module is the executor-process mirror
of those feeds:

* push mode: the scheduler's ``UpdateShuffleLocations`` notification
  lands in :func:`apply_delta` as map tasks commit;
* pull mode (and as the push-mode catch-up): a starved tail polls the
  scheduler's ``GetShuffleLocationDelta`` RPC through the stub installed
  by :func:`configure_scheduler` (the poll loop / executor server set it
  at startup).

Feed entries are fenced by ``epoch``: executor-loss rollback invalidates
a feed scheduler-side and any recreated feed starts at the next epoch,
so a mirror RESETS when the epoch advances and ABORTS (raises) when the
scheduler reports the feed invalid — two generations of locations are
never merged.  Deltas apply only when contiguous (``from_index`` at or
below the mirror's length); gapped pushes are dropped and the poll
catches up.

Everything here is jax-free and cheap when unused: a barrier-scheduled
executor never touches this module.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

log = logging.getLogger(__name__)

# how long a starved tail waits on the condition variable before falling
# back to a scheduler poll (push mode normally wakes it long before)
DEFAULT_POLL_INTERVAL_S = 0.05
# bounded mirror: feeds of long-gone jobs must not accumulate forever
MAX_FEEDS = 64


class _Feed:
    __slots__ = ("locations", "complete", "valid", "epoch", "touched_mono")

    def __init__(self) -> None:
        self.locations: List[object] = []
        self.complete = False
        self.valid = True
        self.epoch = 0
        self.touched_mono = time.monotonic()


_cv = threading.Condition()
_feeds: Dict[Tuple[str, int], _Feed] = {}
# GetShuffleLocationDelta transport: a zero-arg callable returning the
# scheduler stub (installed by PollLoop / ExecutorServer at startup)
_scheduler_stub: Optional[Callable[[], object]] = None


def configure_scheduler(stub_fn: Callable[[], object]) -> None:
    """Install the scheduler-stub factory tailing fetches poll through.
    Last writer wins — one executor process talks to one scheduler (the
    HA fail-over re-registers and re-installs)."""
    global _scheduler_stub
    with _cv:
        _scheduler_stub = stub_fn


def reset() -> None:
    """Test aid: forget every mirrored feed and the stub."""
    global _scheduler_stub
    with _cv:
        _feeds.clear()
        _scheduler_stub = None
        _cv.notify_all()


def _feed(key: Tuple[str, int]) -> _Feed:
    f = _feeds.get(key)
    if f is None:
        if len(_feeds) >= MAX_FEEDS:
            oldest = min(_feeds, key=lambda k: _feeds[k].touched_mono)
            _feeds.pop(oldest, None)
        f = _Feed()
        _feeds[key] = f
    f.touched_mono = time.monotonic()
    return f


def apply_delta(
    job_id: str,
    stage_id: int,
    from_index: int,
    locations: list,
    complete: bool,
    valid: bool,
    epoch: int,
) -> None:
    """Merge one feed delta (push notification or poll response) into
    the mirror.  Epoch fencing: newer epoch resets the mirror, older is
    dropped; ``valid=False`` at the current-or-newer epoch — or at epoch
    0, the scheduler's "no such feed" answer after restart/job eviction
    — marks the feed dead and wakes every tail so it aborts."""
    with _cv:
        feed = _feed((job_id, stage_id))
        if not valid and (epoch == 0 or epoch >= feed.epoch):
            # epoch 0 is the scheduler saying "I don't know this feed at
            # all" (restart / job eviction — live feeds start at epoch 1):
            # authoritative, kills any generation.  A stale invalid from
            # an OLD generation (delayed push racing a recreation) still
            # drops below.
            feed.valid = False
            _cv.notify_all()
            return
        if epoch < feed.epoch:
            return  # stale generation (including its invalid tombstones)
        if epoch > feed.epoch:
            feed.locations = []
            feed.complete = False
            feed.valid = True
            feed.epoch = epoch
        if from_index > len(feed.locations):
            return  # gap (lost push): the poll catches up from our length
        fresh = locations[len(feed.locations) - from_index :]
        if fresh:
            feed.locations.extend(fresh)
        if complete:
            feed.complete = True
        if fresh or complete:
            _cv.notify_all()


def apply_delta_proto(delta) -> None:
    """``apply_delta`` from a ``pb.ShuffleLocationDelta``."""
    from ..serde.scheduler_types import PartitionLocation

    apply_delta(
        delta.job_id,
        delta.stage_id,
        delta.from_index,
        [PartitionLocation.from_proto(l) for l in delta.locations],
        bool(delta.complete),
        bool(delta.valid),
        delta.epoch,
    )


def _poll(job_id: str, stage_id: int) -> None:
    """One GetShuffleLocationDelta round trip (outside the lock); RPC
    errors are swallowed — the tail keeps waiting and retries on its
    next starvation tick (scheduler restart mid-job lands here until
    the task is cancelled or reaped)."""
    with _cv:
        stub_fn = _scheduler_stub
        feed = _feeds.get((job_id, stage_id))
        from_index = len(feed.locations) if feed is not None else 0
    if stub_fn is None:
        return
    try:
        from ..proto import pb

        stub = stub_fn()
        resp = stub.GetShuffleLocationDelta(
            pb.ShuffleLocationDeltaParams(
                job_id=job_id, stage_id=stage_id, from_index=from_index
            ),
            timeout=10,
        )
    except Exception as e:  # noqa: BLE001 - poll is best-effort
        log.debug(
            "GetShuffleLocationDelta(%s, %d) failed: %s", job_id, stage_id, e
        )
        return
    apply_delta_proto(resp)


def feed_snapshot(job_id: str, stage_id: int) -> dict:
    """Introspection/test surface: the mirror's current view."""
    with _cv:
        feed = _feeds.get((job_id, stage_id))
        if feed is None:
            return {"locations": 0, "complete": False, "valid": True, "epoch": 0}
        return {
            "locations": len(feed.locations),
            "complete": feed.complete,
            "valid": feed.valid,
            "epoch": feed.epoch,
        }


def tail_locations(
    job_id: str,
    stage_id: int,
    partition: int,
    stop_event: Optional[threading.Event] = None,
    cancel_event: Optional[threading.Event] = None,
    metrics=None,
    poll_interval_s: float = DEFAULT_POLL_INTERVAL_S,
) -> Iterator[object]:
    """Yield ``partition``'s map-side locations one by one as they land
    (flattening wrapper over :func:`tail_location_batches`)."""
    for chunk in tail_location_batches(
        job_id,
        stage_id,
        partition,
        stop_event=stop_event,
        cancel_event=cancel_event,
        metrics=metrics,
        poll_interval_s=poll_interval_s,
    ):
        yield from chunk


def tail_location_batches(
    job_id: str,
    stage_id: int,
    partition: int,
    stop_event: Optional[threading.Event] = None,
    cancel_event: Optional[threading.Event] = None,
    metrics=None,
    poll_interval_s: float = DEFAULT_POLL_INTERVAL_S,
) -> Iterator[list]:
    """Yield ``partition``'s map-side locations as they land in the feed,
    finishing when the feed is complete and drained.

    Each yielded item is one backlog DRAIN: every location that had
    accumulated in the feed since the previous drain, already filtered
    to ``partition``.  A consumer that kept pace sees singleton lists; a
    consumer that fell behind (slow first fetch, late start against an
    almost-complete feed) sees the whole backlog at once and can fan it
    out over a concurrent fetch pool instead of draining in feed order.

    Starvation (stall-on-producer) is accounted into the owning
    operator's ``fetch_wait_time_ns`` so the doctor's attribution stays
    exact — a pipelined consumer's wait shows up as fetch wait, not as
    an unattributed hole.  An invalidated feed raises ``ExecutionError``
    (transient: the scheduler has already rolled the consumer back and
    this task's late status is guarded).
    """
    from ..errors import Cancelled, ExecutionError

    cursor = 0
    epoch: Optional[int] = None  # the generation this tail is consuming
    while True:
        batch: list = []
        done = False
        still_starved = False
        with _cv:
            feed = _feed((job_id, stage_id))
            if not feed.valid:
                raise ExecutionError(
                    f"shuffle feed for stage {stage_id} was invalidated "
                    "(producer rollback in progress)"
                )
            # epoch pin: a tail consumes exactly ONE feed generation.  If
            # the mirror reset under us (the new attempt's seed landed
            # before our cancel did), our cursor indexes the DEAD
            # generation — abort instead of splicing two generations.
            if feed.epoch:
                if epoch is None:
                    epoch = feed.epoch
                elif feed.epoch != epoch:
                    raise ExecutionError(
                        f"shuffle feed for stage {stage_id} was superseded "
                        f"(epoch {epoch} -> {feed.epoch})"
                    )
            if cursor < len(feed.locations):
                batch = feed.locations[cursor:]
                cursor = len(feed.locations)
            elif feed.complete:
                done = True
            else:
                t0 = time.monotonic_ns()
                _cv.wait(poll_interval_s)
                if metrics is not None:
                    metrics.add(
                        "fetch_wait_time_ns", time.monotonic_ns() - t0
                    )
                still_starved = (
                    cursor >= len(feed.locations)
                    and not feed.complete
                    and feed.valid
                )
        if done:
            return
        for ev, exc in (
            (cancel_event, Cancelled("task cancelled")),
            (stop_event, ExecutionError("shuffle tail aborted: shutdown")),
        ):
            if ev is not None and ev.is_set():
                raise exc
        if batch:
            chunk = [
                loc
                for loc in batch
                if (pid := getattr(loc, "partition_id", None)) is None
                or pid.partition_id == partition
            ]
            if chunk:
                yield chunk
            continue
        if still_starved:
            # nothing arrived inside the wait window: fall back to a poll
            # (the pull-mode transport; push mode rarely gets here)
            t0 = time.monotonic_ns()
            _poll(job_id, stage_id)
            if metrics is not None:
                metrics.add("fetch_wait_time_ns", time.monotonic_ns() - t0)
