"""Pipelined, slab-buffered, compressed shuffle write.

The map side of every multi-stage query splits its stage output across
N output partitions.  The original ``ShuffleWriterExec`` did everything
on the compute thread: an O(n log n) argsort per batch, then one tiny
synchronous uncompressed IPC write per (input batch, output partition)
run — so a 64-in x 16-out shuffle produced 1024 file fragments and the
stage subplan sat idle during every write syscall.  This module is the
write-side twin of :mod:`shuffle.fetcher` (PAPERS.md Zerrow / Arrow
Flight benchmarking: producer-side layout and copy/compression decisions
dominate end-to-end shuffle throughput):

* the compute thread only hash-splits (O(n) counting-sort permutation,
  :func:`exec.operators.partition_permutation`) and appends zero-copy
  row slices to per-output-partition **slab buffers**;
* a slab reaching ``ballista.shuffle.write_coalesce_rows`` is handed to
  a bounded **writer pool**: concatenation, IPC serialization (optional
  lz4/zstd body compression) and sink I/O all run off the compute
  thread.  Output partitions are sharded across the pool's threads
  (partition ``p`` -> worker ``p % W``), so each sink is touched by
  exactly one thread and per-sink batch order stays deterministic;
* the pool's queues are bounded by BYTES — a stage subplan that produces
  faster than the disk (or memory store) absorbs blocks in ``append``
  instead of buffering the whole stage output;
* the first worker error tears the pipeline down and re-raises on the
  compute thread; cancellation via :meth:`AsyncShuffleWriter.abort`
  closes every queue and sink without leaking file handles.

Metrics (into the owning operator's registry, mirrored to the process
registry): ``bytes_written_raw`` (batch bytes handed to sinks),
``bytes_written_wire`` (bytes that actually hit the sink — the
raw/wire ratio is the compression ratio), ``slab_flushes``,
``write_queue_full_ns`` (compute-thread backpressure time) and
``write_time_ns`` (serialization + sink I/O time on the pool threads).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

import pyarrow as pa

from ..errors import ExecutionError
from .fetcher import _TeeMetrics

_WRITE_REGISTRY_NAMES = {
    "bytes_written_raw": "shuffle_bytes_written_raw_total",
    "bytes_written_wire": "shuffle_bytes_written_wire_total",
    "write_queue_full_ns": "shuffle_write_queue_full_ns_total",
    "slab_flushes": "shuffle_slab_flushes_total",
    "write_time_ns": "shuffle_write_ns_total",
}

# Process-wide write-queue occupancy: bytes coalesced but not yet written
# across every live AsyncShuffleWriter.  The telemetry heartbeat
# piggyback (obs/telemetry.py) reports it next to the fetch side's
# staging bytes — two plain ints, no jax/pyarrow on the read path.
_queued_lock = threading.Lock()
_queued_bytes = 0


def _queued_add(n: int) -> None:
    global _queued_bytes
    with _queued_lock:
        _queued_bytes += n


def _queued_sub(n: int) -> None:
    global _queued_bytes
    with _queued_lock:
        _queued_bytes -= n
        if _queued_bytes < 0:  # defensive: never report negative pressure
            _queued_bytes = 0


def queued_bytes() -> int:
    """Bytes sitting in shuffle write-pool queues right now."""
    with _queued_lock:
        return _queued_bytes


@dataclass(frozen=True)
class WritePolicy:
    """Map-side write knobs (see ``ballista.shuffle.write_*`` and the
    storage/replication knobs ``ballista.shuffle.{store,replication,
    external_path}``)."""

    coalesce_rows: int = 32768
    queue_bytes: int = 32 << 20
    concurrency: int = 2
    compression: str = "none"
    pipelined: bool = True
    store: str = "local"  # local | mem | external
    replication: str = "none"  # none | async | sync
    external_path: str = ""

    @property
    def replicate(self) -> bool:
        """Upload a replica of each finished partition?  Only meaningful
        for local/mem primaries — an external-store primary already
        survives its producer."""
        return (
            self.replication != "none"
            and bool(self.external_path)
            and self.store != "external"
        )

    @staticmethod
    def from_config(config) -> "WritePolicy":
        rows = config.shuffle_write_coalesce_rows
        if rows == 0:
            # several source batches per slab: IPC serialization and the
            # worker-side gather amortize much better on 4x-batch slabs
            # than on batch-sized ones (measured 1.7x -> 2.4x+ at the
            # default batch size), and downstream readers see 4x fewer
            # fragments
            rows = 4 * config.batch_size
        store = config.shuffle_store
        if store == "local" and config.shuffle_to_memory:
            store = "mem"  # back-compat spelling of the mem store
        return WritePolicy(
            coalesce_rows=rows,
            queue_bytes=config.shuffle_write_queue_bytes,
            concurrency=config.shuffle_write_concurrency,
            compression=config.shuffle_compression,
            pipelined=config.shuffle_write_pipelined,
            store=store,
            replication=config.shuffle_replication,
            external_path=config.shuffle_external_path,
        )


_CODEC_PROBE = {"lz4": "lz4_frame", "zstd": "zstd"}


def ipc_write_options(compression: str) -> Optional[pa.ipc.IpcWriteOptions]:
    """IpcWriteOptions for the configured codec (None for 'none').

    The codec NAME is validated at config parse; availability is a
    build-time property of the pyarrow wheel, checked here so the error
    names the missing codec instead of failing inside the IPC writer."""
    if not compression or compression == "none":
        return None
    if not pa.Codec.is_available(_CODEC_PROBE[compression]):
        raise ExecutionError(
            f"ballista.shuffle.compression={compression!r} but this "
            "pyarrow build lacks the codec"
        )
    return pa.ipc.IpcWriteOptions(compression=compression)


class _Closed(Exception):
    """Internal: the pipeline was torn down (error or abort)."""


class _ByteQueue:
    """Bounded-by-bytes handoff from the compute thread to one writer.

    ``put`` blocks while the byte budget is exhausted — but always admits
    an item when the queue is EMPTY, so a single slab larger than the
    budget cannot deadlock the pipeline (same rule as the fetch side's
    ``_PrefetchQueue``)."""

    def __init__(self, max_bytes: int, metrics, cancel_event=None) -> None:
        self._max = max(1, max_bytes)
        self._metrics = metrics
        self._cancel = cancel_event
        self._items: list = []
        self._bytes = 0
        self._cv = threading.Condition()
        self._closed = False
        self._done = False  # sentinel received: no more puts expected

    def put(self, item, nbytes: int) -> None:
        with self._cv:
            t0 = None
            while self._bytes >= self._max and self._items and not self._closed:
                if self._cancel is not None and self._cancel.is_set():
                    # a cancelled task's compute thread must not stay
                    # parked on backpressure behind a hung sink
                    raise _Closed()
                if t0 is None:
                    t0 = time.monotonic_ns()
                self._cv.wait(0.25 if self._cancel is not None else None)
            if t0 is not None:
                self._metrics.add(
                    "write_queue_full_ns", time.monotonic_ns() - t0
                )
            if self._closed:
                raise _Closed()
            self._items.append((item, nbytes))
            self._bytes += nbytes
            _queued_add(nbytes)
            self._cv.notify_all()

    def finish(self) -> None:
        """No more items: the worker drains what is queued, then exits."""
        with self._cv:
            self._done = True
            self._cv.notify_all()

    def get(self):
        """Next item; None when finished-and-drained.  A CLOSED queue
        (error/abort teardown) raises instead — the worker must not run
        its success-path sink closes over a torn-down pipeline."""
        with self._cv:
            while not self._items and not self._done and not self._closed:
                self._cv.wait()
            if self._closed:
                raise _Closed()
            if not self._items:
                return None
            item, nbytes = self._items.pop(0)
            self._bytes -= nbytes
            _queued_sub(nbytes)
            self._cv.notify_all()
            return item

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._items.clear()
            _queued_sub(self._bytes)
            self._bytes = 0
            self._cv.notify_all()


class AsyncShuffleWriter:
    """One write task's pipeline over its output-partition sinks.

    ``sink_factory(out_part)`` creates the partition's sink (file or
    memory store) — invoked lazily on the owning WORKER thread, so
    directory creation and file opens stay off the compute thread.  Every
    partition gets a sink even when no row hashed to it (readers need no
    existence probe), exactly like the synchronous path."""

    _OPEN = object()  # queue item: ensure the sink exists, write nothing

    def __init__(
        self,
        n_out: int,
        sink_factory: Callable[[int], object],
        policy: WritePolicy,
        metrics,
        cancel_event: Optional[threading.Event] = None,
        replicate_fn: Optional[Callable[[object], None]] = None,
    ) -> None:
        self._n_out = n_out
        self._sink_factory = sink_factory
        self._policy = policy
        # replication hook: invoked on the WORKER thread right after a
        # sink closes (the partition's bytes are final) — uploads the
        # external-store replica off the compute thread.  Must never
        # raise (a failed upload degrades to single copy).
        self._replicate_fn = replicate_fn
        self._metrics = _TeeMetrics(metrics, _WRITE_REGISTRY_NAMES)
        self._cancel = cancel_event
        self._slabs: List[list] = [[] for _ in range(n_out)]
        self._slab_rows = [0] * n_out
        self._slab_nbytes = [0] * n_out
        self._slab_total = 0  # est. bytes pinned across ALL slabs
        self._touched = [False] * n_out
        n_workers = max(1, min(policy.concurrency, n_out))
        self._queues = [
            _ByteQueue(
                max(1, policy.queue_bytes // n_workers),
                self._metrics,
                cancel_event=cancel_event,
            )
            for _ in range(n_workers)
        ]
        self._sinks: List[Optional[object]] = [None] * n_out
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._finished = False

    # ------------------------------------------------------------- compute
    def append(self, out_part: int, batch: pa.RecordBatch) -> None:
        """Buffer one whole batch for ``out_part``; a slab reaching the
        coalesce target ships to the writer pool."""
        if batch.num_rows == 0:
            return
        self._push(
            out_part,
            (batch, None),
            int(getattr(batch, "nbytes", 0) or 0),
            n_rows=batch.num_rows,
        )

    def append_rows(
        self, out_part: int, batch: pa.RecordBatch, indices
    ) -> None:
        """Buffer ``batch``'s rows at ``indices`` (a numpy index array)
        for ``out_part``.  The gather itself (``take``) runs on the
        WORKER when the slab flushes — the compute thread pays only the
        hash + permutation, never a row copy."""
        if len(indices) == 0:
            return
        est = int(
            getattr(batch, "nbytes", 0) * len(indices)
            // max(1, batch.num_rows)
        )
        self._push(out_part, (batch, indices), est, n_rows=len(indices))

    def _push(self, out_part: int, item, nbytes: int, n_rows=None) -> None:
        if self._cancel is not None and self._cancel.is_set():
            from ..errors import Cancelled

            raise Cancelled("task cancelled")
        self._raise_error()
        self._slabs[out_part].append((item, nbytes))
        self._slab_rows[out_part] += (
            n_rows if n_rows is not None else item[0].num_rows
        )
        self._slab_nbytes[out_part] += nbytes
        self._slab_total += nbytes
        if (
            self._policy.coalesce_rows < 0
            or self._slab_rows[out_part] >= self._policy.coalesce_rows
        ):
            self._flush_slab(out_part)
        if self._slab_total > self._policy.queue_bytes:
            # slab references pin their SOURCE batches (append_rows holds
            # indices, not copies), so slab memory must answer to the same
            # byte budget as the queues: under pressure every slab flushes
            # early — a few more fragments beats unbounded pinning at
            # high partition counts
            for p in range(self._n_out):
                self._flush_slab(p)

    def finish(self) -> List[object]:
        """Flush every slab, create sinks for untouched partitions, drain
        the pool and return the CLOSED sinks (one per output partition,
        each with ``path`` / ``num_batches`` / ``num_rows`` and its wire
        size in ``wire_bytes``)."""
        try:
            for p in range(self._n_out):
                self._flush_slab(p)
            for p in range(self._n_out):
                if not self._touched[p]:
                    self._enqueue(p, self._OPEN, 0)
            for q in self._queues:
                q.finish()
            self._start_workers()  # n_out == 0: nothing was ever enqueued
            for t in self._threads:
                t.join()
            # _finished only flips on SUCCESS: an error raised here must
            # leave abort() armed so the failing worker's still-open
            # sinks get their OS handles released.  A cancel that landed
            # during the drain made workers bail via _Closed WITHOUT
            # closing their sinks — that is not a success either.
            self._raise_error()
            if self._cancel is not None and self._cancel.is_set():
                from ..errors import Cancelled

                raise Cancelled("task cancelled")
            self._finished = True
            return [s for s in self._sinks]
        except BaseException:
            self.abort()
            raise

    def abort(self) -> None:
        """Tear the pipeline down (worker error, consumer error or task
        cancel): close every queue, wake blocked threads, then ABANDON
        the sinks that never closed — OS handles are released but
        nothing is published (a partial mem:// partition stored under
        the canonical key would shadow the retry's real one)."""
        if self._finished:
            return
        with self._error_lock:
            if self._error is None:
                self._error = ExecutionError("shuffle write aborted")
        for q in self._queues:
            q.close()
        for t in self._threads:
            t.join(timeout=5)
        for s in self._sinks:
            if s is not None and getattr(s, "wire_bytes", None) is None:
                try:
                    s.abandon()
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    pass
        self._finished = True

    # ------------------------------------------------------------ internal
    def _flush_slab(self, p: int) -> None:
        items = self._slabs[p]
        if not items:
            return
        nbytes = sum(n for _, n in items)
        self._slabs[p] = []
        self._slab_rows[p] = 0
        self._slab_total -= self._slab_nbytes[p]
        self._slab_nbytes[p] = 0
        self._metrics.add("slab_flushes", 1)
        # gather + concat (the one copy this path pays) happen on the WORKER
        self._enqueue(p, [it for it, _ in items], nbytes)

    def _enqueue(self, p: int, item, nbytes: int) -> None:
        self._touched[p] = True
        self._start_workers()
        try:
            self._queues[p % len(self._queues)].put((p, item), nbytes)
        except _Closed:
            if self._cancel is not None and self._cancel.is_set():
                from ..errors import Cancelled

                raise Cancelled("task cancelled")
            self._raise_error()
            raise ExecutionError("shuffle write pipeline closed")

    def _start_workers(self) -> None:
        if self._threads:
            return
        for i, q in enumerate(self._queues):
            t = threading.Thread(
                target=self._worker,
                args=(i, q),
                name=f"shuffle-write-{i}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _raise_error(self) -> None:
        with self._error_lock:
            if self._error is not None:
                raise self._error

    def _fail(self, e: BaseException) -> None:
        with self._error_lock:
            if self._error is None:
                self._error = e
        for q in self._queues:
            q.close()

    def _worker(self, w: int, q: _ByteQueue) -> None:
        from ..testing.faults import fault_point

        try:
            while True:
                got = q.get()
                if got is None:
                    break
                if self._cancel is not None and self._cancel.is_set():
                    raise _Closed()  # stop writing; abort abandons sinks
                p, item = got
                t0 = time.monotonic_ns()
                sink = self._sinks[p]
                if sink is None:
                    sink = self._sinks[p] = self._sink_factory(p)
                if item is not self._OPEN:
                    parts = [
                        b if ix is None else b.take(pa.array(ix))
                        for b, ix in item
                    ]
                    batch = (
                        parts[0] if len(parts) == 1
                        else pa.concat_batches(parts)
                    )
                    fault_point(
                        "shuffle.write.sink",
                        path=getattr(sink, "path", ""),
                        partition=p,
                    )
                    sink.write(batch)
                    self._metrics.add(
                        "bytes_written_raw",
                        int(getattr(batch, "nbytes", 0) or 0),
                    )
                self._metrics.add("write_time_ns", time.monotonic_ns() - t0)
            # drain complete: close this worker's shard of sinks
            t0 = time.monotonic_ns()
            for p in range(w, self._n_out, len(self._queues)):
                s = self._sinks[p]
                if s is not None:
                    self._metrics.add("bytes_written_wire", s.close())
                    if self._replicate_fn is not None:
                        self._replicate_fn(s)
            self._metrics.add("write_time_ns", time.monotonic_ns() - t0)
        except _Closed:
            # teardown (error elsewhere, abort or cancel): leave this
            # shard's sinks to abort()'s abandon pass — closing them here
            # would PUBLISH partial partitions and inflate wire metrics
            pass
        except BaseException as e:  # first error wins; tears the pipe down
            self._fail(e)
