"""In-process memory shuffle store: the TPU-first shuffle data plane.

The reference persists every shuffle partition as an Arrow IPC file and
serves it over Flight (``shuffle_writer.rs:142-292`` →
``flight_service.rs:80-118``).  On a TPU host the data either stays on the
mesh (gang stages exchange via ICI collectives) or — for stage outputs
that must cross a process/host boundary — can be held in RAM and streamed
straight out of the executor's Flight service without touching disk.

Paths use the scheme ``mem://<job>/<stage>/<out_partition>/<in_partition>``
so PartitionLocation / ShuffleWritePartition stats, the scheduler graph,
and fault recovery are completely unchanged: a lost executor loses its
memory partitions exactly like its local files, and ``reset_stages`` rolls
the producing stage back the same way.

Lifetime mirrors the shuffle janitor's job-directory GC: ``delete_job`` is
called wherever job work-dirs are removed.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

SCHEME = "mem://"

# Spool mode (process-isolated task workers): the worker process cannot
# publish into the PARENT executor's in-memory store, so with a spool
# dir set its puts write compact IPC buffers to files under the shared
# work_dir instead (tmpfs-speed when work_dir is tmpfs) and the parent
# absorbs them into its own store when the task completes — mem://
# partitions stay served from executor memory while plan execution
# stays out of the executor's GIL (reference DedicatedExecutor,
# cpu_bound_executor.rs:37-131).
_spool_dir: Optional[str] = None

_lock = threading.Lock()
# values are compact Arrow IPC stream buffers, NOT RecordBatch lists: a
# stored batch slice would pin its parent batch's entire allocation (and
# overstate stats); serializing compacts to exactly the partition's bytes,
# and readers reopen the buffer zero-copy
_store: Dict[Tuple[str, int, int, int], pa.Buffer] = {}
_job_touched: Dict[str, float] = {}  # job_id -> last put() wall time


def make_path(job_id: str, stage_id: int, out_part: int, in_part: int) -> str:
    return f"{SCHEME}{job_id}/{stage_id}/{out_part}/{in_part}"


def parse_path(path: str) -> Optional[Tuple[str, int, int, int]]:
    if not path.startswith(SCHEME):
        return None
    parts = path[len(SCHEME):].split("/")
    if len(parts) != 4:
        return None
    return parts[0], int(parts[1]), int(parts[2]), int(parts[3])


def set_spool_dir(path: Optional[str]) -> None:
    """Divert puts in THIS process to spool files (task workers)."""
    global _spool_dir
    if path is not None:
        import os

        os.makedirs(path, exist_ok=True)
    _spool_dir = path


def spool_file(spool_dir: str, path: str) -> Optional[str]:
    key = parse_path(path)
    if key is None:
        return None
    import os

    return os.path.join(
        spool_dir, f"{key[0]}__{key[1]}__{key[2]}__{key[3]}.arrow"
    )


def absorb_spooled(spool_dir: str, path: str) -> bool:
    """Parent side: move a worker's spooled partition into this
    process's store (memory-map, copy into an owned buffer, unlink)."""
    import time

    key = parse_path(path)
    f = spool_file(spool_dir, path)
    if key is None or f is None:
        return False
    import os

    try:
        # no exists() pre-check: a janitor sweep or a duplicate task
        # completion can unlink between check and open (TOCTOU) — treat
        # any filesystem race as "not spooled" and let the caller warn
        with open(f, "rb") as fh:
            buf = pa.py_buffer(fh.read())
        os.unlink(f)
    except OSError:
        return False
    with _lock:
        _store[key] = buf
        _job_touched[key[0]] = time.monotonic()
    return True


def put(
    job_id: str,
    stage_id: int,
    out_part: int,
    in_part: int,
    schema: pa.Schema,
    batches: List[pa.RecordBatch],
) -> str:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, schema) as writer:
        for b in batches:
            writer.write_batch(b)
    return put_buffer(job_id, stage_id, out_part, in_part, sink.getvalue())


def put_buffer(
    job_id: str, stage_id: int, out_part: int, in_part: int, buf: pa.Buffer
) -> str:
    """Store an already-serialized IPC stream buffer.

    The write-side sink streams batches into its own IPC writer as they
    arrive (optionally compressed) and hands the finished buffer here, so
    the partition is never double-buffered as a Python batch list on top
    of its serialized bytes."""
    import time

    key = (job_id, stage_id, out_part, in_part)
    path = make_path(*key)
    if _spool_dir is not None:
        import os

        f = spool_file(_spool_dir, path)
        tmp = f + ".tmp.%d" % os.getpid()
        with open(tmp, "wb") as fh:
            fh.write(buf)
        os.replace(tmp, f)  # atomic: a retried task never sees half a file
        return path
    with _lock:
        _store[key] = buf
        _job_touched[job_id] = time.monotonic()
    return path


def put_size(path: str) -> int:
    if _spool_dir is not None:
        import os

        f = spool_file(_spool_dir, path)
        if f is not None and os.path.exists(f):
            return os.path.getsize(f)
    key = parse_path(path)
    with _lock:
        buf = _store.get(key) if key else None
    return buf.size if buf is not None else 0


def get_buffer(path: str) -> Optional[pa.Buffer]:
    """The stored partition's raw serialized IPC stream buffer (None on
    miss).  The zero-copy read path: consumers reopen it with
    ``pa.ipc.open_stream`` and every batch is a view over these bytes —
    and the Flight service hands the same buffer to the wire without
    materializing a batch list first."""
    key = parse_path(path)
    if key is None:
        return None
    with _lock:
        return _store.get(key)


def get(path: str) -> Optional[Tuple[pa.Schema, List[pa.RecordBatch]]]:
    buf = get_buffer(path)
    if buf is None:
        return None
    with pa.ipc.open_stream(buf) as reader:
        batches = list(reader)
        return reader.schema, batches


def delete_job(job_id: str) -> int:
    with _lock:
        keys = [k for k in _store if k[0] == job_id]
        for k in keys:
            del _store[k]
        _job_touched.pop(job_id, None)
    return len(keys)


def sweep(ttl_s: float) -> List[str]:
    """Drop jobs idle longer than ttl_s (the janitor's memory analogue of
    the work-dir sweep)."""
    import time

    # monotonic ages: a wall-clock jump must not mass-evict live jobs
    now = time.monotonic()
    with _lock:
        stale = [j for j, t in _job_touched.items() if now - t > ttl_s]
    for j in stale:
        delete_job(j)
    return stale


def job_ids() -> List[str]:
    with _lock:
        return sorted({k[0] for k in _store})


def job_entries(job_id: str) -> List[Tuple[str, pa.Buffer]]:
    """(path, serialized IPC stream buffer) for every stored partition of
    one job — the drain-time replica upload walks these."""
    with _lock:
        return [
            (make_path(*k), buf) for k, buf in _store.items() if k[0] == job_id
        ]


def stored_bytes() -> int:
    with _lock:
        return sum(buf.size for buf in _store.values())


def clear() -> None:
    with _lock:
        _store.clear()
        _job_touched.clear()
