"""In-process memory shuffle store: the TPU-first shuffle data plane.

The reference persists every shuffle partition as an Arrow IPC file and
serves it over Flight (``shuffle_writer.rs:142-292`` →
``flight_service.rs:80-118``).  On a TPU host the data either stays on the
mesh (gang stages exchange via ICI collectives) or — for stage outputs
that must cross a process/host boundary — can be held in RAM and streamed
straight out of the executor's Flight service without touching disk.

Paths use the scheme ``mem://<job>/<stage>/<out_partition>/<in_partition>``
so PartitionLocation / ShuffleWritePartition stats, the scheduler graph,
and fault recovery are completely unchanged: a lost executor loses its
memory partitions exactly like its local files, and ``reset_stages`` rolls
the producing stage back the same way.

Lifetime mirrors the shuffle janitor's job-directory GC: ``delete_job`` is
called wherever job work-dirs are removed.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

SCHEME = "mem://"

_lock = threading.Lock()
# values are compact Arrow IPC stream buffers, NOT RecordBatch lists: a
# stored batch slice would pin its parent batch's entire allocation (and
# overstate stats); serializing compacts to exactly the partition's bytes,
# and readers reopen the buffer zero-copy
_store: Dict[Tuple[str, int, int, int], pa.Buffer] = {}
_job_touched: Dict[str, float] = {}  # job_id -> last put() wall time


def make_path(job_id: str, stage_id: int, out_part: int, in_part: int) -> str:
    return f"{SCHEME}{job_id}/{stage_id}/{out_part}/{in_part}"


def parse_path(path: str) -> Optional[Tuple[str, int, int, int]]:
    if not path.startswith(SCHEME):
        return None
    parts = path[len(SCHEME):].split("/")
    if len(parts) != 4:
        return None
    return parts[0], int(parts[1]), int(parts[2]), int(parts[3])


def put(
    job_id: str,
    stage_id: int,
    out_part: int,
    in_part: int,
    schema: pa.Schema,
    batches: List[pa.RecordBatch],
) -> str:
    import time

    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, schema) as writer:
        for b in batches:
            writer.write_batch(b)
    buf = sink.getvalue()

    key = (job_id, stage_id, out_part, in_part)
    with _lock:
        _store[key] = buf
        _job_touched[job_id] = time.time()
    return make_path(*key)


def put_size(path: str) -> int:
    key = parse_path(path)
    with _lock:
        buf = _store.get(key) if key else None
    return buf.size if buf is not None else 0


def get(path: str) -> Optional[Tuple[pa.Schema, List[pa.RecordBatch]]]:
    key = parse_path(path)
    if key is None:
        return None
    with _lock:
        buf = _store.get(key)
    if buf is None:
        return None
    with pa.ipc.open_stream(buf) as reader:
        batches = list(reader)
        return reader.schema, batches


def delete_job(job_id: str) -> int:
    with _lock:
        keys = [k for k in _store if k[0] == job_id]
        for k in keys:
            del _store[k]
        _job_touched.pop(job_id, None)
    return len(keys)


def sweep(ttl_s: float) -> List[str]:
    """Drop jobs idle longer than ttl_s (the janitor's memory analogue of
    the work-dir sweep)."""
    import time

    now = time.time()
    with _lock:
        stale = [j for j, t in _job_touched.items() if now - t > ttl_s]
    for j in stale:
        delete_job(j)
    return stale


def job_ids() -> List[str]:
    with _lock:
        return sorted({k[0] for k in _store})


def stored_bytes() -> int:
    with _lock:
        return sum(buf.size for buf in _store.values())


def clear() -> None:
    with _lock:
        _store.clear()
        _job_touched.clear()
