"""Shuffle transport selection: a DELIBERATE local-vs-Flight decision.

The original reduce-side read picked its transport by accident: a bare
``os.path.exists(loc.path)`` probe decided "local".  On one host that is
usually right; on a multi-host deployment without a shared filesystem a
coincidentally-existing path silently reads the WRONG file (another
executor's work_dir laid out the same way, a stale previous run) as
shuffle input — a correctness bug, not just a slow path.

This module replaces the probe with executor HOST IDENTITY:

* every executor registers its ``(executor_id, host)`` here at
  construction (``Executor.__init__``) and unregisters at shutdown —
  including the process-isolated task-runner worker, which inherits the
  parent executor's advertised host;
* a location is served locally iff its ``executor_meta`` matches a
  registered local identity: same executor id, or same (normalized)
  host — two executors on one machine share a filesystem, so each can
  mmap the other's partition files directly;
* a process that never hosted an executor (a client collecting results,
  a test harness, a micro-benchmark) has no foreign shuffle inputs to
  alias against, so it keeps the existence-probe fallback.

Local reads go through :func:`read_local_batches` — ``pa.memory_map`` +
IPC file reader, so every yielded batch is a zero-copy view of the page
cache (the Zerrow property end to end: the bytes the map side wrote are
the bytes the reduce side consumes, no serialize→gRPC→deserialize hop
for data that never leaves the host).

``ballista.shuffle.local_transport`` (:class:`fetcher.FetchPolicy`)
selects the mode: ``auto`` (identity-gated, the default) or ``off``
(always Flight — the forced-remote leg of the locality A/B bench).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterator

import pyarrow as pa

# transport verdicts for one location
LOCAL = "local"
FLIGHT = "flight"

_LOOPBACK = {"localhost", "127.0.0.1", "::1", "[::1]"}

_lock = threading.Lock()
# executor_id -> normalized host; multiple executors may share a host
# (standalone clusters register several loopback executors per process)
_local_executors: Dict[str, str] = {}


def normalize_host(host: str) -> str:
    """Hostname normalization for identity matching: case-folded, with
    every loopback spelling collapsed to ``127.0.0.1`` so a location
    advertised as ``localhost`` matches an executor registered as
    ``127.0.0.1`` (they are the same filesystem)."""
    h = (host or "").strip().lower()
    return "127.0.0.1" if h in _LOOPBACK else h


def register_local_executor(executor_id: str, host: str) -> None:
    """Record that ``executor_id`` (advertising ``host``) runs in THIS
    process — its partitions, and any same-host executor's, are local."""
    if not executor_id:
        return
    with _lock:
        _local_executors[executor_id] = normalize_host(host)


def unregister_local_executor(executor_id: str) -> None:
    with _lock:
        _local_executors.pop(executor_id, None)


def clear_local_executors() -> None:
    """Test aid: forget every registered identity."""
    with _lock:
        _local_executors.clear()


def local_identities() -> Dict[str, str]:
    with _lock:
        return dict(_local_executors)


def has_local_identity() -> bool:
    with _lock:
        return bool(_local_executors)


def is_local_location(loc) -> bool:
    """Does ``loc``'s serving executor share this process's machine?
    True on executor-id match (same process / same executor) or on
    normalized-host match (different executor, same machine — shared
    filesystem).  False whenever no identity is registered: the caller
    decides what a bare process may probe."""
    meta = getattr(loc, "executor_meta", None)
    if meta is None:
        return False
    eid = getattr(meta, "id", "") or ""
    host = normalize_host(getattr(meta, "host", "") or "")
    with _lock:
        if eid and eid in _local_executors:
            return True
        return bool(host) and host in _local_executors.values()


def decide(loc, local_transport: str = "auto") -> str:
    """Transport verdict for one file-backed location: :data:`LOCAL` or
    :data:`FLIGHT`.  (mem:// and external-store locations are dispatched
    before this — they have their own stores.)

    ``auto``: local on identity match; a process with NO registered
    executor falls back to the existence probe (see module docstring).
    ``off``: always Flight — the forced-remote A/B leg.
    """
    if local_transport == "off":
        return FLIGHT
    if is_local_location(loc):
        return LOCAL
    if not has_local_identity():
        # bare client/test process: no identity to alias against
        path = getattr(loc, "path", "")
        if path and os.path.exists(path):
            return LOCAL
    return FLIGHT


def read_local_batches(path: str) -> Iterator[pa.RecordBatch]:
    """Zero-copy stream of one local partition file: every batch is a
    view over the memory-mapped file (page cache), not a copy — the
    same serving path the Flight server uses, minus the wire.  Falls
    back to buffered reads on filesystems without mmap.  Raises
    ``FileNotFoundError`` into the retry/replica/recovery machinery when
    the file vanished (janitor sweep, lost with its executor)."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"no such local shuffle partition {path!r}")
    try:
        source = pa.memory_map(path, "rb")
    except Exception:  # pragma: no cover - mmap-less filesystems
        source = pa.OSFile(path, "rb")
    try:
        reader = pa.ipc.open_file(source)
    except BaseException:
        source.close()
        raise
    try:
        for i in range(reader.num_record_batches):
            yield reader.get_batch(i)
    finally:
        source.close()
